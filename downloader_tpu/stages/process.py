"""Process stage: walk the download directory and select convertible media.

Behavioral parity with /root/reference/lib/process.js:

- extension whitelist ``.mp4 .mkv .mov .webm`` (lib/process.js:15-20,70-72)
- a sole top-level directory is always traversed (lib/process.js:40-48)
- MOVIE mode keeps every directory (lib/process.js:53-55)
- paths containing ``/extras`` or ``/commentary`` (case-insensitive) are
  rejected (lib/process.js:59-61)
- directory names containing ``season`` or ``s<digits>`` (case-insensitive)
  are accepted (lib/process.js:64-66)
- anything else is rejected; rejected directories are not descended into
- zero matches raises ``Failed to find any suitable media files``
  (lib/process.js:109-111)
"""

from __future__ import annotations

import asyncio
import os
import re
import time
from typing import List

from .. import schemas
from ..utils.stale import PART_TEMP_STRICT_RE as _PART_TEMP_RE
from .base import Job, StageContext, StageFn

# (reference lib/process.js:15-20)
MEDIA_EXTS = {".mp4", ".mkv", ".mov", ".webm"}

# The torrent client's fast-resume sidecar (torrent/resume.py RESUME_NAME
# — equality pinned by a test) lives at the download root.  It is the
# framework's own artifact, not downloaded content, so the filter must
# not let it defeat the sole-top-level-directory rule below.
_RESUME_SIDECAR = ".dt-resume"
# our own workdir sidecars, invisible to the sole-top-level-directory
# parity check below: the torrent resume state and the staged-artifact
# content manifest (stages/manifest.py) live beside the payload but are
# not payload
_SIDECARS = frozenset({_RESUME_SIDECAR, ".manifest.json"})

# (reference lib/process.js:59-66) — substring matches, like JS regex.test
_SKIP_PATH_RE = re.compile(r"/extras|/commentary", re.IGNORECASE)
_SEASON_RE = re.compile(r"s\d+|season", re.IGNORECASE)


class NoMediaFilesError(Exception):
    """Raised when the walk finds nothing convertible
    (reference lib/process.js:109-111)."""


def _dir_allowed(root: str, dir_path: str, is_movie: bool, logger) -> bool:
    name = os.path.basename(dir_path)

    # Sole top-level directory is always traversed (lib/process.js:40-48).
    # The reference checks the *name* against the root listing, so a nested
    # directory sharing the sole top-level dir's name is also allowed —
    # preserved as-is for parity.
    try:
        if os.path.exists(os.path.join(root, name)):
            entries = [e for e in os.listdir(root) if e not in _SIDECARS]
            if len(entries) == 1 and entries[0] == name:
                logger.info(
                    "directory allowed: only top level directory", path=dir_path
                )
                return True
    except OSError:
        pass

    # In movie mode, assume the best (lib/process.js:53-55).
    if is_movie:
        return True

    # Explicitly skip extras/commentary anywhere in the path
    # (lib/process.js:59-61).
    if _SKIP_PATH_RE.search(dir_path.replace(os.sep, "/")):
        return False

    # Allow season-like directory names (lib/process.js:64-66).
    return bool(_SEASON_RE.search(name))


# what an HLS-style packager emits per segment: MPEG-TS pieces and fMP4
# fragments.  Only MANIFEST jobs widen the filter to them — a stray .ts
# in a torrent payload stays excluded, exactly the parity behavior.
MANIFEST_EXTS = {".ts", ".m4s"}


def stage_exts(config, source_kind: str = "AUTO"):
    """The extension whitelist the stage actually runs with: the parity
    set, plus raw ``.y4m`` when the upscale stage is enabled (shared by
    the barrier stage below and the streaming pipeline's filter), plus
    the segment-container extensions for MANIFEST-ingest jobs."""
    from .upscale import upscale_enabled

    exts = MEDIA_EXTS | {".y4m"} if upscale_enabled(config) else MEDIA_EXTS
    if (source_kind or "AUTO").upper() == "MANIFEST":
        exts = exts | MANIFEST_EXTS
    return exts


def incremental_filter(root: str, media: schemas.Media, logger,
                       exts=MEDIA_EXTS):
    """Per-file media predicate for the streaming pipeline.

    Returns ``allow(path) -> bool`` giving, for any file under ``root``,
    the same verdict :func:`find_media_files` reaches for it — a file is
    kept iff its extension is whitelisted, it is not a transcode temp,
    and every ancestor directory up to ``root`` passes
    :func:`_dir_allowed`.  Directory verdicts are memoized, which is
    only sound while the tree *shape* is stable; every streaming source
    guarantees that before its first event (torrents preallocate the
    full layout, the bucket method pre-creates all directories from the
    materialized listing, HTTP/file sources are a single file at the
    root).  The authoritative post-download walk reconciles any
    divergence regardless.
    """
    is_movie = media.type == schemas.MediaType.Value("MOVIE")
    root = os.path.abspath(root)
    verdicts = {root: True}

    def _ancestors_allowed(dir_path: str) -> bool:
        dir_path = os.path.abspath(dir_path)
        cached = verdicts.get(dir_path)
        if cached is not None:
            return cached
        if not dir_path.startswith(root + os.sep):
            # outside the job workdir: never ours to stage
            verdicts[dir_path] = False
            return False
        allowed = _ancestors_allowed(os.path.dirname(dir_path)) and (
            _dir_allowed(root, dir_path, is_movie, logger)
        )
        verdicts[dir_path] = allowed
        return allowed

    def allow(path: str) -> bool:
        name = os.path.basename(path)
        if _PART_TEMP_RE.search(name):
            return False
        if os.path.splitext(name)[1] not in exts:
            return False
        return _ancestors_allowed(os.path.dirname(path))

    return allow


def find_media_files(root: str, media: schemas.Media, logger,
                     exts=MEDIA_EXTS) -> List[str]:
    """Depth-first walk honoring the filter; returns kept file paths.

    (reference ``findMediaFiles``, lib/process.js:29-99 — klaw walk with a
    filter callback; only files are collected, directories are traversal
    decisions)
    """
    is_movie = media.type == schemas.MediaType.Value("MOVIE")
    files: List[str] = []

    def _walk(dir_path: str) -> None:
        try:
            entries = sorted(os.scandir(dir_path), key=lambda e: e.name)
        except FileNotFoundError:
            raise
        for entry in entries:
            rel = os.path.relpath(entry.path, root)
            if entry.is_dir(follow_symlinks=False):
                if _dir_allowed(root, entry.path, is_movie, logger):
                    logger.info(f"including directory '{rel}'")
                    _walk(entry.path)
                else:
                    logger.warn(f"skipping directory '{rel}'")
            else:
                ext = os.path.splitext(entry.name)[1]
                if _PART_TEMP_RE.search(entry.name):
                    # an in-flight or SIGKILL-orphaned transcode temp
                    # (<dst>.part-<pid>.<seq><ext>) carries a media
                    # extension but is never content — ingesting a
                    # corrupt partial on redelivery is worse than the
                    # reference's behavior, which has no such temps.
                    # Strict two-number form only, so real content like
                    # "Movie.part-2.mkv" is never swallowed (review r5)
                    logger.warn(f"skipping transcode temp '{rel}'")
                elif ext in exts:
                    logger.info(f"including file '{rel}'")
                    files.append(entry.path)
                else:
                    logger.warn(f"skipping file '{rel}'")

    _walk(root)
    return files


async def stage_factory(ctx: StageContext) -> StageFn:
    logger = ctx.logger

    async def process(job: Job):
        # cooperative cancellation: the walk itself is fast local I/O,
        # so one check before it starts is the stage's whole window
        ctx.cancel.raise_if_cancelled()
        # config-gated divergence: with the upscale stage enabled, raw
        # .y4m streams (what a decode front-end emits) count as media
        # too, and MANIFEST-ingest jobs accept segment containers.  The
        # parity default stays the reference's exact whitelist.
        exts = stage_exts(ctx.config,
                          getattr(job, "source_kind", "AUTO"))
        last = job.last_stage
        download_path = last["path"] if isinstance(last, dict) else last.path
        logger.info("processing directory", path=download_path)

        with ctx.tracer.span("stage.process", path=download_path):
            walk_mark = time.monotonic()
            cache_files = getattr(job, "cache_files", None)
            if cache_files is not None:
                # cache-hit serving: the entry already named its files
                # (stages/download.py materialize_hit), so apply the
                # SAME per-file verdict the walk would reach — without
                # the directory re-walk.  Missing paths (clobbered
                # workdir) fall back to the authoritative walk.
                if all(os.path.exists(p) for p in cache_files):
                    allow = incremental_filter(
                        download_path, job.media, logger, exts)
                    found = sorted(p for p in cache_files if allow(p))
                else:
                    found = await asyncio.to_thread(
                        find_media_files, download_path, job.media,
                        logger, exts)
            else:
                found = await asyncio.to_thread(
                    find_media_files, download_path, job.media, logger, exts
                )
            if ctx.record is not None:
                # the media-filter verdicts, on the hop ledger (barrier
                # dispatch; the streaming pipeline bills its own)
                ctx.record.note_hop("filter", 0,
                                    time.monotonic() - walk_mark)

        if len(found) == 0:
            raise NoMediaFilesError("Failed to find any suitable media files")

        logger.info("found media files", count=len(found))
        if ctx.record is not None:
            ctx.record.event("process", files=len(found))
        return {"files": found, "downloadPath": download_path}

    return process
