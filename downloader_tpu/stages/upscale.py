"""Upscale stage: run staged media frames through the TPU super-resolution
model between ``process`` and ``upload``.

The reference pipeline has no compute stage — its downstream "converter"
service does the media transform (/root/reference/lib/main.js:157-167
just hands the job over).  This stage is the config-gated, in-pipeline
version of that converter workload: decoded frames go through the
:class:`~downloader_tpu.compute.pipeline.FrameUpscaler` (bf16 convs on
the MXU, batch sharded over the device mesh) and the upscaled stream
replaces the original in the upload set.

Gating and scope:

- Enabled only when ``config.instance.upscale.enabled`` is true; the
  default pipeline stays byte-for-byte reference-parity
  (download -> process -> upload).
- Only raw Y4M streams are transformed (sniffed by content magic, not
  extension — see :func:`~downloader_tpu.compute.video.sniff_y4m`).
  Compressed containers pass through untouched: decoding them needs a
  codec stack (ffmpeg) that a production deployment would run as a
  decode front-end piping y4m into this stage.
- The engine (params + compiled functions + device mesh) is memoized in
  ``ctx.resources`` so every job in the process shares one compilation
  cache and one copy of the params in HBM.

Stage contract: consumes ``{files, downloadPath}`` from process
(lib/process.js:117-120 shape), returns the same shape with upscaled
paths substituted, so ``upload`` runs unchanged.
"""

from __future__ import annotations

import asyncio
import os
import threading

from .base import Job, StageContext, StageFn

_ENGINE_KEY = "upscale.engine"
_ENGINE_LOCK = threading.Lock()  # _get_engine runs in worker threads


def _engine_config(config):
    """Read ``instance.upscale.*`` with safe defaults."""
    from ..platform.config import cfg_get

    def opt(key, default):
        return cfg_get(config, f"instance.upscale.{key}", default)

    return {
        "scale": int(opt("scale", 2)),
        "features": int(opt("features", 128)),
        "depth": int(opt("depth", 4)),
        "batch": int(opt("batch", 8)),
        "checkpoint": opt("checkpoint", None),
        "use_mesh": bool(opt("use_mesh", True)),
    }


def upscale_enabled(config) -> bool:
    """True when ``instance.upscale.enabled`` is set (app.py gating)."""
    from ..platform.config import cfg_get

    return bool(cfg_get(config, "instance.upscale.enabled", False))


def _get_engine(ctx: StageContext):
    """Build (once per process) the shared FrameUpscaler."""
    with _ENGINE_LOCK:  # concurrent jobs must share one engine/params copy
        engine = ctx.resources.get(_ENGINE_KEY)
        if engine is None:
            from ..compute.models.upscaler import UpscalerConfig
            from ..compute.pipeline import FrameUpscaler

            opts = _engine_config(ctx.config)
            engine = FrameUpscaler(
                config=UpscalerConfig(
                    scale=opts["scale"],
                    features=opts["features"],
                    depth=opts["depth"],
                ),
                batch=opts["batch"],
                checkpoint_dir=opts["checkpoint"],
                use_mesh=opts["use_mesh"],
            )
            ctx.resources[_ENGINE_KEY] = engine
    return engine


async def stage_factory(ctx: StageContext) -> StageFn:
    logger = ctx.logger

    async def upscale(job: Job):
        from ..compute.video import sniff_y4m

        last = job.last_stage
        files = last["files"] if isinstance(last, dict) else last.files
        download_path = (
            last["downloadPath"] if isinstance(last, dict) else last.downloadPath
        )

        out_files = []
        with ctx.tracer.span("stage.upscale", files=len(files)):
            for path in files:
                header = sniff_y4m(path)
                if header is None:
                    logger.info(
                        "passing through non-y4m media", path=os.path.basename(path)
                    )
                    out_files.append(path)
                    continue
                # engine construction does JAX backend init + model init —
                # seconds even when healthy, and a wedged device tunnel
                # hangs PJRT init — so it must not block the event loop
                # any more than the per-file device work below does
                engine = await asyncio.to_thread(_get_engine, ctx)
                stem, ext = os.path.splitext(path)
                dst = f"{stem}.{engine.config.scale}x{ext}"
                logger.info(
                    "upscaling",
                    path=os.path.basename(path),
                    size=f"{header.width}x{header.height}",
                    scale=engine.config.scale,
                )
                try:
                    # the device work holds the GIL only between dispatches;
                    # running in a thread keeps heartbeats/telemetry flowing
                    frames = await asyncio.to_thread(
                        engine.upscale_y4m, path, dst
                    )
                except BaseException:
                    # a partial .y4m output would be picked up as media by
                    # the redelivered job's process walk — remove it
                    try:
                        os.unlink(dst)
                    except OSError:
                        pass
                    raise
                logger.info(
                    "upscaled", path=os.path.basename(dst), frames=frames
                )
                if ctx.metrics is not None and hasattr(
                    ctx.metrics, "frames_upscaled"
                ):
                    ctx.metrics.frames_upscaled.inc(frames)
                out_files.append(dst)

        return {"files": out_files, "downloadPath": download_path}

    return upscale
