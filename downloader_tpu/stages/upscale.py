"""Upscale stage: run staged media frames through the TPU super-resolution
model between ``process`` and ``upload``.

The reference pipeline has no compute stage — its downstream "converter"
service does the media transform (/root/reference/lib/main.js:157-167
just hands the job over).  This stage is the config-gated, in-pipeline
version of that converter workload: decoded frames go through the
:class:`~downloader_tpu.compute.pipeline.FrameUpscaler` (bf16 convs on
the MXU, batch sharded over the device mesh) and the upscaled stream
replaces the original in the upload set.

Gating and scope:

- Enabled only when ``config.instance.upscale.enabled`` is true; the
  default pipeline stays byte-for-byte reference-parity
  (download -> process -> upload).
- Raw Y4M streams (sniffed by content magic, not extension — see
  :func:`~downloader_tpu.compute.video.sniff_y4m`) are transformed
  directly.  Compressed containers (the extensions the process stage
  selects, reference lib/process.js:15-20) go through a config-gated
  decode front-end: ``instance.upscale.decode: true`` pipes
  ``<decoder> -i file -f yuv4mpegpipe -`` (ffmpeg by default) straight
  into the same Y4M path — no intermediate raw file on disk.  The
  decoder binary is feature-detected; absent decoder or disabled flag
  means the container passes through untouched, preserving the
  reference-parity default.
- The mirror-image encode back-end: ``instance.upscale.encode: true``
  pipes the upscaled Y4M stream into ``<encoder> -f yuv4mpegpipe -i -
  … <dst>`` (ffmpeg/libx264 by default, binary and args configurable),
  so compressed containers stay compressed end-to-end — without it a
  2x-upscaled stream staged as raw Y4M is 10-100x the source object
  size (VERDICT r3 "what's missing" #1).  Also feature-detected: an
  absent encoder falls back to raw Y4M output with a warning (the
  upscale itself still runs).  Plumbing: :mod:`..compute.transcode`.
- The engine (params + compiled functions + device mesh) is memoized in
  ``ctx.resources`` so every job in the process shares one compilation
  cache and one copy of the params in HBM.

Stage contract: consumes ``{files, downloadPath}`` from process
(lib/process.js:117-120 shape), returns the same shape with upscaled
paths substituted, so ``upload`` runs unchanged.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import threading

from ..platform import faults
from ..platform.errors import Retrier
from .base import Job, StageContext, StageFn

_ENGINE_KEY = "upscale.engine"
_ENGINE_LOCK = threading.Lock()  # _get_engine runs in worker threads

# containers the decode front-end will attempt — exactly the set the
# process stage selects as media (one source of truth; reference
# lib/process.js:15-20)
from .process import MEDIA_EXTS as _DECODE_EXTS  # noqa: E402


def _engine_config(config):
    """Read ``instance.upscale.*`` with safe defaults."""
    from ..platform.config import cfg_get

    def opt(key, default):
        return cfg_get(config, f"instance.upscale.{key}", default)

    from ..compute.transcode import DEFAULT_ENCODE_ARGS

    return {
        "scale": int(opt("scale", 2)),
        "features": int(opt("features", 128)),
        "depth": int(opt("depth", 4)),
        "batch": int(opt("batch", 8)),
        "checkpoint": opt("checkpoint", None),
        "use_mesh": bool(opt("use_mesh", True)),
        # donation of the input planes is off by default on measurement
        # (compute/pipeline.py: cannot alias the scale^2-larger outputs,
        # and serializes dispatch on async backends)
        "donate": bool(opt("donate", False)),
        "decode": bool(opt("decode", False)),
        "decoder": str(opt("decoder", "ffmpeg")),
        "encode": bool(opt("encode", False)),
        "encoder": str(opt("encoder", "ffmpeg")),
        "encode_args": [str(a) for a in opt("encode_args",
                                            list(DEFAULT_ENCODE_ARGS))],
        "container": str(opt("container", "mkv")).lstrip("."),
    }


def upscale_enabled(config) -> bool:
    """True when ``instance.upscale.enabled`` is set (app.py gating)."""
    from ..platform.config import cfg_get

    return bool(cfg_get(config, "instance.upscale.enabled", False))


def _get_engine(ctx: StageContext):
    """Build (once per process) the shared FrameUpscaler."""
    with _ENGINE_LOCK:  # concurrent jobs must share one engine/params copy
        engine = ctx.resources.get(_ENGINE_KEY)
        if engine is None:
            from ..compute.models.upscaler import UpscalerConfig
            from ..compute.pipeline import FrameUpscaler

            opts = _engine_config(ctx.config)
            engine = FrameUpscaler(
                config=UpscalerConfig(
                    scale=opts["scale"],
                    features=opts["features"],
                    depth=opts["depth"],
                ),
                batch=opts["batch"],
                checkpoint_dir=opts["checkpoint"],
                use_mesh=opts["use_mesh"],
                donate=opts["donate"],
            )
            ctx.resources[_ENGINE_KEY] = engine
    return engine


async def stage_factory(ctx: StageContext) -> StageFn:
    logger = ctx.logger
    opts = _engine_config(ctx.config)
    # chip calls ride the service's shared retry executor + a "compute"
    # circuit breaker of their own (same board as store/publish/http, so
    # a wedged device shows up beside a hard-down backend on /readyz)
    retrier = Retrier.shared(ctx.resources, ctx.config,
                             metrics=ctx.metrics, logger=ctx.logger)

    async def upscale(job: Job):
        from ..compute.transcode import transcode
        from ..compute.video import sniff_y4m

        if ctx.record is not None:
            # upscale jobs are their own SLO class (control/slo.py
            # WORKLOAD_CLASSES): the settle seam feeds the UPSCALE
            # objective alongside the priority class's
            ctx.record.workload = "UPSCALE"

        last = job.last_stage
        files = last["files"] if isinstance(last, dict) else last.files
        download_path = (
            last["downloadPath"] if isinstance(last, dict) else last.downloadPath
        )

        out_files = []
        with ctx.tracer.span("stage.upscale", files=len(files)):
            for path in files:
                header = sniff_y4m(path)
                decoder = None
                if header is None:
                    ext = os.path.splitext(path)[1].lower()
                    if opts["decode"] and ext in _DECODE_EXTS:
                        # graftlint: disable=blocking-call-in-async -- which() is ~10 PATH stats, once per file
                        decoder = shutil.which(opts["decoder"])
                        if decoder is None:
                            logger.warn(
                                "decoder not available; passing through",
                                decoder=opts["decoder"],
                                path=os.path.basename(path),
                            )
                    if decoder is None:
                        logger.info(
                            "passing through non-y4m media",
                            path=os.path.basename(path),
                        )
                        out_files.append(path)
                        continue
                encoder = None
                if opts["encode"]:
                    # graftlint: disable=blocking-call-in-async -- which() is ~10 PATH stats, once per file
                    encoder = shutil.which(opts["encoder"])
                    if encoder is None:
                        # weaker fallback than decode's passthrough: the
                        # upscale still runs, output is raw y4m (the
                        # pre-encode behavior) — staged oversized but valid
                        logger.warn(
                            "encoder not available; writing raw y4m",
                            encoder=opts["encoder"],
                            path=os.path.basename(path),
                        )
                # engine construction does JAX backend init + model init —
                # seconds even when healthy, and a wedged device tunnel
                # hangs PJRT init — so it must not block the event loop
                # any more than the per-file device work below does
                engine = await asyncio.to_thread(_get_engine, ctx)
                stem, ext = os.path.splitext(path)
                # the FULL source name stays in transformed dsts so
                # movie.mkv and movie.mp4 in one job cannot collide on
                # one output.  Direct y4m input without encode keeps its
                # extension (the output is still y4m).
                if encoder is not None:
                    dst = f"{path}.{engine.config.scale}x.{opts['container']}"
                elif decoder is not None:
                    dst = f"{path}.{engine.config.scale}x.y4m"
                else:
                    dst = f"{stem}.{engine.config.scale}x{ext}"
                logger.info(
                    "upscaling",
                    path=os.path.basename(path),
                    size=(f"{header.width}x{header.height}" if header
                          else "compressed"),
                    scale=engine.config.scale,
                    decoded=decoder is not None,
                    encoded=encoder is not None,
                )
                # the device work holds the GIL only between dispatches;
                # running in a thread keeps heartbeats/telemetry flowing.
                # No cleanup here: transcode writes through a temp and
                # renames on success, so on failure dst either doesn't
                # exist or is a COMPLETE output from a prior attempt —
                # which a redelivered job should keep, not delete.
                record = ctx.record

                def _run_transcode(src=path, out=dst, dec=decoder,
                                   enc=encoder):
                    # bind the job's hop ledger to the engine for this
                    # worker thread: the h2d/compute/d2h hops billed
                    # inside the dispatch/fetch path land on THIS job
                    if record is not None and record.hops is not None:
                        with engine.hop_sink.bound(record.note_hop):
                            return transcode(
                                engine, src, out, decoder=dec, encoder=enc,
                                encode_args=opts["encode_args"])
                    return transcode(engine, src, out, decoder=dec,
                                     encoder=enc,
                                     encode_args=opts["encode_args"])

                async def _compute(src=path):
                    if faults.enabled():
                        await faults.fire("compute.upscale",
                                          key=os.path.basename(src))
                    return await asyncio.to_thread(_run_transcode)

                frames = await retrier.run(
                    "compute.upscale", _compute, cancel=ctx.cancel,
                    record=ctx.record, logger=logger)
                logger.info(
                    "upscaled", path=os.path.basename(dst), frames=frames
                )
                if ctx.record is not None:
                    ctx.record.event("upscale", frames=frames,
                                     file=os.path.basename(dst))
                if ctx.metrics is not None and hasattr(
                    ctx.metrics, "frames_upscaled"
                ):
                    ctx.metrics.frames_upscaled.inc(frames)
                # separate guard: the duck-typing contract protects the
                # attributes actually used (an embedder's metrics object
                # may predate these counters)
                if (ctx.metrics is not None
                        and hasattr(ctx.metrics, "transcode_bytes_in")
                        and hasattr(ctx.metrics, "transcode_bytes_out")):
                    ctx.metrics.transcode_bytes_in.inc(
                        os.path.getsize(path))
                    ctx.metrics.transcode_bytes_out.inc(
                        os.path.getsize(dst))
                out_files.append(dst)

        return {"files": out_files, "downloadPath": download_path}

    return upscale
