"""Streaming per-file staging pipeline: download ∥ filter ∥ upload.

The barrier dispatch (orchestrator stage loop) pays
``sum(download, process, upload)`` per job even though ingress and
egress use disjoint network paths, and the upload stage pushes files
one at a time in a serial loop at the very end.  This runner replaces
the stage barrier for the default ``download -> process -> upload``
chain: the download stage announces each durably-complete file into a
:class:`~.base.FileStream` (per-file torrent completion, per-object
bucket completion, HTTP promote time), the media filter runs per event,
and a bounded worker pool (``instance.upload_concurrency``, default 3)
stages files while later files are still downloading — so time-to-staged
trends toward ``max(download, upload)`` instead of the sum.

Invariants preserved from the barrier path:

- the ``done`` marker (the orchestrator's idempotency probe) is written
  only after the **authoritative** post-download walk's every file is
  staged — a crash mid-pipeline leaves staged files but no marker, and
  the redelivery skips them via ``_already_staged``
- per-file resume, egress pacing, metrics, and recorder events are the
  same :class:`~.upload.Uploader` code path the barrier stage drives
- cooperative cancellation unwinds within one file/chunk on every
  worker; the orchestrator's ``token.guard`` is the backstop
- ``NoMediaFilesError`` fires exactly when the authoritative walk finds
  nothing, like the process stage
- the 0-50/50-100 progress bands are recomputed for overlap: the
  download stage's own band (0-50) merges with the staged-file fraction
  (0-50) into one monotone percent
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import List

from .. import schemas
from ..platform.config import cfg_get
# combined RUNNING-stage attribution for the registry/profiler while the
# pipelined dispatch runs (all three logical stages at once); defined in
# platform/obs.py, which cannot import this package (cycle via control)
from ..platform.obs import PIPELINE_STAGE  # graftlint: disable=unused-import -- re-exported for stage consumers
from .base import FileStream, Job, StageContext, get_stage_factory

DEFAULT_UPLOAD_CONCURRENCY = 3


def pipeline_mode(config) -> str:
    """``instance.pipeline`` / ``PIPELINE_MODE``: ``streaming`` (default)
    or ``barrier`` (the exact pre-streaming sequential dispatch).
    Misconfiguration fails loudly, like the rate-limit knobs."""
    mode = os.environ.get("PIPELINE_MODE") or cfg_get(
        config, "instance.pipeline", "streaming"
    )
    if mode not in ("streaming", "barrier"):
        raise ValueError(
            f"instance.pipeline must be 'streaming' or 'barrier', got {mode!r}"
        )
    return mode


def upload_concurrency(config) -> int:
    """``instance.upload_concurrency`` / ``UPLOAD_CONCURRENCY``: size of
    the streaming upload worker pool (default 3)."""
    raw = os.environ.get("UPLOAD_CONCURRENCY") or cfg_get(
        config, "instance.upload_concurrency", DEFAULT_UPLOAD_CONCURRENCY
    )
    try:
        value = int(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"upload_concurrency must be an integer, got {raw!r}"
        ) from None
    if value < 1 or value > 64:
        raise ValueError(f"upload_concurrency must be in [1, 64], got {value}")
    return value


class _MergedProgress:
    """Telemetry facade recomputing the 0-50/50-100 split for overlap.

    In barrier mode the download stage owns 0-50 and the upload stage
    owns 50-100, sequentially.  Overlapped, raw interleaving would emit
    regressions (download 32 after upload pushed the total to 40), so
    this facade merges the two fractions — download percent capped at 50
    plus ``int(50 * staged/total)`` — and emits only monotone increases.
    Status events and other jobs' progress (coalesced cache waiters emit
    for their own ids) pass through untouched.
    """

    def __init__(self, inner, media_id: str):
        self._inner = inner
        self._media_id = media_id
        self._status = schemas.TelemetryStatus.Value("DOWNLOADING")
        self._download = 0
        self._staged = 0
        self._total = 0
        self._last = -1

    async def emit_status(self, media_id: str, status: int) -> None:
        await self._inner.emit_status(media_id, status)

    async def emit_progress(self, media_id: str, status: int,
                            percent: int) -> None:
        if media_id != self._media_id:
            await self._inner.emit_progress(media_id, status, percent)
            return
        self._download = max(self._download, min(int(percent), 50))
        await self._flush(status)

    async def note_staged(self, staged: int, total: int) -> None:
        self._staged = staged
        self._total = max(total, staged)
        await self._flush(self._status)

    async def finish(self) -> None:
        """Everything staged: land exactly on 100."""
        self._download = 50
        self._staged = self._total = max(self._total, 1)
        await self._flush(self._status)

    async def _flush(self, status: int) -> None:
        fraction = (min(self._staged / self._total, 1.0)
                    if self._total else 0.0)
        # the upload band opens in PROPORTION to the download band:
        # mid-download the eventual file count is unknown (total = files
        # seen so far), so an absolute 50 * staged/total would jump to
        # ~100/2 off the first completed file and then freeze until the
        # download band caught up.  Weighting by the download fraction
        # bounds the merged percent at 2x the download band — smooth,
        # monotone, and exactly 100 once everything is staged.
        merged = min(int(self._download * (1.0 + fraction)), 100)
        if merged <= self._last:
            return
        if self._last < 50 <= self._download and merged > 50:
            # download-complete milestone: consumers (and the coalesced
            # cache waiters' re-broadcast contract) key on an exact 50 —
            # when files staged mid-download would let the merged value
            # leap straight past it, emit the milestone first
            self._last = 50
            await self._inner.emit_progress(self._media_id, status, 50)
        self._last = merged
        await self._inner.emit_progress(self._media_id, status, merged)


async def _await_with_failfast(primary: asyncio.Task,
                               others: List[asyncio.Task]):
    """Await ``primary``, but re-raise immediately if any of ``others``
    dies first — a failed upload worker must abort the download instead
    of letting it run to completion for nothing."""
    watched = [task for task in others if not task.done()]
    while True:
        done, _pending = await asyncio.wait(
            {primary, *watched}, return_when=asyncio.FIRST_COMPLETED
        )
        if primary in done:
            return primary.result()
        for task in done:
            if task.cancelled():
                raise asyncio.CancelledError()
            if task.exception() is not None:
                raise task.exception()
        watched = [task for task in watched if not task.done()]
        if not watched:
            return await primary


async def run_streaming_job(ctx: StageContext, media, mirrors=(),
                            source_kind: str = "AUTO") -> None:
    """Run one job through the eager per-file pipeline.

    Raises exactly what the barrier stage loop would: the download
    stage's own errors (``ERRDLSTALL`` code preserved),
    ``NoMediaFilesError``, upload errors, ``JobCancelled`` — the
    orchestrator's failure policy is unchanged.

    ``mirrors``/``source_kind`` are the origin-plane fields from the
    Download message (downloader_tpu/origins/): mirrors ride into the
    download stage's racing fetch, and a MANIFEST source kind both
    selects the playlist-ingest method and widens the media filter to
    segment containers — each live segment announced into the
    FileStream stages through this pipeline while later segments are
    still being produced.
    """
    import dataclasses

    from .download import job_download_dir
    from .process import NoMediaFilesError, find_media_files, \
        incremental_filter, stage_exts
    from .upload import Uploader

    logger = ctx.logger
    record = ctx.record
    media_id = media.id
    workdir = job_download_dir(ctx.config, media_id)
    concurrency = upload_concurrency(ctx.config)

    progress = _MergedProgress(ctx.telemetry, media_id)
    # the download stage emits its 0-50 band through the merged facade;
    # everything else on the context is shared with the orchestrator's
    dl_ctx = dataclasses.replace(ctx, telemetry=progress)
    download_fn = await get_stage_factory("download")(dl_ctx)

    stream = FileStream()
    job = Job(media=media, last_stage={}, file_stream=stream,
              mirrors=tuple(mirrors or ()), source_kind=source_kind)
    uploader = Uploader(ctx)
    exts = stage_exts(ctx.config, source_kind)
    allow = incremental_filter(workdir, media, logger, exts)

    accepted: asyncio.Queue = asyncio.Queue()
    enqueued: set = set()
    staged = [0]
    total_known = [0]

    async def _enqueue(path: str) -> None:
        path = os.path.abspath(path)
        if path in enqueued:
            return
        enqueued.add(path)
        total_known[0] = max(total_known[0], len(enqueued))
        await accepted.put(path)

    async def _pump() -> None:
        """Consume per-file events: filter each incrementally, hand the
        keepers to the upload pool."""
        while (event := await stream.next()) is not None:
            ctx.cancel.raise_if_cancelled()
            name = os.path.basename(event.path)
            if record is not None:
                record.event("file_complete", file=name, bytes=event.size)
            filter_mark = time.monotonic()
            verdict = await asyncio.to_thread(allow, event.path)
            if record is not None:
                record.note_hop("filter", event.size,
                                time.monotonic() - filter_mark)
            if verdict:
                logger.info("pipeline: file complete, queued for upload",
                            file=name)
                await _enqueue(event.path)
            else:
                logger.info("pipeline: file complete, filtered out",
                            file=name)

    async def _worker() -> None:
        while True:
            path = await accepted.get()
            if path is None:
                return
            ctx.cancel.raise_if_cancelled()
            await uploader.upload_file(
                media_id, path,
                digest=job.landed_digests.get(path))
            staged[0] += 1
            await progress.note_staged(staged[0], total_known[0])

    with ctx.tracer.span("stage.pipeline", mediaId=media_id,
                         workers=concurrency):
        await uploader.ensure_bucket()
        download_task = asyncio.create_task(download_fn(job))
        pump_task = asyncio.create_task(_pump())
        workers = [asyncio.create_task(_worker()) for _ in range(concurrency)]
        try:
            result = await _await_with_failfast(
                download_task, [pump_task, *workers]
            )
            download_path = (
                result["path"] if isinstance(result, dict) else workdir
            )
            # ingress is over: retire the live counters so the transfer
            # profiler's stall gate stops watching them — otherwise a
            # CPU-only phase after the download (the authoritative walk,
            # _already_staged hashing of large resumed files) reads as a
            # flat-lined transfer and flags a spurious stall_suspect.
            # "upload" too: the next part that actually moves reinstalls
            # it (note_transfer), so tail-upload stalls are still caught
            # while the hash-between-files gaps stay exempt — the same
            # granularity the barrier upload stage gets from its
            # stage-key check.
            if record is not None:
                record.transferred.pop("download", None)
                record.transferred.pop("upload", None)
            # drain the stream fully before the authoritative walk so no
            # event races the reconciliation below
            await stream.close()
            await _await_with_failfast(pump_task, workers)

            # the post-download walk is the source of truth, exactly like
            # the process stage: it catches files the stream never
            # announced and decides the zero-matches error.  A cache hit
            # materializes a whole workdir at once AND names every file
            # it placed (job.cache_files), so that case serves from the
            # known list through the same per-file verdicts — no re-walk
            walk_mark = time.monotonic()
            cache_files = job.cache_files
            if cache_files is not None and all(
                    os.path.exists(p) for p in cache_files):
                found = sorted(p for p in cache_files if allow(p))
            else:
                found = await asyncio.to_thread(
                    find_media_files, download_path, media, logger, exts
                )
            if record is not None:
                record.note_hop("filter", 0,
                                time.monotonic() - walk_mark)
                record.event("process", files=len(found))
            if len(found) == 0:
                raise NoMediaFilesError(
                    "Failed to find any suitable media files"
                )
            total_known[0] = max(len(found), len(enqueued))
            for path in found:
                await _enqueue(path)
            for _ in workers:
                await accepted.put(None)
            await asyncio.gather(*workers)

            # done marker ONLY after every authoritative file is staged
            # AND the staged set verifies against the content manifest
            # (stages/manifest.py): it is the idempotency probe the
            # whole fleet trusts
            await uploader.verify_staged_set(media_id, found)
            await uploader.write_done_marker(media_id)
            await progress.finish()
            logger.info("pipeline: all files staged",
                        files=len(found), streamed=staged[0])
        finally:
            for task in (download_task, pump_task, *workers):
                task.cancel()
            await asyncio.gather(download_task, pump_task, *workers,
                                 return_exceptions=True)

        await uploader.cleanup_workdir(download_path)
