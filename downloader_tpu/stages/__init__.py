"""Pipeline stages.

Three plugins with a uniform async contract, run strictly sequentially per
job with each stage's return value threaded to the next as
``job.last_stage`` (reference stage order + threading:
/root/reference/lib/main.js:28-32,126-140).
"""

from .base import STAGES, Job, StageContext, get_stage_factory, load_stages, register_stage

__all__ = [
    "STAGES",
    "Job",
    "StageContext",
    "get_stage_factory",
    "load_stages",
    "register_stage",
]
