"""Download stage: protocol-dispatched media fetch.

Behavioral parity with /root/reference/lib/download.js:

- download dir = ``<config.instance.download_path>/<media.id>``, with
  relative paths resolved against the repo root (lib/download.js:234-240)
- protocol chosen by the ``SourceType`` enum name, lowercased
  (lib/download.js:243,256-260); unsupported -> ``Protocol not supported.``
- progress 0 emitted before the fetch and 50 after (lib/download.js:255,272)
- methods:
  * ``torrent`` — magnet/metainfo fetch with the 240 s metadata timeout and
    240 s no-progress stall watchdog raising ``ERRDLSTALL``
    (lib/download.js:43-123); progress maps to 0-50%
  * ``http``   — streaming download; ``.torrent`` URLs chain to the torrent
    method (lib/download.js:134-167)
  * ``file``   — gated by ``ALLOW_FILE_URLS=true``; ``file://`` copy
    (lib/download.js:177-189)
  * ``bucket`` — ``bucket://endpoint,bucket,accessKey,secretKey,subFolder``
    fan-in from another object store (lib/download.js:199-227)
- returns ``{"path": download_path}`` (lib/download.js:273-275)
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import hashlib
import json
import os
import posixpath
import re
import socket
import time
import urllib.parse
import urllib.request
import zlib

import aiohttp

from .. import schemas
from ..platform import faults, vfs
from ..platform.errors import Retrier
from ..store import scrub
from ..store.cache import ContentCache, Singleflight, cache_key
from ..utils.disk import ensure_disk_space as _ensure_disk_space
from ..utils.hashing import md5_file_hex
from ..utils.watchdog import STALL_TIMEOUT_SECONDS, StallWatchdog
from .base import Job, StageContext, StageFn

# Repo root, for resolving relative download paths the way the reference
# resolves against ``path.join(__dirname, '..')`` (lib/download.js:234-240).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Progress telemetry interval (reference: 30 s, lib/download.js:88).
PROGRESS_INTERVAL_SECONDS = 30.0

_CHUNK = 1 << 20  # 1 MiB read chunks for streaming HTTP


def _landed_rel_digests(job, root: str) -> "dict[str, str]":
    """``job.landed_digests`` re-keyed relative to ``root`` (the
    workdir), for the cache manifest: the landing-site digests become
    the entry's scrub/verify ground truth.  Paths outside ``root`` —
    and protocols that never stamp digests (torrent) — just yield
    fewer entries; files without one are not re-verifiable, which is
    exactly the pre-digest behavior."""
    digests = getattr(job, "landed_digests", None) or {}
    root = os.path.abspath(root)
    out = {}
    for path, digest in digests.items():
        if os.path.commonpath([os.path.abspath(path), root]) != root:
            continue
        rel = os.path.relpath(os.path.abspath(path), root)
        out[rel.replace(os.sep, "/")] = digest
    return out


class _LandHasher:
    """Hash-on-land: inline md5 over bytes as the chunked write loop
    lands them — integrity comes free with the copy, no second read
    pass.  Each ``update`` is billed to the ``hash`` hop so the ledger
    shows integrity's true CPU cost even when it rides the write loop
    instead of a separate pass.  ``nbytes`` lets the caller prove the
    hasher saw every byte of the final entity before trusting it (a
    spliced or resumed landing bypasses userspace, so the hasher stays
    short and the promote-time fallback read takes over)."""

    def __init__(self, record=None):
        self._md5 = hashlib.md5()
        self._record = record
        self.nbytes = 0

    def update(self, data) -> None:
        mark = time.monotonic()
        self._md5.update(data)
        if self._record is not None:
            self._record.note_hop("hash", len(data),
                                  time.monotonic() - mark)
        self.nbytes += len(data)

    def hexdigest(self) -> str:
        return self._md5.hexdigest()

# Zero-copy body landing (r5): plain-HTTP bodies with a known length
# splice socket -> pipe -> file entirely in the kernel, skipping both
# userspace copies (socket recv + file write), which profile as ~70% of
# staging CPU per byte.  TLS responses can't splice (decryption happens
# in userspace) and chunked/encoded bodies fall back to the streaming
# loop.  SPLICE_OK gates on the syscall's availability (Linux 2.6.17+,
# Python 3.10+).
SPLICE_OK = hasattr(os, "splice")
_SPLICE_DISABLED_ENV = "HTTP_NO_SPLICE"
# one thread-side select+splice slice per event-loop hop: big enough to
# amortize the to_thread dispatch, small enough to keep watchdog/
# progress/rate-limit feedback flowing
_SPLICE_SLICE = 8 << 20
_SPLICE_PIPE_SIZE = 1 << 20
# grown socket receive buffer for spliced connections: bigger per-splice
# moves amortize the ~200 us/syscall kernel cost (A/B measured ~10-15%
# off the cpu_s_per_gb floor).  An EXPLICIT SO_RCVBUF permanently
# disables TCP receive autotuning and silently clamps at rmem_max, so
# the grow is only safe when the locked window — min(request, rmem_max)
# — is at least what autotuning itself could have reached, which is
# tcp_rmem[2] (independent of rmem_max; default ~6 MB).  Gating on
# rmem_max alone (pre-r6) still shrank the window on hosts with
# rmem_max between 1 MiB and tcp_rmem[2] (advisor r5).
_SPLICE_RCVBUF = 8 << 20


def _read_proc_int(path: str, field: int = 0) -> "int | None":
    try:
        with open(path) as fh:
            return int(fh.read().split()[field])
    except (OSError, ValueError, IndexError):
        return None


@functools.lru_cache(maxsize=1)
def _rcvbuf_grow_ok() -> bool:
    rmem_max = _read_proc_int("/proc/sys/net/core/rmem_max")
    if rmem_max is None:
        return False
    autotune_ceiling = _read_proc_int("/proc/sys/net/ipv4/tcp_rmem", 2)
    if autotune_ceiling is None:
        # can't see the autotuning ceiling: only grow when the locked
        # buffer honors the full request (never a shrink vs any ceiling
        # the kernel default could plausibly reach)
        return rmem_max >= _SPLICE_RCVBUF
    return min(_SPLICE_RCVBUF, rmem_max) >= autotune_ceiling

# Segmented HTTP: entities smaller than this aren't worth the extra
# connections (segment setup costs more than the parallelism returns)
SEG_MIN_SIZE = 8 << 20
# state checkpoint cadence while segments stream (crash-resume fidelity)
SEG_STATE_INTERVAL = 2.0


def _write_all(fd: int, view, pos: "int | None",
               thread_ok: bool = False) -> None:
    """Write a full buffer at ``pos`` (None = the fd's own offset),
    through the VFS shim so disk drills (platform/vfs.py) reach the
    landing loop.  ``thread_ok`` attests the caller is off the event
    loop (latency drills only enact there)."""
    vfs.write_all(fd, view, pos, seam="disk.write", thread_ok=thread_ok)


def _spliceable(resp) -> bool:
    """True when this response's body can land via kernel splice."""
    if not SPLICE_OK or os.environ.get(_SPLICE_DISABLED_ENV):
        return False
    if resp.content_length is None:
        return False  # chunked framing is parsed in userspace
    conn = getattr(resp, "connection", None)
    if conn is None or conn.transport is None:
        return False
    transport = conn.transport
    if transport.get_extra_info("sslcontext") is not None:
        return False  # TLS payload decrypts in userspace
    sock = transport.get_extra_info("socket")
    if sock is None:
        return False
    try:
        sock.fileno()
    except (OSError, ValueError):
        return False
    return True


def _splice_slice_blocking(sock_fd: int, pipe_r: int, pipe_w: int,
                           out_fd: int, want: int, timeout: float,
                           abort_fd: int,
                           out_offset: "int | None" = None) -> int:
    """Move up to ``want`` bytes socket -> pipe -> file in the kernel.

    Runs in a worker thread.  The socket stays nonblocking; readiness
    comes from select, which also watches ``abort_fd`` so the event-loop
    side can interrupt instantly (a cancelled to_thread otherwise leaves
    this thread selecting on fds the caller is about to close — an fd
    recycling hazard).  ``out_offset`` writes at an explicit file
    position (segmented downloads share one fd across concurrent
    segments); None uses — and advances — the fd's own offset.
    Returns bytes moved; 0 means EOF before any byte of this slice.
    """
    import select as select_mod

    # poll, not select: select raises ValueError for any fd >= 1024,
    # and this process also runs swarm peers/DHT/segmented connections
    # (review r5)
    poller = select_mod.poll()
    poller.register(sock_fd, select_mod.POLLIN)
    poller.register(abort_fd, select_mod.POLLIN)
    moved = 0
    while moved < want:
        ready = {fd for fd, _ev in poller.poll(timeout * 1000.0)}
        if abort_fd in ready:
            return moved
        if not ready:
            if moved:
                return moved  # partial progress: caller re-slices
            raise TimeoutError("splice: no socket data within timeout")
        try:
            n = os.splice(sock_fd, pipe_w,
                          min(want - moved, _SPLICE_PIPE_SIZE))
        except BlockingIOError:
            continue  # readiness raced away
        if n == 0:
            return moved  # EOF
        left = n
        while left:
            if out_offset is None:
                left -= os.splice(pipe_r, out_fd, left)
            else:
                got = os.splice(pipe_r, out_fd, left,
                                offset_dst=out_offset + moved + (n - left))
                left -= got
        moved += n
    return moved


class _EntityChangedDuringSegments(Exception):
    """A segment's If-Range missed: the origin entity changed mid-flight.

    ``race_abort`` marks it fatal to a whole racing attempt when the
    PRIMARY origin raises it (origins/racing.py re-raises instead of
    failing over): every already-landed byte was validated against the
    old entity, so no mirror can rescue the attempt.  ``fault_class``
    permanent keeps the per-origin Retrier from re-asking an origin
    that just answered deterministically (a mirror serving a different
    entity fails over instantly; the single-origin segmented path never
    routes this through a retrier, so its restart behavior is
    unchanged).
    """

    race_abort = True
    fault_class = "permanent"


def _is_encoded(headers) -> bool:
    """True when the response body is Content-Encoding-compressed — byte
    ranges and on-disk offsets are only meaningful against identity."""
    return headers.get(
        "Content-Encoding", ""
    ).strip().lower() not in ("", "identity")




def choose_validator(headers) -> "str | None":
    """Pick the entity validator to store beside a partial download.

    If-Range requires a STRONG validator (RFC 7232 §3.2): a weak ETag can
    name byte-different entities, which is exactly what range stitching
    must not tolerate.  Last-Modified is itself weak (1 s granularity);
    RFC 7232 §2.2.2 lets a client treat it as strong only when the origin
    offered no ETag at all AND the date is at least 60 seconds older than
    the response's own Date — outside the window in which clock skew and
    sub-minute regeneration could produce two different entities with the
    same timestamp.  Otherwise: None (restart from byte 0 on redelivery
    rather than risk stitching two entities).
    """
    etag = headers.get("ETag", "")
    if etag.startswith("W/"):
        return None  # weak ETag: origin admits byte-level ambiguity
    if etag:
        return etag
    last_modified = headers.get("Last-Modified")
    if not last_modified:
        return None
    from email.utils import parsedate_to_datetime

    try:
        modified = parsedate_to_datetime(last_modified)
        date = parsedate_to_datetime(headers["Date"])
    except (KeyError, ValueError, TypeError):
        return None
    if (date - modified).total_seconds() >= 60.0:
        return last_modified
    return None


def job_download_dir(config, media_id: str) -> str:
    """The per-job workdir ``<instance.download_path>/<media.id>``, with
    relative paths resolved against the repo root exactly like the stage
    itself resolves them (reference lib/download.js:234-240).  Shared
    with the orchestrator's cancelled-job cleanup so both sides always
    name the same directory."""
    configured = getattr(
        getattr(config, "instance", None), "download_path", "downloading"
    )
    prefix = "" if os.path.isabs(configured) else _REPO_ROOT
    return os.path.join(prefix, configured, media_id)


async def _join_offloaded(fn, *args):
    """Run ``fn(*args)`` on the default executor, JOINING the worker
    before propagating cancellation.  The cancel settle path removes
    the job workdir the moment the delivery settles; a bare
    ``asyncio.to_thread`` abandons its still-running thread on cancel,
    and that thread's writes would race the rmtree (re-creating the
    directories it just deleted — an orphan workdir until the next
    boot's recovery sweep).  A SECOND cancellation during the join
    abandons it, the same double-cancel posture as the torrent drive
    loop's cleanup join."""
    loop = asyncio.get_running_loop()
    fut = loop.run_in_executor(None, functools.partial(fn, *args))
    try:
        return await asyncio.shield(fut)
    except asyncio.CancelledError:
        if not fut.done():
            try:
                await asyncio.wait({fut})
            except asyncio.CancelledError:
                pass
        if fut.done() and not fut.cancelled():
            # the cancel wins, but the worker's own failure (ENOSPC…)
            # must be consumed or asyncio logs "exception was never
            # retrieved" at GC on a routine cancel path
            fut.exception()
        raise


def make_bucket_client(endpoint: str, access_key: str, secret_key: str,
                       ssl: bool = True):
    """Default factory for the ``bucket`` method's ad-hoc client
    (reference builds a MinIO client inline, lib/download.js:210-215)."""
    from ..store.s3 import S3ObjectStore

    # default-https matches the reference's hardcoded `useSSL: true`
    # (lib/download.js:212); an explicit scheme in the endpoint wins
    return S3ObjectStore.from_endpoint(endpoint, access_key, secret_key, ssl=ssl)


def parse_bucket_uri(resource_url: str) -> dict:
    """Parse ``bucket://endpoint,bucket,accessKey,secretKey,subFolder``
    (reference lib/download.js:201-207)."""
    params = resource_url.split(",")
    if len(params) < 5:
        raise ValueError(
            "bucket URI must be bucket://endpoint,bucket,accessKey,secretKey,subFolder"
        )
    return {
        "endpoint": params[0].replace("bucket://", "", 1),
        "bucket": params[1],
        "access_key": params[2],
        "secret_key": params[3],
        "sub_folder": params[4],
    }


async def stage_factory(ctx: StageContext) -> StageFn:
    logger = ctx.logger
    telemetry = ctx.telemetry
    downloading = schemas.TelemetryStatus.Value("DOWNLOADING")
    bucket_client_factory = getattr(ctx, "bucket_client_factory", None) or make_bucket_client
    # cooperative cancellation (control/cancel.py): checked at every
    # chunk/piece loop below so a cancelled job unwinds within one chunk
    cancel = ctx.cancel

    # service-wide ingress cap (bytes/s), shared by every job's transfers
    # regardless of protocol; unset = unlimited (reference behavior).
    # Memoized across jobs via ctx.resources so concurrency can't
    # multiply the cap.
    from ..utils.ratelimit import shared_bucket

    limiter = shared_bucket(ctx.resources, ctx.config, "download_rate_limit")
    # per-tenant ingress quota (control/tenancy.py): when the job's
    # tenant carries a download_rate_limit, it stacks UNDER the service
    # cap (the transfer pays both buckets); no tenant table / no quota =
    # the service limiter unchanged
    from ..control.tenancy import stage_limiter

    limiter = stage_limiter(ctx, "ingress", limiter)

    # dependency fault tolerance (platform/errors.py): origin fetches
    # ride the "http" retry policy (transient network errors/5xx back
    # off in-process instead of burning a broker redelivery — the
    # .partial resume point makes each retry cheaper than the last);
    # shared with the orchestrator via ctx.resources
    retrier = Retrier.shared(ctx.resources, ctx.config,
                             metrics=ctx.metrics, logger=ctx.logger)

    # hash-on-land (zero-copy staging ratchet): when staged-set integrity
    # is on, the content digest is computed AT the landing moment —
    # inline with the chunked write loop, or one hot page-cache read at
    # promote — and carried on ``job.landed_digests`` so upload/manifest
    # never burn a second full read pass per staged file.
    from .manifest import integrity_enabled as _integrity_enabled
    hash_on_land = _integrity_enabled(ctx.config)

    # io_uring spike (zero-copy staging ratchet): opt-in landing of
    # segmented chunks through a kernel submission ring instead of one
    # pwrite syscall each.  The knob turns the probe on, the probe turns
    # the ring on — an older kernel or seccomp-filtered container
    # silently keeps the plain pwrite path.
    from ..platform.config import cfg_get
    use_io_uring = bool(cfg_get(ctx.config, "download.io_uring", False))

    # Parallel ranged HTTP: HTTP_SEGMENTS / instance.http_segments
    # connections per download (default 1 = the reference's single
    # stream).  Misconfiguration fails loudly, like the rate limit.
    raw_segments = os.environ.get("HTTP_SEGMENTS") or getattr(
        ctx.config.instance, "http_segments", 1
    )
    try:
        seg_count = int(raw_segments)
    except (TypeError, ValueError):
        raise ValueError(
            f"http_segments must be an integer, got {raw_segments!r}"
        ) from None
    if seg_count < 1 or seg_count > 64:
        raise ValueError(f"http_segments must be in [1, 64], got {seg_count}")

    async def _announce_file(job: Job, path: str, size=None) -> None:
        """Streaming hand-off: tell the pipeline this file's bytes are
        final (stages/base.py FileStream).  No-op in barrier mode and in
        standalone stage use (``job.file_stream`` is None there).
        getattr, not attribute access: jobs are duck-typed here, like
        ``cache_report`` below."""
        stream = getattr(job, "file_stream", None)
        if stream is not None:
            await stream.emit(path, size)

    # One long-lived DHT node shared by every torrent job the orchestrator
    # runs (webtorrent likewise keeps a single bundled DHT instance for the
    # client's lifetime, lib/download.js:19).  Created lazily on the first
    # torrent download, memoized in the cross-job ``ctx.resources`` dict,
    # closed once via ``ctx.cleanups`` at orchestrator shutdown.
    async def _shared_dht(logger):
        import asyncio

        bootstrap_spec = os.environ.get("DHT_BOOTSTRAP") or getattr(
            ctx.config.instance, "dht_bootstrap", None
        )
        if not bootstrap_spec:
            return None
        lock = ctx.resources.setdefault("dht_lock", asyncio.Lock())
        async with lock:
            if "dht_node" in ctx.resources:
                return ctx.resources["dht_node"]
            from ..torrent.dht import DHTNode, parse_bootstrap

            routers = parse_bootstrap(bootstrap_spec)  # validate BEFORE binding
            # routing-table cache: a restarted service rejoins the DHT from
            # the nodes it knew, not just the public routers
            state_path = os.environ.get("DHT_STATE_PATH") or getattr(
                ctx.config.instance, "dht_state_path", None
            )
            if state_path:
                cached = DHTNode.load_nodes(state_path)
                if cached:
                    logger.info("dht node cache loaded", count=len(cached))
                routers = routers + cached
            node = DHTNode(logger=logger)
            await node.start()
            try:
                found = await node.bootstrap(routers)
            except BaseException:
                await node.close()
                raise
            if found == 0:
                # transient DNS/network failure must not memoize a dead
                # node for the process lifetime — retry on the next job
                logger.warn("dht bootstrap found no routers; will retry")
                await node.close()
                return None
            logger.info("dht bootstrapped", routing_table=found)
            ctx.resources["dht_node"] = node

            async def _shutdown_dht() -> None:
                if state_path:
                    try:
                        saved = node.save_nodes(state_path)
                        logger.info("dht node cache saved", count=saved)
                    except OSError as err:
                        logger.warn("dht node cache save failed",
                                    error=str(err))
                await node.close()

            ctx.cleanups.append(_shutdown_dht)
            return node

    async def torrent(resource_url: str, file_id: str, download_path: str, job: Job):
        try:
            from ..torrent import TorrentClient
        except ImportError as err:
            raise NotImplementedError(
                "torrent downloads need downloader_tpu.torrent"
            ) from err

        logger.info("torrent", url=resource_url[:25] + "...")

        # DHT peer discovery (BEP 5) — the reference's webtorrent bundles
        # bittorrent-dht (lib/download.js:19).  Bootstrap routers come from
        # DHT_BOOTSTRAP=host:port,... or config.instance.dht_bootstrap;
        # unset means tracker-only discovery.
        # MSE/PE mode for outgoing peer connections: TORRENT_CRYPTO env or
        # config.instance.torrent_crypto — prefer (default) | require |
        # plaintext.  Incoming (seed-while-leech) always auto-detects.
        crypto = os.environ.get("TORRENT_CRYPTO") or getattr(
            ctx.config.instance, "torrent_crypto", None
        ) or "prefer"
        # Transport for outgoing dials: TORRENT_TRANSPORT env or
        # config.instance.torrent_transport — auto (default: TCP with a
        # uTP/BEP 29 fallback, webtorrent parity) | tcp | utp.
        transport = os.environ.get("TORRENT_TRANSPORT") or getattr(
            ctx.config.instance, "torrent_transport", None
        ) or "auto"
        # tracker announces ride the "tracker" retry policy: attempts-1
        # quick in-client retries per tracker (concurrent across
        # trackers, so a flaky one never serializes the swarm bootstrap)
        tracker_retries = max(
            retrier.policy("tracker").attempts - 1, 0
        )
        client = TorrentClient(logger=logger, dht=await _shared_dht(logger),
                               rate_limiter=limiter, crypto=crypto,
                               transport=transport,
                               tracker_retries=tracker_retries)

        # seed-while-leech: verified pieces are served back to the swarm
        # during the download; SEED_LINGER/config.instance.seed_linger keeps
        # serving that many seconds after completion so concurrent replicas
        # staging the same torrent don't lose their source.  The reference
        # removes the torrent on done (lib/download.js:110-120), so the
        # parity default is 0.
        raw_linger = os.environ.get("SEED_LINGER") or getattr(
            ctx.config.instance, "seed_linger", 0
        )
        try:
            seed_linger = float(raw_linger)
        except (TypeError, ValueError):
            seed_linger = 0.0
        if seed_linger > 0:
            # reap lingering servers at service shutdown
            if "torrent_clients" not in ctx.resources:
                ctx.resources["torrent_clients"] = []

                async def _close_all() -> None:
                    for c in ctx.resources["torrent_clients"]:
                        await c.close()

                ctx.cleanups.append(_close_all)
            clients = ctx.resources["torrent_clients"]
            # prune clients whose linger expired so the list stays bounded
            # by concurrently-seeding jobs, not total jobs ever run
            clients[:] = [c for c in clients if c.is_seeding]
            clients.append(client)

        last_emitted = [None]

        async def on_progress(fraction: float) -> None:
            # download occupies the 0-50% band; only emit on integer change
            # (reference lib/download.js:80-87)
            percent = int(fraction * 100 / 2)
            if percent != last_emitted[0]:
                last_emitted[0] = percent
                await telemetry.emit_progress(file_id, downloading, percent)
                # coalesced same-content jobs ride this fetch: re-broadcast
                # so each waiter re-emits through its own telemetry
                report = getattr(job, "cache_report", None)
                if report is not None:
                    report(percent)

        stats: dict = {}
        record = ctx.record

        async def _file_done(path: str, entry) -> None:
            # per-file completion out of the client's drive loop: the
            # file's last overlapping piece is verified and on disk
            await _announce_file(job, path, entry.length)

        # origin plane: a torrent job's http(s) mirrors are webseeds by
        # another name (BEP 19) — the swarm treats them as always-on
        # HTTP origins for the same piece-verified content, which is
        # exactly the webseed/HTTP-mirror equivalence
        extra_webseeds = [
            m for m in (getattr(job, "mirrors", ()) or ())
            if isinstance(m, str)
            and m.startswith(("http://", "https://"))
        ]
        await client.download(
            resource_url,
            download_path,
            metadata_timeout=STALL_TIMEOUT_SECONDS,
            stall_timeout=STALL_TIMEOUT_SECONDS,
            progress_interval=PROGRESS_INTERVAL_SECONDS,
            on_progress=on_progress,
            seed_linger=seed_linger,
            stats_out=stats,
            cancel=cancel,
            extra_webseeds=extra_webseeds or None,
            # live verified-byte counter for the transfer profiler's
            # per-job throughput/stall sampling (rides the client's own
            # watchdog feeds)
            progress_sink=(None if record is None else
                           lambda n: record.note_transfer("download",
                                                          int(n))),
            on_file_complete=(None if getattr(job, "file_stream", None)
                              is None else _file_done),
        )
        if ctx.record is not None and stats:
            ctx.record.add_bytes(
                "downloaded",
                stats.get("bytes_from_peers", 0)
                + stats.get("bytes_from_webseeds", 0),
            )
        if ctx.metrics is not None and stats:
            m = ctx.metrics
            m.bytes_downloaded.labels(protocol="torrent-peer").inc(
                stats["bytes_from_peers"]
            )
            m.bytes_downloaded.labels(protocol="torrent-webseed").inc(
                stats["bytes_from_webseeds"]
            )
            # bytes NOT refetched thanks to on-disk pieces + the
            # fast-resume sidecar: resume effectiveness at a glance
            m.bytes_downloaded.labels(protocol="torrent-resumed").inc(
                stats["bytes_resumed"]
            )
            m.torrent_hash_failures.inc(stats["hash_failures"])
            m.torrent_bytes_served.inc(stats["bytes_served"])
        if stats:
            logger.info("torrent complete", **{
                k: v for k, v in stats.items()
            })

    async def http(resource_url: str, file_id: str, download_path: str, job: Job):
        logger.info("http", url=resource_url)
        parsed = urllib.parse.urlparse(resource_url)
        filename = posixpath.basename(parsed.path)

        # .torrent files chain to the torrent downloader
        # (reference lib/download.js:144-155)
        if posixpath.splitext(parsed.path)[1] == ".torrent":
            logger.info("downloading a .torrent, chaining to torrent downloader")
            return await torrent(resource_url, file_id, download_path, job)

        os.makedirs(download_path, exist_ok=True)
        output = os.path.join(download_path, filename)
        # bytes stream into ``<name>.partial`` and are renamed on completion,
        # so ``output`` existing is a completion marker and the partial file
        # is a byte-level resume point across job redeliveries — the
        # reference restarts every HTTP download from zero (SURVEY.md §5).
        # ``<name>.partial.meta`` stores the entity validator (ETag or
        # Last-Modified) the partial bytes came from; resume only happens
        # when one exists, sent as ``If-Range`` so a changed entity comes
        # back as a full 200 instead of being stitched onto stale bytes.
        partial = output + ".partial"
        meta = partial + ".meta"

        # the watchdog's feed taps double as the flight recorder's live
        # transfer counter: the profiler samples it into per-job
        # throughput events (a stalled transfer is visibly flat in
        # GET /v1/jobs/{id}/events minutes before this watchdog fires)
        record = ctx.record
        watchdog = StallWatchdog(
            STALL_TIMEOUT_SECONDS,
            on_feed=(None if record is None
                     else lambda n: record.note_transfer("download", n)),
        )
        # identity: a Content-Encoding-compressed body would be written to
        # disk raw (the session doesn't decompress), and byte-range offsets
        # are only meaningful against the unencoded entity
        base_headers = {"Accept-Encoding": "identity"}

        def _entity_complete(resp, offset: int) -> bool:
            # 416 Content-Range is ``bytes */<total>``
            match = re.fullmatch(
                r"bytes \*/(\d+)", resp.headers.get("Content-Range", "")
            )
            return bool(match) and int(match.group(1)) == offset

        def _content_range(resp) -> "tuple | None":
            # satisfied-range form: ``bytes <start>-<end>/<total>``
            match = re.fullmatch(
                r"bytes (\d+)-(\d+)/(\d+)",
                resp.headers.get("Content-Range", ""),
            )
            return tuple(map(int, match.groups())) if match else None

        def _read_validator() -> str:
            try:
                with open(meta) as fh:
                    return fh.read().strip()
            except OSError:
                return ""

        def _remove_meta() -> None:
            try:
                os.remove(meta)
            except OSError:
                pass

        def _discard_partial() -> None:
            # order matters: the stale bytes must be gone BEFORE any new
            # validator is recorded — a crash between the two must never
            # leave a fresh validator paired with old-entity bytes
            try:
                os.remove(partial)
            except OSError:
                pass
            _remove_meta()

        def _write_validator(resp) -> None:
            validator = choose_validator(resp.headers)
            if validator:
                with open(meta, "w") as fh:
                    fh.write(validator)
            else:
                _remove_meta()

        async def _path_digest(path: str) -> "str | None":
            """md5 of the completed entity at ``path``, for the
            pre-promote recovery sidecar and ``job.landed_digests``.
            Free when the inline hasher provably saw every written
            byte; otherwise one read pass while the landing is still
            page-cache hot, billed to the ``hash`` hop."""
            if not hash_on_land:
                return None
            try:
                size = os.path.getsize(path)
            except OSError:
                return None
            hasher = land_hasher[0]
            if hasher is not None and hasher.nbytes == size:
                return hasher.hexdigest()
            mark = time.monotonic()
            # graftlint: disable=second-pass-read -- the blessed landing-site hash: resumed/spliced/segmented landings have no complete inline hasher, and the torn-tail recovery sidecar must hold the digest BEFORE the rename
            digest = await asyncio.to_thread(md5_file_hex, path)
            if record is not None:
                record.note_hop("hash", size, time.monotonic() - mark)
            return digest

        def _stamp_digest(digest: "str | None") -> None:
            digests = getattr(job, "landed_digests", None)
            if digest is not None and digests is not None:
                digests[os.path.abspath(output)] = digest

        def _note_sidecar(digest: "str | None") -> None:
            if digest is not None:
                scrub.note_landed(download_path,
                                  os.path.basename(output), digest)

        async def _promote() -> None:
            # crash-consistent publish: the entity's digest is first
            # persisted DURABLY to the workdir recovery sidecar
            # (.landed.json), THEN the data rename runs
            # fsync-before-rename through the VFS shim, off the loop
            # (a multi-GB landing's fsync would stall every other
            # job's transfer).  Boot recovery (store/scrub.py
            # verify_landed) re-hashes sidecar-named outputs and
            # demotes any mismatch — the torn-tail crash, where the
            # size still checks out but the tail pages never reached
            # the disk — back to re-fetch instead of serving the hole.
            digest = await _path_digest(partial)
            await asyncio.to_thread(_note_sidecar, digest)
            await asyncio.to_thread(vfs.promote, partial, output,
                                    key=output)
            _remove_meta()
            _stamp_digest(digest)

        def _decoder_for(resp):
            # the session never decompresses (auto_decompress=False) and we
            # ask for identity, but a misbehaving origin/CDN can still send
            # Content-Encoding — decode it rather than staging gzip bytes
            # as media.  MAX_WBITS|32 auto-detects gzip and zlib framing.
            enc = resp.headers.get("Content-Encoding", "").strip().lower()
            if enc in ("", "identity"):
                return None
            if enc in ("gzip", "x-gzip", "deflate"):
                return zlib.decompressobj(zlib.MAX_WBITS | 32)
            raise RuntimeError(f"unsupported Content-Encoding: {enc}")

        fetched = [0]  # cumulative across resume rounds, for the watchdog
        # hash-on-land carrier: the inline hasher (if the chunked write
        # loop ran start-to-finish) survives _fetch's return paths here
        land_hasher: list = [None]

        async def _settle_digest() -> None:
            """Stamp ``job.landed_digests[output]`` for the exit paths
            that never ran ``_promote`` (a validated pre-existing
            output from an earlier attempt), so the upload stage and
            the staged manifest never re-read the file just to hash it
            (the r3-r5 second pass).  Promoting paths stamped the
            digest — and the recovery sidecar — at promote time."""
            if not hash_on_land:
                return
            digests = getattr(job, "landed_digests", None)
            if digests is None:
                return  # job double without the carrier: nobody
                # downstream could consume the digest, don't burn a pass
            if os.path.abspath(output) in digests:
                return  # stamped (and sidecar-noted) at promote time
            try:
                size = os.path.getsize(output)
            except OSError:
                return
            mark = time.monotonic()
            # graftlint: disable=second-pass-read -- the blessed landing-site hash: bytes are hot in cache and this digest retires every later re-read
            digest = await asyncio.to_thread(md5_file_hex, output)
            if record is not None:
                record.note_hop("hash", size, time.monotonic() - mark)
            digests[os.path.abspath(output)] = digest
            await asyncio.to_thread(_note_sidecar, digest)

        def _note_origin_wait(mark: float) -> None:
            # request -> response-headers latency: the origin's
            # time-to-first-byte, billed as its own hop so "slow origin"
            # and "slow copy path" are separable in the ledger
            if record is not None:
                record.note_hop("origin_wait", 0, time.monotonic() - mark)

        async def _splice_body(resp, out_fd, offset=None, limit=None,
                               strict=True, progress=None) -> int:
            """Kernel-path body landing: socket -> pipe -> file, no
            userspace copies (see SPLICE_OK).  ~70% of staging CPU per
            byte was the two memcpys this skips (profiled r5).

            ``offset`` None writes at (and advances) the fd's own
            position; an int uses positioned writes — the segmented
            path shares ONE fd across concurrent segments.  ``limit``
            caps landed bytes (a segment must never write past its
            end; surplus response bytes die with the connection).
            ``strict`` raises on early EOF; the segmented caller
            instead returns short and lets its range loop re-request.
            ``progress`` (racing fetch) is called with each landed
            byte count; returning False stops the transfer early —
            the bytes already landed stay valid, the connection dies
            with the response.  Returns bytes landed."""
            import fcntl

            transport = resp.connection.transport
            # pause, drain aiohttp's buffer, pause AGAIN: draining can
            # re-enable reading behind our back (StreamReader's flow
            # control calls resume_reading when its buffer empties —
            # review r5), and the whole block is await-free so no
            # callback can feed more bytes in between.  After this,
            # every remaining body byte is still in the kernel.
            transport.pause_reading()
            head = resp.content.read_nowait(-1)
            transport.pause_reading()
            # the worker writes through a PRIVATE dup of the output fd,
            # owned (like the pipes) by the cleanup below: a
            # double-cancel can leave the worker inside os.splice after
            # the caller's fd is closed and its NUMBER recycled — with
            # a dup, the write lands in the right file description no
            # matter what the caller closed (review r5).  For
            # offset=None the dup shares the file offset, so
            # positionless writes still advance the caller's handle.
            out_dup = os.dup(out_fd)
            total = 0
            resp_left = resp.content_length - len(head)
            if resp_left < 0 and strict:
                # server closed early AND aiohttp buffered the truncated
                # body past content_length's promise — without this the
                # loop below is skipped (remaining <= 0) and a short
                # total returns silently, unlike the unbuffered path
                # which raises (advisor r5).  Close before raising: body
                # bytes are unaccounted, the connection can't be pooled.
                resp.close()
                os.close(out_dup)
                raise aiohttp.ClientPayloadError(
                    f"response over-delivered: buffered {len(head)} bytes "
                    f"against content-length {resp.content_length}")
            cap = (limit if limit is not None
                   else len(head) + max(resp_left, 0))
            pipe_r, pipe_w = os.pipe()
            abort_r, abort_w = os.pipe()
            cleaned = [False]

            def _cleanup(_fut=None) -> None:
                # idempotent; owns EVERY fd the worker touches plus the
                # response — it must only run once no worker thread can
                # still be inside poll/splice
                if cleaned[0]:
                    return
                cleaned[0] = True
                for fd in (pipe_r, pipe_w, abort_r, abort_w, out_dup):
                    os.close(fd)
                # body bytes were consumed behind aiohttp's parser: this
                # connection must never return to the pool
                resp.close()

            fut = None
            try:
                if head:
                    landed = min(len(head), cap)
                    write_mark = time.monotonic()
                    if offset is None:
                        _write_all(out_dup, memoryview(head)[:cap], None)
                    else:
                        # positioned head writes go to a worker like the
                        # streaming fallback's pwrites: a contended
                        # volume must not stall the event loop (r5)
                        await asyncio.to_thread(
                            _write_all, out_dup, memoryview(head)[:cap],
                            offset, True)
                    if record is not None:
                        record.note_hop("disk_write", landed,
                                        time.monotonic() - write_mark)
                    total = landed
                    fetched[0] += landed
                    watchdog.feed(fetched[0])
                    if limiter is not None:
                        await limiter.consume(landed)
                    if progress is not None and not progress(landed):
                        return total
                remaining = min(cap - total, resp_left)
                sock = transport.get_extra_info("socket")
                sock_fd = sock.fileno()
                if _rcvbuf_grow_ok():
                    try:
                        sock.setsockopt(socket.SOL_SOCKET,
                                        socket.SO_RCVBUF, _SPLICE_RCVBUF)
                    except OSError:
                        pass  # best-effort
                try:
                    fcntl.fcntl(pipe_w, fcntl.F_SETPIPE_SZ,
                                _SPLICE_PIPE_SIZE)
                except OSError:
                    pass  # pipe stays at the kernel default: just slower
                while remaining > 0:
                    cancel.raise_if_cancelled()
                    slice_mark = time.monotonic()
                    fut = asyncio.ensure_future(asyncio.to_thread(
                        _splice_slice_blocking, sock_fd, pipe_r, pipe_w,
                        out_dup, min(remaining, _SPLICE_SLICE),
                        STALL_TIMEOUT_SECONDS, abort_r,
                        None if offset is None else offset + total,
                    ))
                    try:
                        moved = await asyncio.shield(fut)
                    except asyncio.CancelledError:
                        # wake the worker and JOIN it before unwinding —
                        # cleanup closes fds it may still be using
                        os.write(abort_w, b"x")
                        try:
                            await fut
                        # graftlint: disable=swallowed-cancellation -- join guard only: the outer handler re-raises the first CancelledError
                        except BaseException:
                            # a SECOND cancellation can interrupt the
                            # join itself; the deferred-cleanup path in
                            # finally handles that case (review r5)
                            pass
                        raise
                    if record is not None and moved:
                        # one hop for the whole kernel path: socket ->
                        # pipe -> file never touches userspace, so there
                        # is no read/write boundary to attribute across
                        record.note_hop("splice", moved,
                                        time.monotonic() - slice_mark)
                    if moved == 0:
                        if not strict:
                            break  # segment range loop re-requests
                        raise aiohttp.ClientPayloadError(
                            f"connection closed {remaining} bytes early "
                            "during splice")
                    total += moved
                    remaining -= moved
                    fetched[0] += moved
                    watchdog.feed(fetched[0])
                    if limiter is not None:
                        await limiter.consume(moved)
                    if progress is not None and not progress(moved):
                        break
            finally:
                if fut is not None and not fut.done():
                    # join interrupted: the worker may still be in
                    # poll/splice — hand fd/response ownership to its
                    # completion callback instead of closing under it
                    # (fd-recycling corruption hazard)
                    os.write(abort_w, b"x")
                    fut.add_done_callback(_cleanup)
                else:
                    _cleanup()
            return total

        async def _stream_body(resp, mode: str, hasher=None) -> int:
            # the async face of the disk family: windowed ``disk`` rules
            # (latency/ENOSPC/EIO) drill the landing loop here, where a
            # brownout-style sleep is legal — the sync shim below only
            # enacts what a syscall can (drift.py windowed coverage)
            if faults.enabled():
                await faults.fire("disk.land", key=partial)
            total = 0
            decoder = _decoder_for(resp)
            use_splice = decoder is None and _spliceable(resp)
            open_mode = mode
            if use_splice and mode == "ab":
                # O_APPEND files are invalid splice targets (EINVAL);
                # resume instead via an explicit seek to the end
                open_mode = "r+b" if os.path.exists(partial) else "wb"
            # graftlint: disable=blocking-call-in-async -- one open(2); the body I/O below is awaited chunk/splice work
            with open(partial, open_mode, buffering=0) as fh:
                if open_mode == "r+b":
                    fh.seek(0, os.SEEK_END)
                if use_splice:
                    return await _splice_body(resp, fh.fileno())
                # hop ledger: socket_read = waiting on (and draining) the
                # response stream, disk_write = the write call itself.
                # Limiter sleeps are deliberate pacing, not a copy hop,
                # so the read clock restarts after each loop body.
                hop_mark = time.monotonic()
                async for raw in resp.content.iter_any():
                    if record is not None:
                        record.note_hop("socket_read", len(raw),
                                        time.monotonic() - hop_mark)
                    cancel.raise_if_cancelled()
                    if limiter is not None:
                        await limiter.consume(len(raw))
                    # watchdog tracks raw network progress; ``total`` counts
                    # decoded bytes written to disk
                    fetched[0] += len(raw)
                    watchdog.feed(fetched[0])
                    data = decoder.decompress(raw) if decoder else raw
                    if data:
                        write_mark = time.monotonic()
                        vfs.fh_write_all(fh, data, key=partial)
                        if record is not None:
                            record.note_hop("disk_write", len(data),
                                            time.monotonic() - write_mark)
                        if hasher is not None:
                            hasher.update(data)
                        total += len(data)
                    hop_mark = time.monotonic()
                if decoder is not None:
                    tail = decoder.flush()
                    if tail:
                        vfs.fh_write_all(fh, tail, key=partial)
                        if hasher is not None:
                            hasher.update(tail)
                        total += len(tail)
            return total

        # -- segmented (parallel ranged) fast path -------------------------
        seg_partial = output + ".partial-seg"
        seg_state_path = seg_partial + ".state"

        def _discard_segmented() -> None:
            # state FIRST: a crash between the removes must never leave a
            # live checkpoint pointing at a missing/zero-filled data file
            for path in (seg_state_path, seg_partial):
                try:
                    os.remove(path)
                except OSError:
                    pass

        async def _fetch_segmented(session, job: Job) -> "int | None":
            """Download with concurrent ranged connections — ``seg_count``
            lanes against one origin, or (origin plane,
            downloader_tpu/origins/) work-stealing ranges RACED across
            the job's mirror set when ``Download.mirrors`` names
            redundant origins for this entity.

            Returns fetched bytes on success, or None when the entity
            isn't segmentable (no range support, no strong validator,
            encoded body, or too small) — the caller then runs the
            sequential path.  Every segment request carries If-Range, so
            a mid-flight entity change surfaces as a 200 and aborts the
            whole attempt instead of stitching two versions; a MIRROR
            whose probe disagrees with the primary's validator/length is
            excluded up front (it serves a different entity).

            Progress survives crashes: segment positions checkpoint to a
            ``.partial-seg.state`` sidecar every few seconds, and a
            redelivered job resumes each segment from its recorded
            position when the validator still matches — racing and
            single-origin runs share the state format, so either can
            resume the other's partial.
            """
            from ..origins.plan import OriginHealth, build_origin_set

            health = OriginHealth.shared(ctx.resources, ctx.config)
            origins = build_origin_set(
                resource_url, getattr(job, "mirrors", ()) or (),
                health=health,
            )
            probe_headers = {**base_headers, "Range": "bytes=0-0"}

            async def _probe_reference(origin) -> "tuple | None":
                """Probe one origin as the entity REFERENCE: 206 +
                strong validator + identity body, else None.

                With mirrors to fail over to, even the PRIMARY's probe
                is bounded (10 s): a black-holed primary must cost
                seconds before a mirror is promoted, not the 240 s
                watchdog (an explicit ``timeout=None`` would be
                UNBOUNDED in aiohttp — not the session default).  A
                lone origin keeps the session default, the legacy
                behavior."""
                kwargs = {}
                if not origin.primary or len(origins) > 1:
                    kwargs["timeout"] = aiohttp.ClientTimeout(total=10)
                request_mark = time.monotonic()
                async with session.get(
                    origin.url, headers=probe_headers, **kwargs
                ) as probe:
                    _note_origin_wait(request_mark)
                    if probe.status != 206:
                        return None  # no byte-range support
                    crange = _content_range(probe)
                    if crange is None:
                        return None
                    ref_validator = choose_validator(probe.headers)
                    if not ref_validator or _is_encoded(probe.headers):
                        return None
                    await probe.read()
                    return ref_validator, crange[2]

            # the PRIMARY defines the entity; a primary that cannot even
            # answer its probe fails over to the first mirror that can
            # (promoted to reference — the failover promise must cover
            # an origin that died before the job started), while a
            # primary that ANSWERS "not segmentable" keeps the legacy
            # sequential path (its entity stays authoritative).
            reference = None
            try:
                reference = await _probe_reference(origins[0])
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as err:
                if len(origins) == 1:
                    raise
                origins[0].dead = True
                logger.warn("primary origin probe failed; trying "
                            "mirrors", error=str(err)[:200])
                if record is not None:
                    record.event("origin_probe",
                                 origin=origins[0].label, ok=False,
                                 primary=True,
                                 reason=f"probe_failed: {str(err)[:80]}")
                for mirror in origins[1:]:
                    try:
                        reference = await _probe_reference(mirror)
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError) as mirror_err:
                        if record is not None:
                            record.event(
                                "origin_probe", origin=mirror.label,
                                ok=False,
                                reason="probe_failed: "
                                       f"{str(mirror_err)[:80]}",
                            )
                        mirror.dead = True
                        continue
                    if reference is None:
                        mirror.dead = True  # answered, not segmentable
                        continue
                    # this mirror now DEFINES the entity: mid-flight
                    # changes on it abort the attempt like a primary's
                    mirror.primary = True
                    if record is not None:
                        record.event("origin_failover",
                                     origin=origins[0].label,
                                     promoted=mirror.label,
                                     what="reference_probe")
                    break
                if reference is None:
                    raise  # nobody could define the entity
            if reference is None:
                return None
            validator, total_len = reference
            reference_origin = next(o for o in origins if not o.dead)
            if total_len < SEG_MIN_SIZE and len(origins) == 1:
                # small entities aren't worth extra connections — unless
                # mirrors exist: racing's failover must cover small
                # files too, and one range is cheap
                return None
            if record is not None:
                record.event("origin_probe",
                             origin=reference_origin.label, ok=True,
                             primary=True, total=total_len,
                             bps=round(
                                 health.bps(reference_origin.label), 1))

            async def _probe_mirror(origin) -> None:
                """Admit a mirror only when it provably serves the SAME
                entity: 206, equal length, equal strong validator."""
                why = None
                try:
                    request_mark = time.monotonic()
                    async with session.get(
                        origin.url, headers=probe_headers,
                        timeout=aiohttp.ClientTimeout(total=10),
                    ) as resp:
                        _note_origin_wait(request_mark)
                        mirror_range = _content_range(resp)
                        if resp.status != 206 or mirror_range is None:
                            why = "no_range_support"
                        elif mirror_range[2] != total_len:
                            why = "length_mismatch"
                        elif choose_validator(resp.headers) != validator:
                            why = "validator_mismatch"
                        elif _is_encoded(resp.headers):
                            why = "encoded_body"
                        else:
                            await resp.read()
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as err:
                    why = f"probe_failed: {str(err)[:80]}"
                if why is not None:
                    origin.dead = True
                    logger.warn("mirror excluded from racing",
                                origin=origin.label, reason=why)
                if record is not None:
                    record.event("origin_probe", origin=origin.label,
                                 ok=why is None, reason=why,
                                 bps=round(health.bps(origin.label), 1))

            unprobed = [o for o in origins
                        if not o.dead and o is not reference_origin]
            if unprobed:
                await asyncio.gather(*(_probe_mirror(o)
                                       for o in unprobed))
            racing = [o for o in origins if not o.dead]

            # segments are [start, pos, end): pos = next absolute byte
            segments = None
            try:
                # graftlint: disable=blocking-call-in-async -- sidecar checkpoint is a few hundred bytes
                with open(seg_state_path) as fh:
                    state = json.load(fh)  # graftlint: disable=blocking-call-in-async -- same tiny sidecar
                # the checkpoint is only as good as the data file it
                # describes: wrong/missing size means the positions are
                # lies (e.g. the big file was deleted to free disk)
                if (state.get("validator") == validator
                        and state.get("total") == total_len
                        and os.path.getsize(seg_partial) == total_len):
                    segments = [
                        [int(s[0]), int(s[1]), int(s[2])]
                        for s in state["segments"]
                    ]
                    resumed = sum(s[1] - s[0] for s in segments)
                    if resumed:
                        logger.info(
                            "http: resuming segmented download",
                            bytes_resumed=resumed, total=total_len,
                        )
            except (OSError, ValueError, KeyError, TypeError, IndexError):
                pass
            if segments is None:
                # racing wants more, smaller ranges than the per-origin
                # lane count: work-stealing balances load only at range
                # granularity, so ~4 ranges per origin (bounded: >= 2 MiB
                # each, <= 64 total) keeps a slow origin from holding a
                # quarter of the file
                lanes = seg_count
                if len(racing) > 1:
                    lanes = max(seg_count, min(len(racing) * 4, 64))
                span = -(-total_len // lanes)
                if len(racing) > 1:
                    span = max(span, 2 << 20)
                segments = [
                    [lo, lo, min(lo + span, total_len)]
                    for lo in range(0, total_len, span)
                ]
            # preflight AFTER the checkpoint: resumed bytes are credit,
            # or a resumable 80%-done download would fail forever on a
            # volume that can easily hold the remainder
            _ensure_disk_space(
                download_path,
                total_len - sum(s[1] - s[0] for s in segments),
            )
            logger.info(
                "http: segmented download", segments=len(segments),
                total=total_len,
            )

            def _write_state(blob: dict) -> None:
                tmp = seg_state_path + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump(blob, fh)
                os.replace(tmp, seg_state_path)

            # one dedicated writer thread: pwrites and state checkpoints
            # leave the event loop (a contended volume must not stall
            # heartbeats/other jobs), stay ordered (single worker, so a
            # cancelled checkpoint write can never interleave with the
            # final one on the same tmp path), and can be drained to
            # completion before the fd closes — a plain to_thread write
            # cancelled mid-flight would keep running unsupervised
            io_pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
            loop = asyncio.get_running_loop()

            # chunk landing primitive: io_uring ring when the knob AND
            # the probe both say yes, else plain pwrite.  The ring is
            # owned by (and only touched from) the single io_pool
            # writer thread; any ring-side failure falls back to
            # pwrite for that chunk (a real write error — ENOSPC,
            # EBADF — fails identically on both paths and propagates).
            uring_writer = None
            if use_io_uring:
                from ..utils import uring as _uring
                if _uring.available():
                    try:
                        uring_writer = _uring.UringWriter()
                    except (OSError, RuntimeError):
                        uring_writer = None
                if uring_writer is not None:
                    logger.info("http: io_uring chunk landing engaged")
            if uring_writer is not None:
                def _land_chunk(fd, data, off, _w=uring_writer):
                    try:
                        return _w.pwrite(fd, data, off)
                    except (OSError, RuntimeError):
                        # whole-chunk fallback (ring setup/teardown
                        # trouble); per-CQE short/EIO fallback lives
                        # inside UringWriter.pwrite itself
                        vfs.write_all(fd, data, off, thread_ok=True)
                        return len(data)
            else:
                def _land_chunk(fd, data, off):
                    vfs.write_all(fd, data, off, thread_ok=True)
                    return len(data)

            async def _save_state() -> None:
                # snapshot on the loop thread (segment tasks mutate
                # ``seg[1]`` between awaits), write in the worker
                blob = {
                    "validator": validator,
                    "total": total_len,
                    "segments": [list(s) for s in segments],
                }
                await loop.run_in_executor(io_pool, _write_state, blob)

            def _truncate() -> None:
                with open(seg_partial, "ab") as fh:
                    fh.truncate(total_len)

            await loop.run_in_executor(io_pool, _truncate)
            await _save_state()
            fd = os.open(seg_partial, os.O_WRONLY)

            async def _fetch_range(seg, url=resource_url,
                                   guard=None) -> None:
                """Fetch ``[seg[1], seg[2])`` from ``url`` into the
                shared fd at absolute offsets — the single-origin
                segment loop, parameterized so the racing scheduler can
                point it at any origin.  ``guard(delta) -> bool`` (the
                scheduler's merge/first-byte-wins hook) is consulted
                after every landed chunk; False stops the fetch with
                the landed bytes intact."""
                stopped = [False]

                def advance(n: int) -> bool:
                    seg[1] += n
                    if guard is None:
                        return True
                    ok = guard(n)
                    if not ok:
                        stopped[0] = True
                    return ok

                while seg[1] < seg[2] and not stopped[0]:
                    cancel.raise_if_cancelled()
                    if faults.enabled():
                        await faults.fire("origin.fetch", key=url)
                    before = seg[1]
                    headers = {
                        **base_headers,
                        "Range": f"bytes={seg[1]}-{seg[2] - 1}",
                        "If-Range": validator,
                    }
                    request_mark = time.monotonic()
                    async with session.get(
                        url, headers=headers
                    ) as resp:
                        _note_origin_wait(request_mark)
                        if resp.status == 200:
                            raise _EntityChangedDuringSegments()
                        if resp.status != 206:
                            resp.raise_for_status()
                            raise RuntimeError(
                                f"segmented: unexpected {resp.status}"
                            )
                        crange = _content_range(resp)
                        if crange is None or crange[0] != seg[1]:
                            raise RuntimeError(
                                "segmented: mis-ranged 206 "
                                f"{resp.headers.get('Content-Range')!r}"
                            )
                        if (_spliceable(resp)
                                and not _is_encoded(resp.headers)):
                            # kernel landing at the segment's offset;
                            # non-strict: a short/closed 206 just
                            # re-ranges like the streaming loop would.
                            # ``advance`` (not a post-hoc +=) keeps
                            # seg[1] honest while slices land, so the
                            # racing guard sees live progress.
                            await _splice_body(
                                resp, fd, offset=seg[1],
                                limit=seg[2] - seg[1], strict=False,
                                progress=advance)
                        else:
                            hop_mark = time.monotonic()
                            async for raw in resp.content.iter_any():
                                if record is not None:
                                    # per-segment busy time: concurrent
                                    # segments each bill their own wait,
                                    # so the hop sums are busy-seconds,
                                    # not wall (like CPU time)
                                    record.note_hop(
                                        "socket_read", len(raw),
                                        time.monotonic() - hop_mark)
                                cancel.raise_if_cancelled()
                                if limiter is not None:
                                    await limiter.consume(len(raw))
                                fetched[0] += len(raw)
                                watchdog.feed(fetched[0])
                                # never write past our segment: a peer
                                # segment owns the bytes after seg[2]
                                data = raw[:seg[2] - seg[1]]
                                write_mark = time.monotonic()
                                await loop.run_in_executor(
                                    io_pool, _land_chunk, fd, data,
                                    seg[1])
                                if record is not None:
                                    record.note_hop(
                                        "disk_write", len(data),
                                        time.monotonic() - write_mark)
                                if not advance(len(data)):
                                    break
                                if len(data) < len(raw):
                                    break  # server over-delivered; done
                                hop_mark = time.monotonic()
                    if stopped[0]:
                        return  # the scheduler ended this writer's turn
                    if seg[1] == before:
                        # a capped/empty 206 must still advance, else
                        # this loops forever against a broken origin
                        raise RuntimeError(
                            f"segmented: no progress at {seg[1]}"
                        )

            async def _checkpoint() -> None:
                while True:
                    await asyncio.sleep(SEG_STATE_INTERVAL)
                    await _save_state()

            saver = asyncio.create_task(_checkpoint())
            if len(racing) > 1:
                # origin plane: one work-stealing scheduler instead of
                # one task per segment — each origin pulls the next
                # pending range, stragglers get duplicated tails, and a
                # dying origin fails over without failing the job.  The
                # canonical triples are the SAME lists the checkpoint
                # snapshots, so crash-resume is unchanged.
                from ..origins.racing import RangeScheduler

                async def _race_fetch(origin, triple, guard) -> None:
                    await _fetch_range(triple, url=origin.url,
                                       guard=guard)

                scheduler = RangeScheduler(
                    racing, segments, _race_fetch,
                    retrier=retrier, health=health, cancel=cancel,
                    record=record, metrics=ctx.metrics, logger=logger,
                    config=ctx.config,
                )
                tasks = [asyncio.create_task(scheduler.run())]
            else:
                # one surviving origin (usually the primary; after a
                # reference promotion, the mirror that answered)
                tasks = [
                    asyncio.create_task(
                        _fetch_range(s, url=racing[0].url)
                    )
                    for s in segments
                ]
            try:
                await asyncio.gather(*tasks)
            finally:
                try:
                    # gather does NOT cancel siblings when one raises:
                    # every task must be settled BEFORE the fd closes, or
                    # an orphan segment pwrites into a closed (and soon
                    # reused) fd — which would corrupt the sequential
                    # fallback's file
                    for task in tasks:
                        task.cancel()
                    await asyncio.gather(*tasks, return_exceptions=True)
                    # likewise settle the saver so it can't resurrect the
                    # state file after the success path removes it
                    saver.cancel()
                    await asyncio.gather(saver, return_exceptions=True)
                    try:
                        await _save_state()
                    except OSError:
                        pass
                finally:
                    # drain the pool BEFORE the fd closes, even when a
                    # second cancellation interrupts any await above
                    # (this inner finally is the ONLY cleanup guaranteed
                    # to run on that path).  Synchronous on purpose: an
                    # await here could itself be interrupted, leaking
                    # the fd and the thread; pool shutdown also rejects
                    # any still-unsettled task's later submissions, so
                    # nothing can reach a closed fd.  The pending work
                    # is page-cache writes — the brief loop stall is
                    # confined to error teardown.
                    io_pool.shutdown(wait=True)
                    if uring_writer is not None:
                        uring_writer.close()
                    os.close(fd)
            # same crash-consistent publish as the sequential promote:
            # sidecar note durably BEFORE the rename.  Segments land by
            # positioned writes with no inline hasher (and a stale
            # sequential hasher from an earlier attempt must not be
            # trusted here), so the digest is one hot-cache pass.
            land_hasher[0] = None
            digest = await _path_digest(seg_partial)
            await asyncio.to_thread(_note_sidecar, digest)
            await asyncio.to_thread(vfs.promote, seg_partial, output,
                                    key=output)
            _stamp_digest(digest)
            try:
                os.remove(seg_state_path)
            except OSError:
                pass
            return fetched[0]

        async def _existing_output_ok(session) -> bool:
            """Validate a pre-existing completed file against the origin.

            Guards against a truncated ``output`` left by a non-atomic
            writer (older deployments wrote ``output`` directly): compare
            its size to the origin's Content-Length when a HEAD can tell
            us.  Unknowable (HEAD unsupported, no length, encoded body)
            -> trust the file.
            """
            try:
                async with session.head(
                    resource_url, headers=base_headers, allow_redirects=True
                ) as resp:
                    if resp.status != 200:
                        return True
                    if _is_encoded(resp.headers):
                        return True
                    length = resp.headers.get("Content-Length")
                    if length is None:
                        return True
                    return int(length) == os.path.getsize(output)
            except (aiohttp.ClientError, ValueError, OSError):
                return True

        async def _fetch() -> int:
            # large read buffer + iter_any: fewer loop wakeups and no
            # re-chunking copy on the hot path (this stage is the service's
            # bandwidth bottleneck)
            async with aiohttp.ClientSession(
                read_bufsize=_CHUNK, auto_decompress=False,
                trust_env=True,  # honor HTTP(S)_PROXY/NO_PROXY like the
                # reference's request lib (lib/download.js:159)
            ) as session:
                if os.path.exists(output):
                    # a previous attempt finished the download but the job
                    # died before settling (e.g. crash before upload acked)
                    if await _existing_output_ok(session):
                        logger.info(
                            "http: already downloaded, skipping", file=output
                        )
                        return 0
                    logger.warn(
                        "http: existing file fails size check, re-downloading",
                        file=output,
                    )
                    os.remove(output)
                # segmented fast path: when configured — or whenever the
                # job carries racing mirrors (origin plane) — and never
                # while a sequential .partial is mid-resume (finish what
                # the cheaper path started)
                from ..origins.plan import resolve_mirrors

                has_mirrors = bool(resolve_mirrors(
                    resource_url, getattr(job, "mirrors", ()) or ()
                ))
                if ((seg_count > 1 or has_mirrors)
                        and not os.path.exists(partial)):
                    try:
                        got = await _fetch_segmented(session, job)
                    except _EntityChangedDuringSegments:
                        logger.warn(
                            "http: entity changed mid-segments, restarting"
                        )
                        _discard_segmented()
                        got = None
                    if got is not None:
                        return got
                # a server may legally satisfy an open-ended range with a
                # capped 206 (fewer bytes than the remainder), so resuming
                # loops until the entity is complete; every round must
                # advance the offset or the attempt errors out
                while True:
                    cancel.raise_if_cancelled()
                    offset = (
                        os.path.getsize(partial)
                        if os.path.exists(partial)
                        else 0
                    )
                    validator = _read_validator() if offset else ""
                    if not (offset and validator):
                        break  # nothing resumable: full download below
                    headers = {
                        **base_headers,
                        "Range": f"bytes={offset}-",
                        "If-Range": validator,
                    }
                    request_mark = time.monotonic()
                    async with session.get(
                        resource_url, headers=headers
                    ) as resp:
                        _note_origin_wait(request_mark)
                        crange = _content_range(resp)
                        if (
                            resp.status == 206
                            and crange is not None
                            and crange[0] == offset
                            and not _is_encoded(resp.headers)
                        ):
                            start, end, total_len = crange
                            logger.info(
                                "http: resuming partial download",
                                offset=offset,
                                total=total_len,
                            )
                            got = await _stream_body(resp, "ab")
                            # promote on the bytes actually on disk — a
                            # close-delimited 206 can deliver fewer bytes
                            # than its Content-Range advertises without
                            # raising
                            if os.path.getsize(partial) >= total_len:
                                await _promote()
                                return fetched[0]
                            if got <= 0:
                                raise RuntimeError(
                                    "http resume made no progress at "
                                    f"offset {offset}"
                                )
                            continue  # short/capped 206: next range round
                        if resp.status == 200:
                            # entity changed (If-Range miss) or no range
                            # support: body is the full entity, restart on
                            # this response
                            _discard_partial()
                            try:
                                expected = int(
                                    resp.headers.get("Content-Length", 0)
                                )
                            except ValueError:
                                expected = 0
                            _ensure_disk_space(download_path, expected)
                            _write_validator(resp)
                            land_hasher[0] = (
                                _LandHasher(record) if hash_on_land
                                else None)
                            await _stream_body(resp, "wb",
                                               hasher=land_hasher[0])
                            await _promote()
                            return fetched[0]
                        if resp.status == 416:
                            # If-Range was sent, so a 416 means the
                            # validator matched; length == offset proves the
                            # partial is the complete entity
                            if _entity_complete(resp, offset):
                                await _promote()
                                return fetched[0]
                            # oversized/stale partial: clean restart below
                        elif resp.status != 206:
                            resp.raise_for_status()
                        # mis-ranged/unparseable 206 or stale 416: restart
                        break
                _discard_partial()
                request_mark = time.monotonic()
                async with session.get(
                    resource_url, headers=base_headers
                ) as resp:
                    _note_origin_wait(request_mark)
                    resp.raise_for_status()
                    try:
                        expected = int(resp.headers.get("Content-Length", 0))
                    except ValueError:
                        expected = 0
                    _ensure_disk_space(download_path, expected)
                    _write_validator(resp)
                    land_hasher[0] = (
                        _LandHasher(record) if hash_on_land else None)
                    await _stream_body(resp, "wb",
                                       hasher=land_hasher[0])
                    await _promote()
                    return fetched[0]

        async def _attempt() -> int:
            if faults.enabled():
                await faults.fire("http.fetch", key=resource_url)
            return await watchdog.watch(_fetch())

        # transient origin trouble retries in-process under the "http"
        # policy; ``fetched``/the .partial resume point persist across
        # attempts, so a retry continues the transfer instead of
        # restarting it.  A stall (ERRDLSTALL) passes straight through —
        # the orchestrator's drop policy owns it.
        total = await retrier.run("http", _attempt, cancel=cancel,
                                  record=ctx.record, logger=logger)
        if ctx.record is not None:
            ctx.record.add_bytes("downloaded", total)
        if ctx.metrics is not None:
            ctx.metrics.bytes_downloaded.labels(protocol="http").inc(total)
        # promote time: every _fetch exit path leaves the complete entity
        # at ``output`` (fresh promote, resumed promote, or a previous
        # attempt's validated file), so this IS the file's durable moment
        # — digest it while the bytes are hot, then announce
        await _settle_digest()
        await _announce_file(job, output)

    async def manifest(resource_url: str, file_id: str,
                       download_path: str, job: Job):
        """HLS-style segment-manifest ingest (origins/manifest.py):
        ``source_kind: MANIFEST`` jobs treat the http(s) source URI as a
        media playlist, landing each segment as its own durable file —
        announced into the FileStream the moment it completes, so the
        streaming pipeline stages early segments while later ones are
        still being produced (live) or still downloading (VOD).

        Mirrors are playlist-level: each ``Download.mirrors`` URL is
        that origin's copy of the playlist, and relative segment URIs
        resolve against whichever origin serves them (EWMA-ordered,
        first-byte hedge, per-origin breaker/retry seams).  No outer
        watchdog: a live playlist legitimately idles between segments,
        so liveness is the ingest's own ``origins.manifest.stall_timeout``
        (raised as ``ERRDLSTALL`` — the orchestrator's dead-stream
        drop policy, same as a stalled transfer).
        """
        from ..origins.manifest import ManifestIngest
        from ..origins.plan import OriginHealth, build_origin_set

        logger.info("manifest", url=resource_url)
        health = OriginHealth.shared(ctx.resources, ctx.config)
        origins = build_origin_set(
            resource_url, getattr(job, "mirrors", ()) or (),
            health=health,
        )

        async def progress(percent: int) -> None:
            await telemetry.emit_progress(file_id, downloading, percent)

        async def announce(path: str, size: int) -> None:
            await _announce_file(job, path, size)

        async with aiohttp.ClientSession(
            read_bufsize=_CHUNK, auto_decompress=False, trust_env=True,
        ) as session:
            ingest = ManifestIngest(
                origins, session, retrier=retrier, health=health,
                cancel=cancel, record=ctx.record, metrics=ctx.metrics,
                logger=logger, config=ctx.config, limiter=limiter,
                announce=announce, progress=progress,
            )
            total = await ingest.run(resource_url, download_path)
        if ctx.record is not None:
            ctx.record.add_bytes("downloaded", total)
        if ctx.metrics is not None:
            ctx.metrics.bytes_downloaded.labels(
                protocol="manifest").inc(total)
        logger.info("manifest complete", bytes=total)

    async def file(resource_url: str, file_id: str, download_path: str, job: Job):
        # (reference lib/download.js:177-189)
        if os.environ.get("ALLOW_FILE_URLS") != "true":
            raise PermissionError("File URLs are not allowed.")

        qualified = urllib.request.url2pathname(
            urllib.parse.urlparse(resource_url).path
        )
        output = os.path.join(download_path, os.path.basename(qualified))
        logger.debug("file copy", src=qualified, dst=output)
        os.makedirs(download_path, exist_ok=True)
        import shutil

        # off the loop: a file:// source is arbitrarily large media —
        # a synchronous copy would stall every other job's transfer for
        # the whole copy (graftlint blocking-call-in-async)
        await _join_offloaded(shutil.copyfile, qualified, output)
        if ctx.metrics is not None:
            ctx.metrics.bytes_downloaded.labels(protocol="file").inc(
                os.path.getsize(output)
            )
        await _announce_file(job, output)

    async def bucket(resource_url: str, file_id: str, download_path: str, job: Job):
        # (reference lib/download.js:199-227)
        logger.info("bucket", url=resource_url)
        params = parse_bucket_uri(resource_url)
        logger.info("bucket endpoint", endpoint=params["endpoint"])

        client = bucket_client_factory(
            params["endpoint"], params["access_key"], params["secret_key"]
        )
        try:
            sub_folder = params["sub_folder"]
            prefix = sub_folder.rstrip("/") + "/"
            total = 0
            # materialize the listing — and pre-create every local parent
            # directory — BEFORE the first byte moves: the streaming
            # filter's directory verdicts (notably the sole-top-level
            # rule) need the tree shape to be final when the first
            # per-object completion event fires
            items = []
            async for item in client.list_objects(params["bucket"], prefix):
                cancel.raise_if_cancelled()
                if not item.name:
                    continue
                # strip the subFolder prefix from the local path
                # (reference lib/download.js:223); object keys are untrusted
                # remote data, so drop dot segments that would escape
                # download_path (S3 keys may legally contain '..')
                relative = item.name.replace(sub_folder, "", 1)
                parts = [
                    p for p in relative.split("/") if p not in ("", ".", "..")
                ]
                if not parts:
                    continue
                items.append((item, os.path.join(download_path, *parts)))
            def _touch_placeholders() -> None:
                for _item, local in items:
                    os.makedirs(os.path.dirname(local), exist_ok=True)
                    # zero-byte placeholder: the media filter's
                    # sole-top-level rule counts root-level FILES in its
                    # directory listing too, so every local path — not
                    # just the directories — must exist before the first
                    # event or an incremental verdict could diverge from
                    # the authoritative walk's.  fget truncates on
                    # write, and events only fire for fully-fetched
                    # objects, so a placeholder is never read as content.
                    with open(local, "ab"):
                        pass

            # off the loop: a few syscalls per object is real stall time
            # on a 200-object bucket (graftlint blocking-call-in-async)
            await _join_offloaded(_touch_placeholders)
            # live per-chunk transfer counters (ObjectStore.fget_object
            # progress callback): a multi-GB object is then visibly
            # moving in GET /v1/jobs/{id}/events instead of flat until
            # its final byte
            moved = [0]

            async def _on_chunk(n: int) -> None:
                moved[0] += n
                if ctx.record is not None:
                    ctx.record.note_transfer("download", total + moved[0])

            for item, local in items:
                cancel.raise_if_cancelled()
                logger.info("bucket fetch", object=item.name, to=local)
                moved[0] = 0
                fetch_mark = time.monotonic()
                await client.fget_object(params["bucket"], item.name,
                                         local, progress=_on_chunk)
                if ctx.record is not None:
                    # one combined hop: the driver streams socket -> disk
                    # inside fget, so read/write are not separable here
                    ctx.record.note_hop("bucket_fetch", item.size,
                                        time.monotonic() - fetch_mark)
                total += item.size
                if ctx.record is not None:
                    ctx.record.note_transfer("download", total)
                # per-object completion: the fget streamed to completion,
                # so this object's local file is durable
                await _announce_file(job, local, item.size)
            if ctx.record is not None:
                ctx.record.add_bytes("downloaded", total)
            if ctx.metrics is not None:
                ctx.metrics.bytes_downloaded.labels(protocol="bucket").inc(total)
        finally:
            closer = getattr(client, "close", None)
            if closer is not None:
                await closer()

    methods = {"torrent": torrent, "http": http, "file": file, "bucket": bucket}

    # -- content-addressed staging cache + singleflight -----------------
    # Shared across every job via ctx.resources: the orchestrator injects
    # its instance (possibly None = disabled); standalone stage use (tests,
    # one-shot CLI) builds one from config on first touch.  N same-content
    # jobs — concurrent or sequential — pay for at most one download.
    if "content_cache" not in ctx.resources:
        ctx.resources["content_cache"] = ContentCache.from_config(
            ctx.config, logger=logger
        )
    cache: "ContentCache | None" = ctx.resources["content_cache"]
    flights: Singleflight = ctx.resources.setdefault(
        "cache_singleflight", Singleflight()
    )

    def _probe_session() -> aiohttp.ClientSession:
        """One shared keep-alive session for HEAD revalidation probes:
        under fan-in every job probes the same origin, so per-probe
        session/connection setup is pure per-job overhead.  Memoized
        across jobs in ctx.resources; closed at orchestrator shutdown."""
        session = ctx.resources.get("cache_probe_session")
        if session is None or session.closed:
            session = aiohttp.ClientSession(trust_env=True)
            ctx.resources["cache_probe_session"] = session

            async def _close(session=session) -> None:
                await session.close()

            ctx.cleanups.append(_close)
        return session

    async def cache_identity(protocol: str, url: str) -> "str | None":
        """Content key for this source; None = not cacheable.

        - torrent magnets: the infohash IS the content address (and the
          client verifies every piece against it before the fill).
        - http: URL + strong RFC-7232 validator from a HEAD probe
          (``choose_validator``'s strict rules) — no validator means no
          way to prove two fetches returned the same entity, so no
          caching.  ``.torrent`` URLs chain to the torrent method and are
          keyed there only via magnets.
        - bucket: endpoint + bucket + subFolder + the job's credentials
          (hashed): only jobs presenting the same credentials share an
          entry, so a cache hit never hands out bytes the job couldn't
          have fetched itself.  Object stores feeding this pipeline
          publish immutable media, the same assumption the idempotency
          marker already makes.
        - file: local copies are already cheap; never cached.
        """
        if cache is None:
            return None
        if protocol == "torrent" and url.startswith("magnet:"):
            try:
                from ..torrent.magnet import parse_magnet

                return cache_key("torrent", parse_magnet(url).info_hash_hex)
            except ValueError:
                return None
        if protocol == "http":
            parsed = urllib.parse.urlparse(url)
            if posixpath.splitext(parsed.path)[1] == ".torrent":
                return None
            try:
                session = _probe_session()
                async with session.head(
                    url, allow_redirects=True,
                    headers={"Accept-Encoding": "identity"},
                    # a black-holed origin must cost seconds, not the
                    # session's 5-minute default, before the real fetch
                    timeout=aiohttp.ClientTimeout(total=10),
                ) as resp:
                    if resp.status != 200:
                        return None
                    validator = choose_validator(resp.headers)
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return None  # probe trouble never blocks the real fetch
            if not validator:
                return None
            return cache_key("http", url, validator)
        if protocol == "bucket":
            try:
                params = parse_bucket_uri(url)
            except ValueError:
                return None
            # credentials ARE part of the key identity: a job may only
            # hit entries filled under the same credentials, so a job
            # whose keys were revoked (or wrong) can never be served
            # bytes it couldn't fetch itself (key material is hashed,
            # so secrets never appear on disk)
            return cache_key("bucket", params["endpoint"], params["bucket"],
                             params["sub_folder"], params["access_key"],
                             params["secret_key"])
        return None

    async def materialize_hit(key: str, download_path: str, job: Job,
                              *, coalesced: bool) -> bool:
        """Serve the job from the cache; False = miss (or entry lost).

        A hit stamps ``job.cache_files`` with the materialized paths so
        downstream (process stage, streaming reconcile) serves from the
        known list instead of re-walking the workdir, and bills the
        ``cache`` hop with the measured link wall — the hop-budget
        ratchet sees cache serving get cheaper, not vanish.
        """
        entry = await cache.lookup(key)
        if entry is None:
            return False
        with ctx.tracer.span("stage.download.cache", key=key[:16]) as span:
            mark = time.monotonic()
            materialized = await cache.materialize_entry(key, download_path)
            got = materialized[0] if materialized is not None else None
            outcome = ("lost" if got is None
                       else ("coalesced" if coalesced else "hit"))
            span.set_tag("outcome", outcome)
        if ctx.record is not None:
            ctx.record.event("cache", outcome=outcome, key=key[:16],
                             bytes=got or 0)
        if got is None:
            return False  # evicted between lookup and link: treat as miss
        job.cache_files = materialized[1]
        if ctx.record is not None:
            ctx.record.note_hop("cache", got, time.monotonic() - mark)
        if ctx.metrics is not None:
            if not coalesced:
                ctx.metrics.cache_hits.inc()
            ctx.metrics.cache_bytes_saved.inc(got)
        logger.info("download served from staging cache",
                    key=key[:16], bytes=got, coalesced=coalesced)
        return True

    async def cached_download(key: str, method, url: str, file_id: str,
                              download_path: str, job: Job) -> None:
        """Probe -> singleflight -> fetch -> fill, for a cacheable key.

        With a fleet plane attached (fleet/plane.py, via the
        orchestrator's stage_resources) the in-process singleflight
        LEADER additionally coordinates fleet-wide before touching the
        origin: shared-tier probe, then the content lease — losers park
        and materialize the winning worker's publish instead of
        duplicating the download.  Coordination trouble degrades to
        exactly the pre-fleet behavior.
        """
        # warm path: no network at all (acceptance: a warm-cache job
        # never re-fetches — only the HEAD revalidation above ran)
        if await materialize_hit(key, download_path, job, coalesced=False):
            return

        async def origin_fill(report) -> None:
            """Fetch from the origin into the workdir + fill the cache."""
            if ctx.metrics is not None:
                ctx.metrics.cache_misses.inc()
            with ctx.tracer.span("stage.download.cache", key=key[:16]) as span:
                span.set_tag("outcome", "miss")
            if ctx.record is not None:
                ctx.record.event("cache", outcome="miss", key=key[:16])
            job.cache_report = report  # torrent progress feeds waiters
            try:
                report(0)
                await method(url, file_id, download_path, job)
                report(50)
            finally:
                job.cache_report = None
            # fill AFTER the fetch completed (torrent pieces are SHA-1
            # verified by the client; http promoted its .partial only on
            # a complete body) — a failed fetch raises before this, so a
            # partial workdir is never inserted.  A fill failure (disk)
            # must not fail a job that already has its bytes.
            try:
                entry = await cache.insert(
                    key, download_path,
                    digests=_landed_rel_digests(job, download_path))
                if ctx.record is not None:
                    ctx.record.event("cache", outcome="fill", key=key[:16],
                                     bytes=entry.size if entry else 0)
            except OSError as err:
                logger.warn("cache fill failed", error=str(err))
                if ctx.record is not None:
                    ctx.record.event("cache", outcome="fill_failed",
                                     key=key[:16], error=str(err)[:120])

        async def leader_fetch(report) -> None:
            # re-probe under the flight: a previous leader may have
            # filled the key while this job queued for leadership
            if await materialize_hit(key, download_path, job, coalesced=False):
                return
            fleet = ctx.resources.get("fleet_plane")
            if fleet is not None:
                async def _led_fill() -> None:
                    # the fetch every parked waiter is actually waiting
                    # on, as a named span in THIS job's trace — the
                    # lease doc carries our traceparent, so a waiter's
                    # assembled trace (GET /v1/trace) shows this span
                    # under the leader's worker id
                    with ctx.tracer.span("fleet.origin_fetch",
                                         key=key[:16]):
                        await origin_fill(report)

                # the admission-edge routing identity rides the lease
                # doc (fleet/router.py computes the identical hash from
                # the message alone), so every peer's watch-fed lease
                # view can steer same-content deliveries here while
                # this fetch leads
                from ..fleet.router import route_key_for
                outcome = await fleet.coordinate(
                    key, cache, _led_fill,
                    cancel=cancel, record=ctx.record,
                    registry=ctx.resources.get("job_registry"),
                    slot=ctx.slot, logger=logger,
                    route_key=route_key_for(url),
                )
                if outcome == "led":
                    return  # origin_fill ran under our lease
                if outcome == "shared":
                    # a peer worker's bytes landed in the LOCAL cache:
                    # serve this job (and the flight's waiters) from it
                    if await materialize_hit(key, download_path, job,
                                             coalesced=False):
                        return
                    # evicted between fill and link: fetch ourselves
                # "uncoordinated": coordination store unavailable or the
                # wait bound hit — fall through to the lone-worker path
            await origin_fill(report)

        async def waiter_progress(percent: int) -> None:
            await telemetry.emit_progress(file_id, downloading, percent)

        led = await flights.run(key, leader_fetch,
                                on_wait_progress=waiter_progress)
        if not led:
            # coalesced onto another job's fetch; take the bytes from the
            # cache it just filled
            if ctx.metrics is not None:
                ctx.metrics.cache_coalesced.inc()
            if not await materialize_hit(key, download_path, job, coalesced=True):
                # leader succeeded but its fill wasn't usable (nothing
                # cacheable, fill error, instant eviction): fetch alone
                logger.warn("coalesced fetch left no cache entry; "
                            "falling back to own download", key=key[:16])
                if ctx.record is not None:
                    ctx.record.event("cache", outcome="fallback",
                                     key=key[:16])
                await method(url, file_id, download_path, job)

    async def download(job: Job):
        media = job.media
        file_id = media.id
        cancel.raise_if_cancelled()

        download_path = job_download_dir(ctx.config, file_id)

        url = media.source_uri
        protocol = schemas.enum_to_string(schemas.SourceType, media.source)

        try:
            os.makedirs(download_path, exist_ok=True)
            logger.info("created downloadPath", path=download_path)
        except OSError as err:
            logger.error("Failed to create directory", error=str(err))

        logger.info("starting download", protocol=protocol, url=url)

        await telemetry.emit_progress(file_id, downloading, 0)

        method = methods.get(protocol.lower())
        if method is None:
            raise ValueError("Protocol not supported.")
        # origin plane: Download.source_kind steers interpretation of
        # the URI.  MANIFEST rides the http transport but is its own
        # ingest loop; AUTO/DIRECT keep the historical dispatch.
        source_kind = (getattr(job, "source_kind", "AUTO")
                       or "AUTO").upper()
        if source_kind == "MANIFEST":
            if protocol.lower() != "http":
                raise ValueError(
                    "source_kind MANIFEST requires an http(s) source"
                )
            method = manifest

        with ctx.tracer.span("stage.download", protocol=protocol, mediaId=file_id):
            try:
                # live manifests are not immutable content: never cached
                key = (None if source_kind == "MANIFEST"
                       else await cache_identity(protocol.lower(), url))
                if key is None:
                    await method(url, file_id, download_path, job)
                else:
                    await cached_download(
                        key, method, url, file_id, download_path, job
                    )
            except Exception as err:
                logger.error("Download error", error=str(err))
                raise

        logger.info("finished download")
        await telemetry.emit_progress(file_id, downloading, 50)
        return {"path": download_path}

    return download
