"""Download stage: protocol-dispatched media fetch.

Behavioral parity with /root/reference/lib/download.js:

- download dir = ``<config.instance.download_path>/<media.id>``, with
  relative paths resolved against the repo root (lib/download.js:234-240)
- protocol chosen by the ``SourceType`` enum name, lowercased
  (lib/download.js:243,256-260); unsupported -> ``Protocol not supported.``
- progress 0 emitted before the fetch and 50 after (lib/download.js:255,272)
- methods:
  * ``torrent`` — magnet/metainfo fetch with the 240 s metadata timeout and
    240 s no-progress stall watchdog raising ``ERRDLSTALL``
    (lib/download.js:43-123); progress maps to 0-50%
  * ``http``   — streaming download; ``.torrent`` URLs chain to the torrent
    method (lib/download.js:134-167)
  * ``file``   — gated by ``ALLOW_FILE_URLS=true``; ``file://`` copy
    (lib/download.js:177-189)
  * ``bucket`` — ``bucket://endpoint,bucket,accessKey,secretKey,subFolder``
    fan-in from another object store (lib/download.js:199-227)
- returns ``{"path": download_path}`` (lib/download.js:273-275)
"""

from __future__ import annotations

import os
import posixpath
import urllib.parse
import urllib.request

import aiohttp

from .. import schemas
from ..utils.watchdog import STALL_TIMEOUT_SECONDS, StallWatchdog
from .base import Job, StageContext, StageFn

# Repo root, for resolving relative download paths the way the reference
# resolves against ``path.join(__dirname, '..')`` (lib/download.js:234-240).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Progress telemetry interval (reference: 30 s, lib/download.js:88).
PROGRESS_INTERVAL_SECONDS = 30.0

_CHUNK = 1 << 20  # 1 MiB read chunks for streaming HTTP


def make_bucket_client(endpoint: str, access_key: str, secret_key: str,
                       ssl: bool = True):
    """Default factory for the ``bucket`` method's ad-hoc client
    (reference builds a MinIO client inline, lib/download.js:210-215)."""
    from ..store.s3 import S3ObjectStore

    # default-https matches the reference's hardcoded `useSSL: true`
    # (lib/download.js:212); an explicit scheme in the endpoint wins
    return S3ObjectStore.from_endpoint(endpoint, access_key, secret_key, ssl=ssl)


def parse_bucket_uri(resource_url: str) -> dict:
    """Parse ``bucket://endpoint,bucket,accessKey,secretKey,subFolder``
    (reference lib/download.js:201-207)."""
    params = resource_url.split(",")
    if len(params) < 5:
        raise ValueError(
            "bucket URI must be bucket://endpoint,bucket,accessKey,secretKey,subFolder"
        )
    return {
        "endpoint": params[0].replace("bucket://", "", 1),
        "bucket": params[1],
        "access_key": params[2],
        "secret_key": params[3],
        "sub_folder": params[4],
    }


async def stage_factory(ctx: StageContext) -> StageFn:
    logger = ctx.logger
    telemetry = ctx.telemetry
    downloading = schemas.TelemetryStatus.Value("DOWNLOADING")
    bucket_client_factory = getattr(ctx, "bucket_client_factory", None) or make_bucket_client

    async def torrent(resource_url: str, file_id: str, download_path: str, job: Job):
        try:
            from ..torrent import TorrentClient
        except ImportError as err:
            raise NotImplementedError(
                "torrent downloads need downloader_tpu.torrent"
            ) from err

        logger.info("torrent", url=resource_url[:25] + "...")
        client = TorrentClient(logger=logger)

        last_emitted = [None]

        async def on_progress(fraction: float) -> None:
            # download occupies the 0-50% band; only emit on integer change
            # (reference lib/download.js:80-87)
            percent = int(fraction * 100 / 2)
            if percent != last_emitted[0]:
                last_emitted[0] = percent
                await telemetry.emit_progress(file_id, downloading, percent)

        await client.download(
            resource_url,
            download_path,
            metadata_timeout=STALL_TIMEOUT_SECONDS,
            stall_timeout=STALL_TIMEOUT_SECONDS,
            progress_interval=PROGRESS_INTERVAL_SECONDS,
            on_progress=on_progress,
        )

    async def http(resource_url: str, file_id: str, download_path: str, job: Job):
        logger.info("http", url=resource_url)
        parsed = urllib.parse.urlparse(resource_url)
        filename = posixpath.basename(parsed.path)

        # .torrent files chain to the torrent downloader
        # (reference lib/download.js:144-155)
        if posixpath.splitext(parsed.path)[1] == ".torrent":
            logger.info("downloading a .torrent, chaining to torrent downloader")
            return await torrent(resource_url, file_id, download_path, job)

        os.makedirs(download_path, exist_ok=True)
        output = os.path.join(download_path, filename)

        watchdog = StallWatchdog(STALL_TIMEOUT_SECONDS)

        async def _fetch() -> int:
            total = 0
            async with aiohttp.ClientSession() as session:
                async with session.get(resource_url) as resp:
                    resp.raise_for_status()
                    with open(output, "wb") as fh:
                        async for chunk in resp.content.iter_chunked(_CHUNK):
                            fh.write(chunk)
                            total += len(chunk)
                            watchdog.feed(total)
            return total

        total = await watchdog.watch(_fetch())
        if ctx.metrics is not None:
            ctx.metrics.bytes_downloaded.labels(protocol="http").inc(total)

    async def file(resource_url: str, file_id: str, download_path: str, job: Job):
        # (reference lib/download.js:177-189)
        if os.environ.get("ALLOW_FILE_URLS") != "true":
            raise PermissionError("File URLs are not allowed.")

        qualified = urllib.request.url2pathname(
            urllib.parse.urlparse(resource_url).path
        )
        output = os.path.join(download_path, os.path.basename(qualified))
        logger.debug("file copy", src=qualified, dst=output)
        os.makedirs(download_path, exist_ok=True)
        import shutil

        shutil.copyfile(qualified, output)
        if ctx.metrics is not None:
            ctx.metrics.bytes_downloaded.labels(protocol="file").inc(
                os.path.getsize(output)
            )

    async def bucket(resource_url: str, file_id: str, download_path: str, job: Job):
        # (reference lib/download.js:199-227)
        logger.info("bucket", url=resource_url)
        params = parse_bucket_uri(resource_url)
        logger.info("bucket endpoint", endpoint=params["endpoint"])

        client = bucket_client_factory(
            params["endpoint"], params["access_key"], params["secret_key"]
        )
        try:
            sub_folder = params["sub_folder"]
            prefix = sub_folder.rstrip("/") + "/"
            total = 0
            async for item in client.list_objects(params["bucket"], prefix):
                if not item.name:
                    continue
                # strip the subFolder prefix from the local path
                # (reference lib/download.js:223); object keys are untrusted
                # remote data, so drop dot segments that would escape
                # download_path (S3 keys may legally contain '..')
                relative = item.name.replace(sub_folder, "", 1)
                parts = [
                    p for p in relative.split("/") if p not in ("", ".", "..")
                ]
                if not parts:
                    continue
                local = os.path.join(download_path, *parts)
                logger.info("bucket fetch", object=item.name, to=local)
                await client.fget_object(params["bucket"], item.name, local)
                total += item.size
            if ctx.metrics is not None:
                ctx.metrics.bytes_downloaded.labels(protocol="bucket").inc(total)
        finally:
            closer = getattr(client, "close", None)
            if closer is not None:
                await closer()

    methods = {"torrent": torrent, "http": http, "file": file, "bucket": bucket}

    async def download(job: Job):
        media = job.media
        file_id = media.id

        configured = ctx.config.instance.download_path
        prefix = "" if os.path.isabs(configured) else _REPO_ROOT
        download_path = os.path.join(prefix, configured, file_id)

        url = media.source_uri
        protocol = schemas.enum_to_string(schemas.SourceType, media.source)

        try:
            os.makedirs(download_path, exist_ok=True)
            logger.info("created downloadPath", path=download_path)
        except OSError as err:
            logger.error("Failed to create directory", error=str(err))

        logger.info("starting download", protocol=protocol, url=url)

        await telemetry.emit_progress(file_id, downloading, 0)

        method = methods.get(protocol.lower())
        if method is None:
            raise ValueError("Protocol not supported.")

        with ctx.tracer.span("stage.download", protocol=protocol, mediaId=file_id):
            try:
                await method(url, file_id, download_path, job)
            except Exception as err:
                logger.error("Download error", error=str(err))
                raise

        logger.info("finished download")
        await telemetry.emit_progress(file_id, downloading, 50)
        return {"path": download_path}

    return download
