"""Upload stage: push filtered files into the staging object store.

Behavioral parity with /root/reference/lib/upload.js:

- validates ``files`` is a list (lib/upload.js:21-23)
- ensures bucket ``triton-staging`` exists (lib/upload.js:29-31) — now
  memoized per service in the cross-job ``ctx.resources``, so the
  existence round trip is paid once per process, not once per job
- object name = ``<media.id>/original/<base64(basename)>``
  (lib/upload.js:43-44)
- per-file existence check; missing file is an error (lib/upload.js:38-41)
- progress telemetry mapped to 50-100% (lib/upload.js:47-51)
- writes ``<media.id>/original/done`` = ``"true"`` — the idempotency marker
  the orchestrator probes (lib/upload.js:55, lib/main.js:120); fleet-
  coordinated jobs seal with a fenced JSON document instead (see
  :func:`done_marker_body` — existence is still the probe contract)
- best-effort removal of the download directory (lib/upload.js:60-64)

The per-file machinery lives in :class:`Uploader` so the streaming
pipeline (stages/streaming.py) can stage individual files from its
bounded worker pool while the download is still running; the barrier
stage below drives the same object through the reference's serial loop,
so resume (`_already_staged`), pacing, metrics, and recorder events are
one code path in both modes.
"""

from __future__ import annotations

import asyncio
import base64
import inspect
import json
import os
import posixpath
import shutil
import time
from typing import Optional

from .. import schemas
from ..platform import faults
from ..platform.errors import Retrier
from ..utils.hashing import md5_file_hex, multipart_etag_hex
from .base import Job, StageContext, StageFn

STAGING_BUCKET = "triton-staging"
DONE_MARKER = "done"


def object_name(media_id: str, file_path: str) -> str:
    """``<id>/original/<base64(basename)>`` (reference lib/upload.js:43-44)."""
    encoded = base64.b64encode(os.path.basename(file_path).encode("utf-8")).decode("ascii")
    return posixpath.join(media_id, "original", encoded)


def done_marker_name(media_id: str) -> str:
    """``<id>/original/done`` (reference lib/upload.js:55)."""
    return posixpath.join(media_id, "original", DONE_MARKER)


def done_marker_body(fence=None, worker=None) -> bytes:
    """The marker document.  Without a fence context it is the
    reference-parity literal ``b"true"``; a fleet-coordinated job seals
    with a fenced JSON document instead, so a resumed stale leader's
    re-seal is rejectable (every consumer treats marker EXISTENCE as
    "staged" — both shapes satisfy the probe)."""
    if not fence:
        return b"true"
    doc = {"done": True, "fence": int(fence)}
    if worker:
        doc["worker"] = worker
    return json.dumps(doc, sort_keys=True).encode("utf-8")


def parse_done_marker(raw: bytes) -> dict:
    """``{"done": bool, "fence": int}`` from either marker shape
    (legacy ``b"true"`` parses as fence 0 — any fenced writer beats
    it).  Unrecognizable bodies read as not-done, fence 0."""
    if raw == b"true":
        return {"done": True, "fence": 0}
    try:
        doc = json.loads(raw.decode("utf-8"))
        return {"done": bool(doc.get("done")),
                "fence": int(doc.get("fence") or 0)}
    except (ValueError, UnicodeDecodeError, AttributeError, TypeError):
        return {"done": False, "fence": 0}


async def _already_staged(store, name: str, file_path: str, record=None,
                          size=None):
    """The staged object's info when it provably holds this file's
    bytes, else None (truthy/falsy, so it still reads as a predicate).

    Requires both a size match and a content-hash match against the
    backend's etag; a backend that can't report one (empty etag) never
    short-circuits — size equality alone could seal a stale same-size
    object under the done marker.  The probe is best-effort: ANY stat
    failure (not just ObjectNotFound — e.g. write-only credentials where
    HEAD answers 403) means "not staged" so the upload proceeds instead
    of failing a job the plain put path would have handled fine.  On a
    hit the returned ``ObjectInfo`` carries the verified size + etag, so
    the caller's content manifest (stages/manifest.py) records the SAME
    hash the skip decision trusted — no second stat, no re-read.

    Hop-ledger billing lives here because only this function knows
    whether a local re-hash actually ran: the ``hash`` hop gets the
    file's bytes only when md5/multipart-etag computed over them (the
    seconds-per-GB hashing rate the attribution exists for); a probe
    that stopped at stat/size/etag gating bills its wall at ZERO bytes,
    so the common first-upload path can't drag the fleet-wide
    ``hop_seconds_per_gb{hop="hash"}`` rate toward "hashing is free".
    """
    probe_mark = time.monotonic()

    def _bill(hashed_bytes: int) -> None:
        if record is not None:
            record.note_hop("hash", hashed_bytes,
                            time.monotonic() - probe_mark)

    try:
        info = await store.stat_object(STAGING_BUCKET, name)
    except Exception:
        _bill(0)
        return None
    if size is None:
        size = os.path.getsize(file_path)
    if not info.etag or info.size != size:
        _bill(0)
        return None
    if "-" in info.etag:
        # multipart object: its etag is md5-of-part-md5s at the store's
        # part size, which we can recompute locally — without this, every
        # large (multipart) file would re-upload on redelivery, exactly
        # the files resume matters for
        part_size = getattr(store, "multipart_part_size", None)
        if not part_size:
            _bill(0)
            return None
        # graftlint: disable=second-pass-read -- resume probe: a redelivered job has no landed digest (fresh process), so matching the store's multipart etag needs one local pass
        expected = await asyncio.to_thread(
            multipart_etag_hex, file_path, part_size
        )
        _bill(size)
        return info if info.etag == expected else None
    # graftlint: disable=second-pass-read -- resume probe: no landed digest survives a redelivery, one pass decides skip-vs-reupload
    expected = await asyncio.to_thread(md5_file_hex, file_path)
    _bill(size)
    return info if info.etag == expected else None


class Uploader:
    """Per-file staging engine, shared by the barrier stage and the
    streaming pipeline.

    One instance per job context; cross-job state (the egress token
    bucket, the staging-bucket existence memo) lives in the orchestrator's
    shared ``ctx.resources``.
    """

    def __init__(self, ctx: StageContext):
        if ctx.store is None:
            raise ValueError("upload stage requires a StageContext.store")
        self.ctx = ctx
        self.store = ctx.store
        self.logger = ctx.logger
        # service-wide egress cap (bytes/s) to the staging store, the
        # mirror of the download stage's ingress bucket: ONE bucket shared
        # by every job's uploads (memoized in the cross-job ctx.resources),
        # so MinIO egress is cappable per instance
        # (``instance.upload_rate_limit`` / 0 = unlimited, parity default)
        from ..utils.ratelimit import shared_bucket

        self.limiter = shared_bucket(ctx.resources, ctx.config,
                                     "upload_rate_limit")
        # per-tenant egress quota (control/tenancy.py), stacked under
        # the service cap exactly like the download stage's ingress side
        from ..control.tenancy import stage_limiter

        self.limiter = stage_limiter(ctx, "egress", self.limiter)
        # dependency fault tolerance (platform/errors.py): staging-store
        # calls ride the service's shared retry executor + "store"
        # circuit breaker (the orchestrator injects its instance via
        # ctx.resources; standalone stage use builds one from config)
        self.retrier = Retrier.shared(ctx.resources, ctx.config,
                                      metrics=ctx.metrics,
                                      logger=ctx.logger)
        self.uploaded_total = 0
        # staged-artifact integrity (stages/manifest.py): per-job content
        # manifest, loaded lazily on the first upload so a redelivered
        # attempt inherits what its predecessor proved
        from .manifest import integrity_enabled

        self._integrity = integrity_enabled(ctx.config)
        self._manifest = None
        self._manifest_lock = asyncio.Lock()

    async def manifest_for(self, media_id: str):
        """The job's content manifest (None when integrity is off).

        The first call loads a prior attempt's ``.manifest.json`` —
        blocking disk I/O, run off-loop like :meth:`JobManifest.persist`
        for the same reason (a contended or network-backed volume must
        not stall concurrent transfers).  The off-loop load is a real
        suspension point, so the lazy init is locked: without it two
        streaming upload workers can both load, and the loser's
        assignment would discard entries the winner already noted —
        a spurious StagedSetMismatch at seal time."""
        if not self._integrity:
            return None
        if (self._manifest is not None
                and self._manifest.media_id == media_id):
            return self._manifest
        async with self._manifest_lock:
            if self._manifest is None or self._manifest.media_id != media_id:
                from .download import job_download_dir
                from .manifest import JobManifest

                self._manifest = await asyncio.to_thread(
                    JobManifest.load,
                    job_download_dir(self.ctx.config, media_id), media_id,
                )
        return self._manifest

    async def ensure_bucket(self) -> None:
        """Staging-bucket existence, checked once per service.

        The result memoizes in the cross-job ``ctx.resources`` only on
        success, so a transient failure retries on the next job; two jobs
        racing the first check both probe — make_bucket tolerates
        already-exists, so the race is harmless.
        """
        if self.ctx.resources.get("staging_bucket_ready"):
            return

        async def _ensure():
            if faults.enabled():
                await faults.fire("store.bucket", key=STAGING_BUCKET)
            if not await self.store.bucket_exists(STAGING_BUCKET):
                await self.store.make_bucket(STAGING_BUCKET)

        bucket_mark = time.monotonic()
        await self.retrier.run("store.bucket", _ensure,
                               cancel=self.ctx.cancel,
                               record=self.ctx.record, logger=self.logger)
        if self.ctx.record is not None:
            # zero-byte control traffic still bills the upload hop: the
            # ledger's hop seconds should tile the staging wall
            self.ctx.record.note_hop("upload", 0,
                                     time.monotonic() - bucket_mark)
        self.ctx.resources["staging_bucket_ready"] = True

    def _put_supports_progress(self) -> bool:
        """Whether the store's fput_object takes a per-part ``progress``
        callback (store/s3.py does; tests monkeypatch fput freely, so the
        probe runs per call, not at construction)."""
        return self._put_supports("progress")

    def _put_supports(self, parameter: str) -> bool:
        try:
            return parameter in inspect.signature(
                self.store.fput_object
            ).parameters
        except (TypeError, ValueError):
            return False

    async def upload_file(self, media_id: str, file_path: str,
                          *, digest: Optional[str] = None) -> int:
        """Stage one file; returns the bytes uploaded (0 = resume skip).

        Egress pacing is charged per multipart part when the store
        reports upload progress (so a single 10 GiB file cannot burst the
        instance's whole egress budget before the bucket pushes back),
        and after the whole put otherwise.  Either way tokens are charged
        only for bytes that actually moved — no refunds on failure, and
        no up-front charge that a failed put would strand.

        ``digest`` is the file's hash-on-land md5 (Job.landed_digests,
        computed at the download landing moment).  When present it rides
        the put as a ``content_md5`` hint for stores that take one, and —
        for a single-part put, whose store etag IS that md5 — it settles
        the content manifest directly, eliminating the post-put stat
        that on a filesystem store was a full read pass per staged file.
        """
        ctx = self.ctx
        ctx.cancel.raise_if_cancelled()
        basename = os.path.basename(file_path)
        self.logger.info("upload", file=basename)
        if not os.path.exists(file_path):
            self.logger.error("failed to upload file, not found",
                              file=file_path)
            raise FileNotFoundError(f"{file_path} not found.")

        name = object_name(media_id, file_path)
        # size BEFORE the put: consume=True permits the backend to take
        # the path destructively (also the hash hop's byte weight)
        size = os.path.getsize(file_path)
        # file-level resume: a redelivered job (crash/nack before the
        # done marker was written) skips files whose bytes are provably
        # already staged — the reference re-uploads everything from
        # scratch (lib/upload.js:34-52).  The probe bills the ``hash``
        # hop itself: file bytes only when a re-hash actually ran — the
        # "hashing still copies through userspace" slice of ROADMAP
        # item 3's copy floor
        staged = await _already_staged(self.store, name, file_path,
                                       record=ctx.record, size=size)
        if staged is not None:
            self.logger.info("already staged, skipping", file=file_path)
            manifest = await self.manifest_for(media_id)
            if manifest is not None:
                # the skip decision just verified size + content hash:
                # record exactly what it trusted
                manifest.note(name, size=staged.size, etag=staged.etag,
                              file=file_path)
                await asyncio.to_thread(manifest.persist)
            if ctx.record is not None:
                ctx.record.event("upload_done", file=basename, bytes=0,
                                 skipped=True)
            return 0

        if ctx.record is not None:
            ctx.record.event("upload_start", file=basename, bytes=size)
        started = time.monotonic()
        charged = 0

        async def _paced(moved: int) -> None:
            # per-part pacing + live transfer counter: the store calls
            # this after each part (or the single put) lands
            nonlocal charged
            charged += moved
            self.uploaded_total += moved
            if ctx.record is not None:
                ctx.record.note_transfer("upload", self.uploaded_total)
            if self.limiter is not None:
                await self.limiter.consume(moved)

        # consume=True: the file's bytes are final (the download stage
        # only announces durable files; the barrier stage runs last) and
        # the whole download dir is deleted after the job settles
        # (reference lib/upload.js:60-64), so the store may ingest by
        # hardlink instead of a byte copy.  The contract permits
        # aliasing only — the path stays on disk, which the streaming
        # pipeline's post-download walk and the torrent serve path rely
        # on (store/base.py fput_object).
        # hash-on-land hint: stores that take a ``content_md5`` seed
        # their etag/stat path from the digest computed at the landing
        # moment, so nothing downstream re-reads the object to hash it
        extra = ({"content_md5": digest}
                 if digest and self._put_supports("content_md5") else {})

        async def _put():
            if faults.enabled():
                await faults.fire("store.put", key=name)
            if self._put_supports_progress():
                await self.store.fput_object(
                    STAGING_BUCKET, name, file_path, consume=True,
                    progress=_paced, **extra,
                )
            else:
                await self.store.fput_object(
                    STAGING_BUCKET, name, file_path, consume=True,
                    **extra)
                # charge AFTER the successful put: consume() deducts
                # immediately and sleeps off the deficit, pacing the
                # AVERAGE egress rate without hooks inside the store
                # client's transfer loop.  Charging up front would strand
                # service-wide tokens for bytes that never moved whenever
                # a job is cancelled or the put fails mid-wait — debt
                # every OTHER job would then sleep off.
                await _paced(size)

        # transient store failures retry in-process (tokens were only
        # charged for bytes that actually moved, so a retried part is
        # paced again like any other bytes); the store breaker opens on
        # a hard-down backend and parks intake at the orchestrator
        upload_mark = time.monotonic()
        await self.retrier.run("store.put", _put, cancel=ctx.cancel,
                               record=ctx.record, logger=self.logger)
        manifest = await self.manifest_for(media_id)
        if manifest is not None:
            threshold = getattr(self.store, "multipart_threshold", None)
            if digest and (threshold is None or size <= threshold):
                # hash-on-land settles the manifest directly: a
                # single-part object's store etag IS the content md5 the
                # download stage computed while the bytes were hot, so
                # there is nothing left to round-trip (and on a
                # filesystem store, nothing left to re-read)
                manifest.note(name, size=size, etag=digest,
                              file=file_path)
            else:
                # capture the store-computed content hash of what just
                # landed (one metadata round trip; the file itself is
                # never re-read) — the pre-seal verification compares
                # against THIS
                try:
                    info = await self.store.stat_object(STAGING_BUCKET,
                                                        name)
                    manifest.note(name, size=info.size, etag=info.etag,
                                  file=file_path)
                except Exception as err:
                    # integrity is defense-in-depth: an unstattable
                    # backend degrades the verify for this file, never
                    # the upload
                    self.logger.warn("manifest stat after upload failed",
                                     file=basename, error=str(err))
                    manifest.note(name, size=size, etag="",
                                  file=file_path)
            await asyncio.to_thread(manifest.persist)
        if ctx.record is not None:
            # the put + manifest seal, as one egress hop (pacing sleeps
            # inside the limiter are part of the hop here: egress wall
            # is what the attribution answers for uploads)
            ctx.record.note_hop("upload", size,
                                time.monotonic() - upload_mark)
            ctx.record.add_bytes("uploaded", size)
            ctx.record.event(
                "upload_done", file=basename, bytes=size,
                seconds=round(time.monotonic() - started, 3),
            )
        if ctx.metrics is not None:
            ctx.metrics.bytes_uploaded.inc(size)
        return size

    async def verify_staged_set(self, media_id: str, files) -> None:
        """Manifest-vs-staged verification, run BEFORE the done marker.

        Re-stats every authoritative file's object against the per-job
        content manifest (size + store content hash recorded as each
        file landed).  Any divergence raises
        :class:`~.manifest.StagedSetMismatch` (transient: the
        redelivery re-stages), so a torn crash mid-upload can never
        seal a short or corrupt staging set under the marker the whole
        fleet trusts.  No-op when ``integrity.enabled`` is off.
        """
        manifest = await self.manifest_for(media_id)
        if manifest is None or not files:
            return
        from .manifest import StagedSetMismatch

        verify_mark = time.monotonic()
        try:
            verified, unverifiable = await manifest.verify_staged(
                self.store, STAGING_BUCKET, files, object_name
            )
        except StagedSetMismatch as err:
            if self.ctx.metrics is not None:
                self.ctx.metrics.manifest_mismatches.inc()
            if self.ctx.record is not None:
                self.ctx.record.event("manifest_mismatch",
                                      problems=len(err.problems))
            self.logger.error("staged set failed manifest verification",
                              problems=err.problems[:5])
            raise
        if unverifiable:
            self.logger.warn("staged objects unverifiable, sealing on "
                             "put success alone", count=unverifiable)
        if self.ctx.record is not None:
            self.ctx.record.note_hop("upload", 0,
                                     time.monotonic() - verify_mark)
            self.ctx.record.event("manifest_verified", files=verified,
                                  unverifiable=unverifiable)

    def _note_fenced_marker(self, media_id: str, fence: int,
                            newer: int) -> None:
        if self.ctx.metrics is not None:
            self.ctx.metrics.fleet_fenced_writes.labels(
                op="done_marker").inc()
        if self.ctx.record is not None:
            self.ctx.record.event("fenced_write", op="done_marker",
                                  fence=fence, newer=newer)
        self.logger.warn("done marker already sealed by a newer fence; "
                         "stale seal fenced off", mediaId=media_id,
                         fence=fence, newer=newer)

    async def write_done_marker(self, media_id: str) -> None:
        """Seal the staging set: the idempotency marker the orchestrator
        probes — written only once EVERY file is staged.

        Fenced (fleet-coordinated jobs only): the marker carries the
        job's lease fence, an existing higher-fenced marker suppresses
        the write entirely (a stale resumed leader must not re-seal a
        set a newer authority already published — the seal it finds IS
        the completion it wanted, so the job still settles DONE), and a
        read-back after the write detects losing to a concurrent newer
        seal.  Jobs without a fence context write the reference-parity
        ``b"true"`` byte-for-byte.
        """
        name = done_marker_name(media_id)
        record = self.ctx.record
        fence = int(getattr(record, "fleet_fence", 0) or 0) \
            if record is not None else 0
        worker = getattr(record, "worker_id", None) \
            if record is not None else None

        if fence:
            # pre-write fence check (best-effort: any read trouble just
            # proceeds to the write — the read-back still verifies)
            try:
                existing = parse_done_marker(await self.store.get_object(
                    STAGING_BUCKET, name))
            except Exception:
                existing = None
            if (existing is not None and existing["done"]
                    and existing["fence"] > fence):
                self._note_fenced_marker(media_id, fence,
                                         existing["fence"])
                return

        async def _seal():
            if faults.enabled():
                await faults.fire("store.put", key=name)
            await self.store.put_object(
                STAGING_BUCKET, name, done_marker_body(fence, worker))

        seal_mark = time.monotonic()
        await self.retrier.run("store.put", _seal, cancel=self.ctx.cancel,
                               record=self.ctx.record, logger=self.logger)
        if fence:
            # CAS-style read-verify, same posture as the coordination
            # store's nonce read-back: a concurrent newer-fenced seal
            # landing over ours is a lost race we must attribute (the
            # set IS sealed either way — by the newer authority)
            try:
                back = parse_done_marker(await self.store.get_object(
                    STAGING_BUCKET, name))
            except Exception:
                back = None
            if back is not None and back["fence"] > fence:
                self._note_fenced_marker(media_id, fence, back["fence"])
        if self.ctx.record is not None:
            self.ctx.record.note_hop("upload", 0,
                                     time.monotonic() - seal_mark)

    async def cleanup_workdir(self, download_path: str) -> None:
        """Best-effort download-dir removal (reference lib/upload.js:60-64)."""
        try:
            await asyncio.to_thread(shutil.rmtree, download_path)
        except OSError as err:
            self.logger.warn("failed to clean up directory", error=str(err))


async def stage_factory(ctx: StageContext) -> StageFn:
    logger = ctx.logger
    uploader = Uploader(ctx)
    downloading = schemas.TelemetryStatus.Value("DOWNLOADING")

    async def upload(job: Job):
        last = job.last_stage
        files = last["files"] if isinstance(last, dict) else last.files
        download_path = (
            last["downloadPath"] if isinstance(last, dict) else last.downloadPath
        )

        if not isinstance(files, list):
            raise TypeError(
                f"Invalid files data type, expected list, got {type(files).__name__!r}"
            )

        logger.info("starting file upload", count=len(files))
        media_id = job.media.id

        with ctx.tracer.span("stage.upload", mediaId=media_id, files=len(files)):
            await uploader.ensure_bucket()

            landed = getattr(job, "landed_digests", None) or {}
            for i, file_path in enumerate(files, start=1):
                # cooperative cancellation at the per-file loop: already
                # staged files stay staged (redelivery/resume semantics
                # are unchanged), the current file simply never starts
                await uploader.upload_file(
                    media_id, file_path,
                    digest=landed.get(os.path.abspath(file_path)))

                # upload occupies the 50-100% progress band
                # (reference lib/upload.js:48)
                percent = (i / len(files) * 50) + 50
                await ctx.telemetry.emit_progress(media_id, downloading, int(percent))

            # integrity gate: the marker seals only a verified set
            await uploader.verify_staged_set(media_id, files)
            await uploader.write_done_marker(media_id)

        logger.info("finished uploading all files")

        await uploader.cleanup_workdir(download_path)
        return {}

    return upload
