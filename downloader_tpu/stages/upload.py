"""Upload stage: push filtered files into the staging object store.

Behavioral parity with /root/reference/lib/upload.js:

- validates ``files`` is a list (lib/upload.js:21-23)
- ensures bucket ``triton-staging`` exists (lib/upload.js:29-31)
- object name = ``<media.id>/original/<base64(basename)>``
  (lib/upload.js:43-44)
- per-file existence check; missing file is an error (lib/upload.js:38-41)
- progress telemetry mapped to 50-100% (lib/upload.js:47-51)
- writes ``<media.id>/original/done`` = ``"true"`` — the idempotency marker
  the orchestrator probes (lib/upload.js:55, lib/main.js:120)
- best-effort removal of the download directory (lib/upload.js:60-64)
"""

from __future__ import annotations

import asyncio
import base64
import os
import posixpath
import shutil

from .. import schemas
from ..utils.hashing import md5_file_hex, multipart_etag_hex
from .base import Job, StageContext, StageFn

STAGING_BUCKET = "triton-staging"
DONE_MARKER = "done"


def object_name(media_id: str, file_path: str) -> str:
    """``<id>/original/<base64(basename)>`` (reference lib/upload.js:43-44)."""
    encoded = base64.b64encode(os.path.basename(file_path).encode("utf-8")).decode("ascii")
    return posixpath.join(media_id, "original", encoded)


def done_marker_name(media_id: str) -> str:
    """``<id>/original/done`` (reference lib/upload.js:55)."""
    return posixpath.join(media_id, "original", DONE_MARKER)


async def _already_staged(store, name: str, file_path: str) -> bool:
    """True when the staged object provably holds this file's bytes.

    Requires both a size match and a content-hash match against the
    backend's etag; a backend that can't report one (empty etag) never
    short-circuits — size equality alone could seal a stale same-size
    object under the done marker.  The probe is best-effort: ANY stat
    failure (not just ObjectNotFound — e.g. write-only credentials where
    HEAD answers 403) means "not staged" so the upload proceeds instead
    of failing a job the plain put path would have handled fine.
    """
    try:
        info = await store.stat_object(STAGING_BUCKET, name)
    except Exception:
        return False
    if not info.etag or info.size != os.path.getsize(file_path):
        return False
    if "-" in info.etag:
        # multipart object: its etag is md5-of-part-md5s at the store's
        # part size, which we can recompute locally — without this, every
        # large (multipart) file would re-upload on redelivery, exactly
        # the files resume matters for
        part_size = getattr(store, "multipart_part_size", None)
        if not part_size:
            return False
        expected = await asyncio.to_thread(
            multipart_etag_hex, file_path, part_size
        )
        return info.etag == expected
    return info.etag == await asyncio.to_thread(md5_file_hex, file_path)


async def stage_factory(ctx: StageContext) -> StageFn:
    logger = ctx.logger
    store = ctx.store
    if store is None:
        raise ValueError("upload stage requires a StageContext.store")
    downloading = schemas.TelemetryStatus.Value("DOWNLOADING")

    # service-wide egress cap (bytes/s) to the staging store, the mirror
    # of the download stage's ingress bucket: ONE bucket shared by every
    # job's uploads (memoized in the cross-job ctx.resources), so MinIO
    # egress is cappable per instance
    # (``instance.upload_rate_limit`` / 0 = unlimited, parity default)
    from ..utils.ratelimit import shared_bucket

    limiter = shared_bucket(ctx.resources, ctx.config, "upload_rate_limit")

    async def upload(job: Job):
        last = job.last_stage
        files = last["files"] if isinstance(last, dict) else last.files
        download_path = (
            last["downloadPath"] if isinstance(last, dict) else last.downloadPath
        )

        if not isinstance(files, list):
            raise TypeError(
                f"Invalid files data type, expected list, got {type(files).__name__!r}"
            )

        logger.info("starting file upload", count=len(files))
        media_id = job.media.id

        uploaded_total = 0
        with ctx.tracer.span("stage.upload", mediaId=media_id, files=len(files)):
            if not await store.bucket_exists(STAGING_BUCKET):
                await store.make_bucket(STAGING_BUCKET)

            for i, file_path in enumerate(files, start=1):
                # cooperative cancellation at the per-file loop: already
                # staged files stay staged (redelivery/resume semantics
                # are unchanged), the current file simply never starts
                ctx.cancel.raise_if_cancelled()
                logger.info("upload", file=os.path.basename(file_path))
                if not os.path.exists(file_path):
                    logger.error("failed to upload file, not found", file=file_path)
                    raise FileNotFoundError(f"{file_path} not found.")

                name = object_name(media_id, file_path)
                # file-level resume: a redelivered job (crash/nack before the
                # done marker was written) skips files whose bytes are
                # provably already staged — the reference re-uploads
                # everything from scratch (lib/upload.js:34-52)
                if await _already_staged(store, name, file_path):
                    logger.info("already staged, skipping", file=file_path)
                else:
                    # size BEFORE the put: consume=True permits the
                    # backend to take the path destructively
                    size = os.path.getsize(file_path)
                    # consume=True: the staged file is deleted with the
                    # whole download dir right after this stage
                    # (reference lib/upload.js:60-64), so the store may
                    # ingest it by hardlink instead of a byte copy
                    await store.fput_object(
                        STAGING_BUCKET, name, file_path, consume=True)
                    if limiter is not None:
                        # charge AFTER the successful put: consume()
                        # deducts immediately and sleeps off the deficit,
                        # pacing the AVERAGE egress rate without hooks
                        # inside the store client's transfer loop.
                        # Charging up front would strand service-wide
                        # tokens for bytes that never moved whenever a
                        # job is cancelled or the put fails mid-wait —
                        # debt every OTHER job would then sleep off.
                        await limiter.consume(size)
                    uploaded_total += size
                    if ctx.record is not None:
                        ctx.record.add_bytes("uploaded", size)
                        # live counter for the transfer profiler's
                        # per-job throughput/stall sampling
                        ctx.record.note_transfer("upload", uploaded_total)
                    if ctx.metrics is not None:
                        ctx.metrics.bytes_uploaded.inc(size)

                # upload occupies the 50-100% progress band
                # (reference lib/upload.js:48)
                percent = (i / len(files) * 50) + 50
                await ctx.telemetry.emit_progress(media_id, downloading, int(percent))

            await store.put_object(
                STAGING_BUCKET, done_marker_name(media_id), b"true"
            )

        logger.info("finished uploading all files")

        # best-effort cleanup (reference lib/upload.js:60-64)
        try:
            await asyncio.to_thread(shutil.rmtree, download_path)
        except OSError as err:
            logger.warn("failed to clean up directory", error=str(err))
        return {}

    return upload
