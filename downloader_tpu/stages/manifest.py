"""Staged-artifact integrity: the per-job content manifest.

The ``done`` marker is the idempotency probe the whole fleet trusts —
once it exists, the converter (and every redelivered attempt) assumes
the staging set under ``<id>/original/`` is complete and correct.  A
worker crash mid-upload cannot tear a SINGLE object (S3 semantics: an
object appears only when its put completes), but before this module
nothing proved the SET: a marker written against a staging prefix that
lost an object, or whose object was re-written by a buggy peer between
upload and seal, would publish a short or corrupt set downstream.

The manifest closes that window:

- as each file **lands** in the staging store, the uploader records the
  object's name, the local file's size, and the **store-computed
  content hash** (the S3-style etag: plain MD5 for single-part puts,
  ``md5(md5(parts))-N`` for multipart) — captured from the stat the
  upload path already performs, never by re-reading the file;
- the entries persist to ``<workdir>/.manifest.json`` (atomic
  temp+rename per update), so a redelivered attempt after a crash
  inherits what its predecessor proved;
- :meth:`JobManifest.verify_staged` runs **before the done marker is
  written**, re-statting every object in the authoritative file list:
  each must exist, match the recorded size, and carry the recorded
  etag.  Any discrepancy raises :class:`StagedSetMismatch` (classified
  transient — the redelivery re-stages) and the marker is never
  written, so a torn crash can at worst delay a publish, never corrupt
  one.

Backends that do not report etags (``ObjectInfo.etag == ""``) degrade
to size-only verification — documented, and still enough to catch the
short-set case.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
from typing import Dict, Optional

from ..platform.config import cfg_get
from ..platform.errors import TRANSIENT
from ..store.base import ObjectNotFound

MANIFEST_BASENAME = ".manifest.json"
SCHEMA = 1


class StagedSetMismatch(RuntimeError):
    """The staged objects do not match the per-job content manifest.

    Carries ``fault_class = TRANSIENT``: the failure policy parks and
    nacks, and the redelivered attempt re-stages whatever diverged
    (``_already_staged`` skips the objects that still verify).
    """

    fault_class = TRANSIENT

    def __init__(self, media_id: str, problems: list):
        self.media_id = media_id
        self.problems = problems
        super().__init__(
            f"staged set for {media_id} failed manifest verification: "
            + "; ".join(problems[:5])
            + (f" (+{len(problems) - 5} more)" if len(problems) > 5 else "")
        )


def integrity_enabled(config) -> bool:
    """``integrity.enabled`` (default True): the manifest + pre-seal
    verification.  Off restores the exact pre-manifest upload path."""
    return bool(cfg_get(config, "integrity.enabled", True))


class JobManifest:
    """Content manifest for one job's staging set.

    Entries key on the staged object name; each holds the local size
    and the store's content hash observed when the object landed.  The
    file lives beside the job's own downloads (a dot-file, invisible to
    the media-extension walk) and dies with the workdir — by then the
    set is sealed or swept.
    """

    def __init__(self, workdir: str, media_id: str):
        self.workdir = workdir
        self.media_id = media_id
        self.path = os.path.join(workdir, MANIFEST_BASENAME)
        self.entries: Dict[str, dict] = {}
        # persist() runs on worker threads (the upload path hands it to
        # asyncio.to_thread); concurrent staging workers must not race
        # the temp-file write
        self._io_lock = threading.Lock()

    @classmethod
    def load(cls, workdir: str, media_id: str) -> "JobManifest":
        """Load a prior attempt's manifest (missing/torn file = empty:
        the resume probes repopulate it entry by entry)."""
        manifest = cls(workdir, media_id)
        try:
            with open(manifest.path, "r", encoding="utf-8") as fh:
                raw = json.load(fh)
        except (OSError, ValueError):
            return manifest
        if isinstance(raw, dict) and raw.get("mediaId") == media_id:
            entries = raw.get("entries")
            if isinstance(entries, dict):
                manifest.entries = {
                    str(name): dict(entry)
                    for name, entry in entries.items()
                    if isinstance(entry, dict)
                }
        return manifest

    def note(self, object_name: str, *, size: int, etag: str,
             file: Optional[str] = None) -> None:
        """Record one landed object (memory only — the caller persists
        via :meth:`persist` off-loop after each landing)."""
        self.entries[object_name] = {
            "size": int(size), "etag": etag or "",
            "file": os.path.basename(file) if file else "",
        }

    def persist(self) -> None:
        """Write the manifest (atomic temp + rename — a crash mid-update
        leaves the previous manifest, never a torn one).

        Blocking disk I/O: callers on the event loop wrap it in
        ``asyncio.to_thread`` so a large staging set's per-file updates
        never stall concurrent transfers.  The entries dict is copied
        up front (atomic under the GIL) so loop-side ``note`` calls
        cannot mutate it mid-serialization.
        """
        blob = {"schema": SCHEMA, "mediaId": self.media_id,
                "entries": dict(self.entries)}
        tmp = self.path + ".tmp"
        try:
            with self._io_lock:
                os.makedirs(self.workdir, exist_ok=True)
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(blob, fh, separators=(",", ":"))
                os.replace(tmp, self.path)
        except OSError:
            # the manifest is defense-in-depth: losing an update degrades
            # the verify (the entry re-notes on the next attempt), it
            # must never fail the upload that just succeeded
            pass

    async def verify_staged(self, store, bucket: str, files,
                            object_name_fn):
        """Re-stat every authoritative file's staged object against the
        manifest; raise :class:`StagedSetMismatch` on any divergence.

        ``files`` is the post-download walk's list (the same one the
        done marker seals); ``object_name_fn`` maps a local path to its
        staged object name.  Returns ``(verified, unverifiable)``
        counts.  Only :class:`~..store.base.ObjectNotFound` proves an
        object missing; any OTHER stat failure (write-only credentials
        where HEAD answers 403, a store outage at verify time) makes
        that object unverifiable and skips it — the same best-effort
        posture as ``_already_staged`` and the post-put stat, because
        this layer is defense-in-depth and must never fail a staging
        set the put path itself proved landed.
        """
        # stats are independent metadata round trips: run them
        # concurrently (bounded — a 200-file season must not open 200
        # sockets at once) so the seal pays ~1 RTT, not len(files)
        gate = asyncio.Semaphore(16)

        async def _check(file_path):
            """(problem | None, unverifiable 0|1) for one file."""
            name = object_name_fn(self.media_id, file_path)
            entry = self.entries.get(name)
            if entry is None:
                return f"{name}: no manifest entry", 0
            try:
                async with gate:
                    info = await store.stat_object(bucket, name)
            except ObjectNotFound:
                return f"{name}: missing from store", 0
            except Exception:
                return None, 1
            if int(info.size) != int(entry.get("size", -1)):
                return (f"{name}: size {info.size} != manifest "
                        f"{entry.get('size')}"), 0
            expected = entry.get("etag") or ""
            if expected and info.etag and info.etag != expected:
                return f"{name}: etag {info.etag} != manifest {expected}", 0
            return None, 0

        results = await asyncio.gather(*(_check(f) for f in files))
        problems = [problem for problem, _ in results if problem]
        unverifiable = sum(skipped for _, skipped in results)
        if problems:
            raise StagedSetMismatch(self.media_id, problems)
        return len(files) - unverifiable, unverifiable
