"""The stage plugin contract.

Reference contract: every stage module exports
``async (config, emitter, logger) => async (job) => result``
(/root/reference/lib/download.js:30,230, lib/process.js:101-103,
lib/upload.js:14-17).  The orchestrator loads stages by name from the
``stages`` list, validates the factory returned a callable
(lib/main.js:99-115), and threads each result to the next stage as
``job.lastStage`` (lib/main.js:129-140).

Differences from the reference, per SURVEY.md §7 step 6 (bug fixes):
- telemetry is an explicit ``StageContext`` field, not a ``global.telem``
- the tracer is threaded through and actually used

Streaming hand-off (beyond reference): alongside the ``last_stage``
barrier contract, a job may carry a :class:`FileStream` — the download
stage announces each durably-complete file into it (``FileEvent``) the
moment its bytes are final, so the streaming pipeline
(stages/streaming.py) can filter and upload that file while later files
are still downloading.  Stages that ignore ``job.file_stream`` keep
working unchanged: the pipeline reconciles against the authoritative
directory walk when the download completes.
"""

from __future__ import annotations

import asyncio
import dataclasses
import importlib
import os
from typing import Any, Awaitable, Callable, Dict, Optional

from .. import schemas
from ..control.cancel import CancelToken
from ..platform.logging import Logger
from ..platform.telemetry import NullTelemetry, Telemetry
from ..platform.tracing import NullTracer, Tracer
from ..utils import EventEmitter

# Fixed stage order (reference lib/main.js:28-32).
STAGES = ["download", "process", "upload"]


@dataclasses.dataclass
class FileEvent:
    """One durably-complete file, announced by the download stage while the
    rest of the job may still be transferring.

    "Durable" means the file's bytes are final on disk: torrent files whose
    every overlapping piece is SHA-1-verified and written, HTTP downloads at
    promote time (``.partial`` renamed onto the output name), bucket objects
    after their ``fget`` completes.  Downstream consumers (the streaming
    pipeline's filter + upload pool) may read the file immediately.
    """

    path: str
    size: int = 0


class FileStream:
    """Bounded hand-off channel from the download stage to the streaming
    pipeline (stages/streaming.py).

    ``emit`` applies backpressure when the consumer lags (the producer's
    transfer loop slows instead of buffering unboundedly) and becomes a
    no-op once the stream is closed, so late announcements — e.g. from a
    source that keeps calling back after the consumer gave up — never
    error the producer.  ``next`` returns ``None`` when the stream is
    closed and drained.
    """

    _SENTINEL = object()

    def __init__(self, maxsize: int = 1024):
        self._queue: asyncio.Queue = asyncio.Queue(maxsize)
        self._closed = False

    @property
    def closed(self) -> bool:
        return self._closed

    async def emit(self, path: str, size: Optional[int] = None) -> None:
        if self._closed:
            return
        if size is None:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
        await self._queue.put(FileEvent(path=path, size=int(size)))

    async def close(self) -> None:
        """Append the end-of-stream sentinel (idempotent)."""
        if self._closed:
            return
        self._closed = True
        await self._queue.put(self._SENTINEL)

    async def next(self) -> Optional[FileEvent]:
        """Next event, or None once the stream is closed and drained."""
        item = await self._queue.get()
        if item is self._SENTINEL:
            # keep the sentinel visible for any other reader
            self._queue.put_nowait(self._SENTINEL)
            return None
        return item


@dataclasses.dataclass
class Job:
    """What a stage receives: the decoded message plus the previous stage's
    result (reference ``_.create(msg, {lastStage})``, lib/main.js:131-133)."""

    media: schemas.Media
    last_stage: Any = None
    # set by the download stage while this job LEADS a singleflight fetch
    # (store/cache.py): a ``report(percent)`` callable whose updates are
    # re-emitted through each coalesced waiter's own telemetry
    cache_report: Any = None
    # streaming hand-off (stages/streaming.py): when the orchestrator runs
    # the pipelined dispatch it sets a FileStream here, and the download
    # stage announces each durably-complete file into it the moment its
    # bytes are final — None (barrier mode / standalone stage use) keeps
    # the exact pre-streaming behavior
    file_stream: Optional[FileStream] = None
    # origin plane (downloader_tpu/origins/): redundant origins for the
    # SAME entity from Download.mirrors — http(s) URLs the racing fetch
    # spreads ranges across (or extra webseeds for a torrent source).
    # Empty = the exact single-origin behavior.
    mirrors: tuple = ()
    # Download.source_kind as an enum NAME ("AUTO" | "DIRECT" |
    # "MANIFEST"): MANIFEST ingests an http(s) source_uri as an
    # HLS-style media playlist; AUTO/DIRECT keep the historical
    # whole-entity dispatch on Media.source.
    source_kind: str = "AUTO"
    # cache-hit serving (stages/download.py materialize_hit): the
    # absolute paths the cache entry materialized into the workdir, so
    # the process stage (and the streaming pipeline's authoritative
    # reconcile) can serve straight from the known list instead of
    # re-walking the directory tree.  None = not served from cache;
    # downstream walks as before.
    cache_files: Optional[list] = None
    # hash-on-land (stages/download.py): ``{abspath: md5_hex}`` for files
    # whose content digest was computed while their bytes were still hot
    # in the page cache, at the landing/promote moment.  The upload stage
    # passes these through to the store and the staged manifest so no
    # later step has to re-read a staged file just to hash it.  Empty =
    # no digest known; downstream falls back to stat-side hashing.
    landed_digests: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class StageContext:
    """Everything a stage factory may need.

    ``config``/``emitter``/``logger`` mirror the reference factory args;
    the rest replaces its globals and module-level singletons.
    """

    config: Any
    emitter: EventEmitter
    logger: Logger
    telemetry: Telemetry = dataclasses.field(default_factory=NullTelemetry)
    metrics: Any = None
    store: Any = None
    tracer: Tracer = dataclasses.field(default_factory=NullTracer)
    # Optional override for the download stage's ad-hoc ``bucket://`` client
    # (tests inject a fake; default builds an S3 client).
    bucket_client_factory: Optional[Callable] = None
    # Cross-job shared state: the orchestrator passes the SAME dict/list to
    # every job's context, so stages can memoize long-lived resources (e.g.
    # the download stage's DHT node) and register async teardown callables
    # that run once at orchestrator shutdown.
    resources: dict = dataclasses.field(default_factory=dict)
    cleanups: list = dataclasses.field(default_factory=list)
    # Cooperative cancellation (control/cancel.py): the orchestrator
    # passes the job's token; stages check it in their chunk/file loops
    # (``ctx.cancel.raise_if_cancelled()``).  Standalone stage use gets a
    # fresh never-fired token, so the checks are always safe to call.
    cancel: CancelToken = dataclasses.field(default_factory=CancelToken)
    # The job's control-plane registry record (control/registry.py), for
    # byte-counter sampling (``record.add_bytes``); None outside the
    # orchestrator.
    record: Any = None
    # The job's run-slot handle (control/scheduler.py RunSlot): lets a
    # stage that parks for a long idle wait — the fleet plane's lease
    # waiters — give the concurrency slot back to runnable jobs and
    # reacquire it before resuming.  None outside the orchestrator.
    slot: Any = None

StageFn = Callable[[Job], Awaitable[Any]]
StageFactory = Callable[[StageContext], Awaitable[StageFn]]

_REGISTRY: Dict[str, str] = {
    "download": "downloader_tpu.stages.download",
    "process": "downloader_tpu.stages.process",
    "upload": "downloader_tpu.stages.upload",
    # built-in but not in the default STAGES order: config-gated via
    # ``instance.upscale.enabled`` (see app.py / stages/upscale.py)
    "upscale": "downloader_tpu.stages.upscale",
}


def register_stage(name: str, module: str) -> None:
    """Register an out-of-tree stage module (must expose ``stage_factory``)."""
    _REGISTRY[name] = module


def get_stage_factory(name: str) -> StageFactory:
    """Resolve a stage name to its factory.

    Mirrors the reference's dynamic ``require(path.join(__dirname,
    `${stage}.js`))`` loading (lib/main.js:101-106).
    """
    try:
        module_name = _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown stage {name!r}; known: {sorted(_REGISTRY)}") from None
    module = importlib.import_module(module_name)
    return module.stage_factory


async def load_stages(ctx: StageContext, names: Optional[list] = None) -> Dict[str, StageFn]:
    """Instantiate each stage and validate the contract
    (reference lib/main.js:99-115)."""
    table: Dict[str, StageFn] = {}
    for name in names or STAGES:
        factory = get_stage_factory(name)
        fn = await factory(ctx)
        if not callable(fn):
            raise TypeError(
                f"Invalid stage {name!r}: factory return value was not callable"
            )
        table[name] = fn
    return table
