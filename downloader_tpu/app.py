"""Service entrypoint & lifecycle.

Capability-equivalent to /root/reference/index.js: load config
(index.js:18), build logger + tracer (index.js:12-15), start the
orchestrator (index.js:19), install signal/unhandled-error handlers that run
the termination handler and exit 0/1 (index.js:21-35).

Run with ``python -m downloader_tpu``.
"""

from __future__ import annotations

import asyncio
import signal

from . import schemas  # noqa: F401  (ensures schemas import before serving)
from .health import start_server
from .mq import new_queue, resolve_backend
from .mq.memory import InMemoryBroker
from .orchestrator import Orchestrator
from .platform import metrics as prom
from .platform.config import cfg_get, load_config
from .platform.logging import get_logger
from .platform.telemetry import Telemetry
from .platform.tracing import init_tracer
from .store import new_client


def build_service(config=None, broker=None, store=None):
    """Wire the service graph; returns (orchestrator, metrics, telemetry).

    Factored out of :func:`main` so tests and benchmarks can assemble the
    exact production object graph against hermetic backends.
    """
    config = config or load_config("converter")
    logger = get_logger("downloader")
    tracer = init_tracer("downloader", logger, config)
    metrics = prom.new("downloader")
    # exporter health on /metrics: a down OTLP collector shows up as
    # climbing drop/error gauges instead of silently missing traces
    metrics.bind_tracer(tracer)

    # optional field-number reconciliation with a real triton-core
    # deployment (schemas/remap.py); bad tables fail here, at boot
    schemas.configure_remap(cfg_get(config, "wire_remap", None))

    # Queue backend per config: a real AMQP connection pair (one for jobs,
    # one for telemetry, like the reference's AMQP + Telemetry connections,
    # lib/main.js:46-50) or the hermetic in-process broker.  For the memory
    # backend, cap redeliveries so a deterministically-failing (poison) job
    # cannot hot-loop at the head of the queue and starve the worker;
    # RabbitMQ would need a dead-letter policy for the same guarantee.
    if broker is None and resolve_backend(config) == "memory":
        broker = InMemoryBroker(max_redeliveries=5)
    mq = new_queue(config, broker=broker, logger=logger)
    telem_mq = new_queue(config, broker=broker, logger=logger)
    telemetry = Telemetry(telem_mq, metrics)

    store = store if store is not None else new_client(config)

    # config-gated TPU compute stage: insert ``upscale`` between process
    # and upload (the reference has no compute stage; its downstream
    # converter does the transform — see stages/upscale.py)
    from .stages.base import STAGES
    from .stages.upscale import upscale_enabled

    stages = list(STAGES)
    if upscale_enabled(config):
        stages.insert(stages.index("upload"), "upscale")
        logger.info("upscale stage enabled", stages=stages)

    orchestrator = Orchestrator(
        config=config,
        mq=mq,
        store=store,
        telemetry=telemetry,
        metrics=metrics,
        tracer=tracer,
        logger=logger,
        stages=stages,
    )
    return orchestrator, metrics, telemetry


async def run(config=None) -> None:
    logger = get_logger("downloader")
    orchestrator, metrics, _telemetry = build_service(config)

    await orchestrator.start()
    runner = await start_server(orchestrator, metrics)
    logger.info("initialized")

    stop = asyncio.Event()

    def _on_signal() -> None:
        logger.info("signal received, shutting down")
        stop.set()

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, _on_signal)

    # SIGUSR1: dump every thread/task stack to the log — the "what is
    # this wedged worker doing" escape hatch when even the admin port
    # is unreachable (same payload as GET /debug/stacks)
    if hasattr(signal, "SIGUSR1"):
        def _on_dump() -> None:
            from .platform.obs import dump_stacks

            dump = dump_stacks()
            logger.warn("SIGUSR1 stack dump",
                        threads=dump["threads"], tasks=dump["tasks"])

        loop.add_signal_handler(signal.SIGUSR1, _on_dump)

    await stop.wait()
    await orchestrator.shutdown()
    await runner.cleanup()
    # flush any spans still queued for the OTLP collector
    await asyncio.to_thread(orchestrator.tracer.close)
    logger.info("shutdown complete")


def main() -> None:
    asyncio.run(run())


if __name__ == "__main__":
    main()
