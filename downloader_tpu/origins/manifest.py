"""HLS-style segment-manifest ingest (live + VOD).

A ``source_kind: MANIFEST`` job's ``source_uri`` names a *media
playlist* (the ``#EXTM3U`` / ``#EXTINF`` / ``#EXT-X-ENDLIST`` subset
every HLS/DASH-adjacent packager emits).  :class:`ManifestIngest` polls
it at a bounded interval, downloads each new segment through the origin
plane's :class:`~.racing.SegmentFetcher` (EWMA-ordered mirrors,
first-byte hedge, per-origin breaker/retry seams), and announces every
durable segment into the job's FileStream — so the streaming pipeline's
incremental filter + bounded upload pool stage segments while later
ones are still being produced.  The job settles DONE when the playlist
ends (``#EXT-X-ENDLIST``); a playlist that stops changing without
ending raises :class:`ManifestStalled` (``ERRDLSTALL``: the
orchestrator acks + drops, the dead-live-stream policy).

Supported tags (unknown tags are ignored, like real players):

- ``#EXT-X-TARGETDURATION:<s>`` — drives the refresh interval
  (``target/2`` clamped to ``origins.manifest.min_poll``/``max_poll``)
- ``#EXT-X-MEDIA-SEQUENCE:<n>`` — segment identity across refreshes
  (a sliding live window must not re-download renumbered lines)
- ``#EXTINF:<duration>[,title]`` — the next line is a segment URI,
  resolved against the *fetching origin's* playlist URL (so relative
  URIs ride whichever mirror serves the segment)
- ``#EXT-X-ENDLIST`` — no further segments: finish and settle

VOD fast path: a playlist that is already ended on first fetch skips
the polling machinery entirely and just drains its segment list.
"""

from __future__ import annotations

import asyncio
import os
import posixpath
import time
import urllib.parse
import zlib
from dataclasses import dataclass
from typing import List, Optional

from ..platform import faults
from ..platform.config import cfg_get
from .plan import Origin
from .racing import SegmentFetcher

DEFAULT_MIN_POLL = 0.25
DEFAULT_MAX_POLL = 6.0
DEFAULT_STALL_TIMEOUT = 240.0  # the transfer watchdog's posture
DEFAULT_LIVE_WINDOW = 0  # 0 = ingest from the playlist's first segment


class ManifestStalled(RuntimeError):
    """A live playlist stopped producing segments without ending."""

    code = "ERRDLSTALL"


class _HedgeTimeout(RuntimeError):
    """An origin spent the whole hedge window without answering.

    PERMANENT under the taxonomy ON PURPOSE: the hedge is the
    *fetcher's* impatience, not the origin's verdict — the next origin
    should get the segment after ONE window, without the Retrier
    re-asking the slow origin (attempts × hedge of added latency) and
    without ``record_failure`` opening a healthy-but-far origin's
    cross-job breaker over what may just be cold-cache TTFB.
    """

    fault_class = "permanent"


@dataclass
class Segment:
    seq: int
    uri: str
    duration: float = 0.0


@dataclass
class MediaPlaylist:
    target_duration: float
    media_sequence: int
    segments: List[Segment]
    ended: bool


def parse_playlist(text: str) -> MediaPlaylist:
    """Parse an HLS-style media playlist (see module doc).

    Raises ``ValueError`` (PERMANENT under the taxonomy: a mis-submitted
    manifest job must fail fast, not burn retries) when the payload is
    not a playlist at all.
    """
    if "#EXTM3U" not in text[:256]:
        raise ValueError("not an HLS playlist (missing #EXTM3U header)")
    target = 0.0
    media_seq = 0
    ended = False
    segments: List[Segment] = []
    pending: Optional[float] = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#EXT-X-TARGETDURATION:"):
            try:
                target = float(line.split(":", 1)[1])
            except ValueError:
                pass
        elif line.startswith("#EXT-X-MEDIA-SEQUENCE:"):
            try:
                media_seq = int(line.split(":", 1)[1])
            except ValueError:
                pass
        elif line.startswith("#EXTINF:"):
            try:
                pending = float(line.split(":", 1)[1].split(",", 1)[0])
            except ValueError:
                pending = 0.0
        elif line.startswith("#EXT-X-ENDLIST"):
            ended = True
        elif line.startswith("#"):
            continue  # unknown tag: ignored, like real players
        else:
            segments.append(Segment(
                seq=media_seq + len(segments), uri=line,
                duration=pending or 0.0,
            ))
            pending = None
    return MediaPlaylist(target_duration=target, media_sequence=media_seq,
                         segments=segments, ended=ended)


class ManifestIngest:
    """Poll one playlist, land its segments, announce each durably.

    ``origins`` is the job's origin set where each URL is that origin's
    copy of the PLAYLIST; segment URIs resolve per-origin, so a mirror
    serves its own segments.  ``announce(path, size)`` is the FileStream
    hand-off (None in barrier/standalone use); ``progress(percent)``
    emits the download stage's 0-50 telemetry band (capped at 49 while
    the playlist is live — 50 is the download-complete milestone the
    caller owns).
    """

    def __init__(self, origins: List[Origin], session, *, retrier,
                 health, cancel, record=None, metrics=None, logger=None,
                 config=None, limiter=None, announce=None, progress=None):
        self.origins = origins
        self.session = session
        self.cancel = cancel
        self.record = record
        self.logger = logger
        self.limiter = limiter
        self.announce = announce
        self.progress = progress
        self.fetcher = SegmentFetcher(
            origins, retrier=retrier, health=health, cancel=cancel,
            record=record, metrics=metrics, logger=logger, config=config,
        )
        self.min_poll = float(cfg_get(
            config, "origins.manifest.min_poll", DEFAULT_MIN_POLL
        ))
        self.max_poll = float(cfg_get(
            config, "origins.manifest.max_poll", DEFAULT_MAX_POLL
        ))
        self.stall_timeout = float(cfg_get(
            config, "origins.manifest.stall_timeout",
            DEFAULT_STALL_TIMEOUT
        ))
        self.live_window = int(cfg_get(
            config, "origins.manifest.live_window", DEFAULT_LIVE_WINDOW
        ))
        self._headers = {"Accept-Encoding": "identity"}
        self._moved_total = 0

    # -- mechanism -------------------------------------------------------
    async def _get(self, url: str, hedge: float):
        """One GET with the hedge window bounding time-to-headers."""
        coro = self.session.get(url, headers=self._headers)
        if hedge > 0:
            try:
                return await asyncio.wait_for(coro, hedge)
            except asyncio.TimeoutError:
                raise _HedgeTimeout(
                    f"no response within the {hedge:g}s hedge window"
                ) from None
        return await coro

    @staticmethod
    def _decoder_for(resp):
        """Mirror of the whole-file HTTP path's Content-Encoding
        defense: the session never decompresses and we ask for
        identity, but a misbehaving CDN can still send gzip — decode
        it rather than staging compressed bytes as media."""
        enc = resp.headers.get("Content-Encoding", "").strip().lower()
        if enc in ("", "identity"):
            return None
        if enc in ("gzip", "x-gzip", "deflate"):
            return zlib.decompressobj(zlib.MAX_WBITS | 32)
        raise RuntimeError(f"unsupported Content-Encoding: {enc}")

    async def _fetch_playlist(self) -> str:
        cell = {}

        async def fetch_one(origin: Origin, hedge: float) -> int:
            if faults.enabled():
                await faults.fire("origin.playlist", key=origin.url)
            # per-attempt liveness bound (see _fetch_segment)
            async with asyncio.timeout(max(self.stall_timeout, 1.0)):
                resp = await self._get(origin.url, hedge)
                try:
                    resp.raise_for_status()
                    text = await resp.text()
                finally:
                    resp.release()
            cell["text"] = text
            return len(text)

        await self.fetcher.fetch(fetch_one, what="playlist")
        return cell["text"]

    async def _fetch_segment(self, segment: Segment, dest: str) -> int:
        tmp = dest + ".part"
        record = self.record

        async def fetch_one(origin: Origin, hedge: float) -> int:
            url = urllib.parse.urljoin(origin.url, segment.uri)
            if faults.enabled():
                await faults.fire("origin.segment", key=url)
            moved = 0
            # per-ATTEMPT liveness bound: the ingest loop's own stall
            # check cannot fire while blocked inside this fetch, and a
            # sole origin gets no hedge window — without this bound a
            # mid-body black-hole would ride aiohttp's 5-minute session
            # default × retry attempts before the contract ("liveness
            # is the ingest's stall_timeout") meant anything
            async with asyncio.timeout(max(self.stall_timeout, 1.0)):
                resp = await self._get(url, hedge)
                try:
                    resp.raise_for_status()
                    decoder = self._decoder_for(resp)
                    hop_mark = time.monotonic()
                    # graftlint: disable=blocking-call-in-async -- one open(2); the segment body loop below awaits per chunk
                    with open(tmp, "wb") as fh:
                        async for chunk in resp.content.iter_any():
                            if record is not None:
                                record.note_hop(
                                    "socket_read", len(chunk),
                                    time.monotonic() - hop_mark)
                            self.cancel.raise_if_cancelled()
                            if self.limiter is not None:
                                await self.limiter.consume(len(chunk))
                            data = (decoder.decompress(chunk)
                                    if decoder else chunk)
                            write_mark = time.monotonic()
                            if data:
                                fh.write(data)
                                if record is not None:
                                    record.note_hop(
                                        "disk_write", len(data),
                                        time.monotonic() - write_mark)
                                moved += len(data)
                            if record is not None:
                                record.note_transfer(
                                    "download",
                                    self._moved_total + moved,
                                )
                            hop_mark = time.monotonic()
                        if decoder is not None:
                            tail = decoder.flush()
                            if tail:
                                fh.write(tail)
                                moved += len(tail)
                finally:
                    resp.close()
            # durable only on a complete body: a failed-over retry
            # restarts the temp file, never stitches two origins
            os.replace(tmp, dest)
            return moved

        moved = await self.fetcher.fetch(
            fetch_one, what=f"segment seq={segment.seq}"
        )
        self._moved_total += moved
        return moved

    # -- naming ----------------------------------------------------------
    @staticmethod
    def _segment_name(segment: Segment) -> str:
        path = urllib.parse.urlsplit(segment.uri).path
        name = posixpath.basename(path)
        if not name:
            name = f"seg{segment.seq:08d}.ts"
        # keep names collision-proof across sequence reuse without
        # losing the media extension the filter keys on
        return name

    def _dest(self, download_path: str, segment: Segment,
              used: set) -> str:
        name = self._segment_name(segment)
        if name in used:
            name = f"{segment.seq:08d}-{name}"
        used.add(name)
        return os.path.join(download_path, name)

    # -- the ingest loop -------------------------------------------------
    def _poll_interval(self, playlist: MediaPlaylist) -> float:
        base = (playlist.target_duration / 2.0
                if playlist.target_duration > 0 else 1.0)
        return min(max(base, self.min_poll), self.max_poll)

    async def _emit_progress(self, fetched: int, known: int,
                             ended: bool) -> None:
        if self.progress is None:
            return
        percent = int(50 * fetched / known) if known else 0
        if not ended:
            percent = min(percent, 49)
        await self.progress(min(percent, 50))

    async def run(self, playlist_url: str, download_path: str) -> int:
        """Ingest until ``#EXT-X-ENDLIST`` (or VOD drain); returns bytes
        landed.  Raises :class:`ManifestStalled` when a live playlist
        goes ``origins.manifest.stall_timeout`` without producing."""
        os.makedirs(download_path, exist_ok=True)
        done_seqs: set = set()
        used_names: set = set()
        total = 0
        fetched = 0
        last_change = time.monotonic()
        first = True
        final_text = ""
        while True:
            self.cancel.raise_if_cancelled()
            text = await self._fetch_playlist()
            playlist = parse_playlist(text)
            final_text = text
            segments = playlist.segments
            if first:
                if self.record is not None:
                    self.record.event(
                        "manifest_open", segments=len(segments),
                        ended=playlist.ended,
                        target_duration=playlist.target_duration,
                    )
                if (not playlist.ended and self.live_window > 0
                        and len(segments) > self.live_window):
                    skipped = segments[:-self.live_window]
                    done_seqs.update(s.seq for s in skipped)
                    segments = segments[-self.live_window:]
                    if self.logger is not None:
                        self.logger.info(
                            "manifest: joining at the live edge",
                            skipped=len(skipped),
                            window=self.live_window,
                        )
                first = False
            new = [s for s in segments if s.seq not in done_seqs]
            if new and self.record is not None:
                self.record.event("manifest_refresh", new=len(new),
                                  head_seq=new[0].seq,
                                  ended=playlist.ended)
            if new or playlist.ended:
                last_change = time.monotonic()
            known = len(done_seqs) + len(new)
            for segment in new:
                self.cancel.raise_if_cancelled()
                dest = self._dest(download_path, segment, used_names)
                moved = await self._fetch_segment(segment, dest)
                total += moved
                fetched += 1
                done_seqs.add(segment.seq)
                last_change = time.monotonic()
                if self.logger is not None:
                    self.logger.info("manifest: segment landed",
                                     seq=segment.seq, bytes=moved,
                                     file=os.path.basename(dest))
                if self.announce is not None:
                    # the streaming pipeline may stage this segment NOW,
                    # while the playlist keeps producing later ones
                    await self.announce(dest, moved)
                await self._emit_progress(fetched, known, playlist.ended)
            if playlist.ended:
                if self.record is not None:
                    self.record.event("manifest_end",
                                      segments=len(done_seqs),
                                      bytes=total)
                break
            idle = time.monotonic() - last_change
            if idle > self.stall_timeout:
                raise ManifestStalled(
                    f"live playlist unchanged for {idle:.0f}s "
                    f"(stall budget {self.stall_timeout:.0f}s)"
                )
            await self.cancel.guard(
                asyncio.sleep(self._poll_interval(playlist))
            )
        # provenance: keep the final playlist beside the segments (its
        # extension is not media, so the filter never stages it)
        name = posixpath.basename(
            urllib.parse.urlsplit(playlist_url).path
        ) or "playlist.m3u8"
        try:
            # graftlint: disable=blocking-call-in-async -- playlist text is KBs, written once at ingest end
            with open(os.path.join(download_path, name), "w") as fh:
                fh.write(final_text)
        except OSError:
            pass
        return total
