"""Origin plane: multi-origin racing fetch + segment-manifest ingest.

Turns "one job = one origin" into "one job = a set of interchangeable
origins plus an optional live manifest" (ROADMAP item 4):

- :mod:`plan` — origin identity: URL -> bounded metric/breaker label,
  and the cross-job :class:`~.plan.OriginHealth` EWMA throughput table
  the scheduler's assignment and straggler decisions read.
- :mod:`racing` — :class:`~.racing.RangeScheduler`: work-stealing byte
  ranges across origins, per-origin Retrier/CircuitBreaker seams
  (``origin:<label>``), straggler-tail duplication (first-byte-wins,
  loser cancelled), and failover that never fails the job while any
  origin lives; plus :class:`~.racing.SegmentFetcher`, the hedged
  per-segment variant the manifest ingest drives.
- :mod:`manifest` — HLS-style media-playlist ingest: bounded-interval
  refresh, live-edge window, ``#EXT-X-ENDLIST`` termination, VOD fast
  path, each durable segment announced into the job's FileStream so the
  streaming pipeline stages it while later segments are still being
  produced.

The byte-moving mechanism stays in ``stages/download.py`` (the same
``.partial``/splice/If-Range machinery single-origin fetches use); this
package owns only the *policy*: which origin fetches which bytes next.
"""

from .plan import Origin, OriginHealth, origin_label, resolve_mirrors
from .racing import RangeScheduler, SegmentFetcher

__all__ = [
    "Origin", "OriginHealth", "RangeScheduler", "SegmentFetcher",
    "origin_label", "resolve_mirrors",
]
