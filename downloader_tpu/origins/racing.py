"""Racing fetch: work-stealing byte ranges across redundant origins.

:class:`RangeScheduler` owns the *policy* of a multi-origin download —
which origin fetches which byte range next — while the byte-moving
*mechanism* (ranged requests, If-Range validation, splice/stream landing
into the shared ``.partial-seg`` file, checkpointing) stays with the
caller (``stages/download.py``), passed in as a ``fetch`` callback.
That split keeps resume, hashing, and the streaming upload overlap
byte-identical with the single-origin path: racing only changes who
serves each range.

Scheduling model:

- one worker per origin; workers *pull* the next pending range
  (work-stealing), so a fast origin naturally serves more bytes —
  no static partitioning to mis-size
- per-origin throughput EWMA (:class:`~.plan.OriginHealth`, fed from
  the same per-chunk progress hook that bills the hop ledger) drives
  the straggler decision: once no pending ranges remain, an idle origin
  whose EWMA beats the owner's by ``origins.dup_factor`` duplicates the
  straggler's remaining tail — first landed byte wins, the loser is
  cancelled (politely at its next chunk; a black-holed loser is task-
  cancelled when the scheduler finishes), and both writers produce
  identical bytes (every request carries the same strong validator), so
  the brief overlap window is harmless
- every range attempt runs under the origin's own Retrier policy and
  CircuitBreaker (dependency ``origin:<label>``, family-config
  ``retry.origin`` / ``breakers.origin``): an exhausted origin is
  marked dead *for this job* and its in-flight range returns to the
  pending pool at its landed position — failover re-fetches zero
  already-landed bytes and the job fails only when every origin died

:class:`SegmentFetcher` is the per-segment variant the manifest ingest
(:mod:`.manifest`) drives: whole small objects instead of ranges, with
EWMA-ordered origin selection, a first-byte hedge timeout, and the same
per-origin breaker/retry seams.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, List, Optional

from ..control.cancel import JobCancelled
from ..platform.config import cfg_get
from ..platform.errors import BreakerOpen
from .plan import Origin, OriginHealth

DEFAULT_DUP_FACTOR = 1.25
# a straggler tail smaller than this is cheaper to wait out than to
# duplicate (connection setup would cost more than the overlap saves)
DEFAULT_MIN_DUP_BYTES = 1 << 20
# a range whose writers have landed nothing for this long is STALLED:
# idle origins may then take it over / duplicate it regardless of the
# EWMA and min-tail gates — those gates assume "slow", not "black-
# holed", and only the 240 s job watchdog would otherwise resolve a
# hang (by failing a job a healthy origin could have finished)
DEFAULT_STALL_TAKEOVER = 10.0
# idle-worker re-evaluation cadence while waiting for a dup opportunity
# (also the run() completion-poll cadence: a hung loser must not block
# the finished download)
_WAKE_POLL = 0.05

ASSIGN = "assign"
FAILOVER = "failover"
STRAGGLER_DUP = "straggler_dup"
FASTEST = "fastest"


class _Range:
    """Scheduler-side state for one canonical ``[start, pos, end]``
    triple (the SAME list object the caller's checkpoint snapshots)."""

    __slots__ = ("seg", "index", "owner", "dup", "winner",
                 "failed_over", "done", "last_progress")

    def __init__(self, seg: list, index: int):
        self.seg = seg
        self.index = index
        self.owner: Optional[Origin] = None
        self.dup: Optional[Origin] = None
        # which role's bytes decided the range: None until a duplicate
        # lands its first byte, then "dup"
        self.winner: Optional[str] = None
        self.failed_over = False
        # win-credit latch (metrics fire once per range); COMPLETION is
        # always judged on bytes (``complete``), never on this flag — a
        # range whose final bytes landed is finished no matter which
        # writer's credit bookkeeping got there first
        self.done = False
        self.last_progress = time.monotonic()

    @property
    def complete(self) -> bool:
        return self.seg[1] >= self.seg[2]

    @property
    def remaining(self) -> int:
        return max(self.seg[2] - self.seg[1], 0)

    def stalled(self, now: float, after: float) -> bool:
        """True when this in-flight range has landed nothing for
        ``after`` seconds — its writer(s) are black-holed, not slow."""
        return (not self.complete
                and (self.owner is not None or self.dup is not None)
                and now - self.last_progress > after)


class RangeScheduler:
    """Drive one entity's ranges across an origin set (see module doc).

    ``fetch(origin, triple, guard)`` is the mechanism callback: fetch
    ``[triple[1], triple[2])`` from ``origin.url``, landing bytes at
    their absolute offsets, advancing ``triple[1]`` per chunk, and
    calling ``guard(delta_bytes)`` (sync) after each landed chunk —
    ``False`` means stop fetching now (range finished elsewhere / this
    writer lost the duplicate race).  The triple handed to ``fetch`` is
    PRIVATE to that attempt; the scheduler merges progress into the
    canonical checkpointed triple inside the guard, so concurrent
    owner/duplicate writers never share a cursor — and since both
    streams carry the same strong validator, every byte below the
    merged maximum is on disk no matter which writer put it there.
    """

    def __init__(self, origins: List[Origin], segments: List[list],
                 fetch: Callable, *, retrier, health: OriginHealth,
                 cancel=None, record=None, metrics=None, logger=None,
                 config=None):
        self.origins = origins
        self.ranges = [_Range(seg, i) for i, seg in enumerate(segments)]
        self.fetch = fetch
        self.retrier = retrier
        self.health = health
        self.cancel = cancel
        self.record = record
        self.metrics = metrics
        self.logger = logger
        self.dup_factor = float(cfg_get(
            config, "origins.dup_factor", DEFAULT_DUP_FACTOR
        ))
        self.min_dup_bytes = int(cfg_get(
            config, "origins.min_dup_bytes", DEFAULT_MIN_DUP_BYTES
        ))
        self.stall_takeover = float(cfg_get(
            config, "origins.stall_takeover", DEFAULT_STALL_TAKEOVER
        ))
        self._wake = asyncio.Event()

    # -- observability ---------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self.record is not None:
            self.record.event(kind, **fields)

    def _note_win(self, origin: Origin, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.origin_race_wins.labels(
                origin=origin.label, reason=reason
            ).inc()

    def _active_ranges(self, origin: Origin, delta: int) -> None:
        if self.metrics is not None:
            self.metrics.origin_active_ranges.labels(
                origin=origin.label
            ).inc(delta)

    # -- scheduling ------------------------------------------------------
    def _live(self, exclude: Optional[Origin] = None) -> List[Origin]:
        return [o for o in self.origins
                if not o.dead and o is not exclude]

    def _breaker(self, origin: Origin):
        breakers = getattr(self.retrier, "breakers", None)
        if breakers is None or not breakers.enabled:
            return None
        return breakers.get(f"origin:{origin.label}")

    def _blocked(self, origin: Origin) -> bool:
        """True while the origin's breaker would reject a call — the
        worker idles instead of burning attempts into an open breaker
        (half-open is NOT blocked: the probe may revive it)."""
        breaker = self._breaker(origin)
        return breaker is not None and breaker.blocking

    def _all_done(self) -> bool:
        # byte-completeness, never the credit latch: the final bytes may
        # land through a writer whose credit bookkeeping lost the race
        return all(rng.complete for rng in self.ranges)

    def _pick(self, origin: Origin):
        """Next work item for ``origin``: ``(range, role)`` or None."""
        now = time.monotonic()
        # pending ranges first (work-stealing pull).  A range with live
        # writers is normally NOT pending (a fresh owner would just
        # duplicate their work) — unless the range is STALLED: a
        # black-holed writer cannot be failed over until its own
        # request errors, so a fresh owner takes (or, with BOTH slots
        # held by stalled writers, EVICTS) the owner slot and
        # first-byte-wins re-arbitrates.  Eviction is safe: slot
        # releases are identity-guarded, so the replaced writer becomes
        # a harmless zombie — it writes the same validated bytes if it
        # ever wakes, and the scheduler cancels it at run() end.
        for rng in self.ranges:
            if rng.complete or rng.done:
                continue
            stalled = rng.stalled(now, self.stall_takeover)
            if rng.owner is not None:
                if not (stalled and rng.dup is not None):
                    continue  # a live owner keeps its slot
            elif rng.dup is not None and not stalled:
                continue  # a live dup is already serving it
            rng.owner = origin
            rng.winner = None  # all writers re-race from here
            rng.last_progress = now
            reason = FAILOVER if rng.failed_over else ASSIGN
            self._event("range_assign", origin=origin.label,
                        range=[rng.seg[0], rng.seg[2]],
                        pos=rng.seg[1], reason=reason)
            return rng, "owner"
        # straggler duplication: no pending work left — shadow the
        # biggest in-flight tail whose owner this origin clearly beats,
        # or ANY stalled tail (the EWMA/min-tail gates assume a slow
        # owner; a hung one must not park the job until the watchdog)
        my_bps = self.health.bps(origin.label)
        best = None
        for rng in self.ranges:
            if (rng.complete or rng.done or rng.owner is None
                    or rng.dup is not None or rng.owner is origin):
                continue
            if not rng.stalled(now, self.stall_takeover):
                if rng.remaining < self.min_dup_bytes:
                    continue
                owner_bps = self.health.bps(rng.owner.label)
                if my_bps <= owner_bps * self.dup_factor:
                    continue
            if best is None or rng.remaining > best.remaining:
                best = rng
        if best is not None:
            best.dup = origin
            best.last_progress = now
            self._event("range_assign", origin=origin.label,
                        range=[best.seg[0], best.seg[2]],
                        pos=best.seg[1], reason=STRAGGLER_DUP,
                        owner=best.owner.label)
            return best, "dup"
        return None

    def _release_failed(self, origin: Origin, rng: _Range, role: str,
                        err: BaseException) -> None:
        """One origin's attempt on ``rng`` failed: put the work back.
        The canonical position keeps every landed byte, so the next
        owner resumes instead of re-fetching."""
        if role == "owner" and rng.owner is origin:
            rng.owner = None
            rng.failed_over = True
        if role == "dup" and rng.dup is origin:
            rng.dup = None
            if rng.winner == "dup":
                # the duplicate won the race and then died: whoever
                # picks the range up next is a fresh owner
                rng.winner = None
                rng.failed_over = True
        origin.failures += 1
        self._event("origin_failover", origin=origin.label,
                    range=[rng.seg[0], rng.seg[2]], pos=rng.seg[1],
                    error=str(err)[:160], type=type(err).__name__)
        if self.logger is not None:
            self.logger.warn("origin failed; range returns to pool",
                             origin=origin.label, pos=rng.seg[1],
                             range_end=rng.seg[2], error=str(err)[:200])

    def _release_lost(self, origin: Origin, rng: _Range,
                      role: str) -> None:
        """A writer stopped politely without completing the range (it
        lost the duplicate race): free its slot, no failover marks."""
        if role == "owner" and rng.owner is origin:
            rng.owner = None
        if role == "dup" and rng.dup is origin:
            rng.dup = None

    def _finish(self, origin: Origin, rng: _Range, role: str) -> None:
        if rng.done:
            return
        # the latch ALWAYS closes on completion (bytes are bytes); only
        # the metric credit is role-gated — an owner observing the range
        # complete after its duplicate won the first byte still finishes
        # the range, it just doesn't claim the win
        rng.done = True
        self._wake.set()
        if role == "owner" and rng.winner == "dup":
            return
        if role == "dup" and rng.winner != "dup":
            return
        if role == "dup":
            reason = STRAGGLER_DUP
        elif rng.failed_over:
            reason = FAILOVER
        else:
            reason = FASTEST
        self._note_win(origin, reason)

    async def _run_item(self, origin: Origin, rng: _Range,
                        role: str) -> None:
        seg = rng.seg
        # PRIVATE cursor (see class doc): starts at the canonical
        # position, advances with THIS writer's landed bytes only
        private = [seg[0], seg[1], seg[2]]
        last_mark = time.monotonic()

        def guard(delta: int) -> bool:
            nonlocal last_mark
            now = time.monotonic()
            if delta > 0:
                rng.last_progress = now
                self.health.feed(origin.label, delta, now - last_mark)
                if self.metrics is not None:
                    self.metrics.origin_bytes.labels(
                        origin=origin.label
                    ).inc(delta)
                if role == "dup" and rng.winner is None:
                    # first duplicated byte landed: the dup wins, the
                    # owner stops at its next chunk
                    rng.winner = "dup"
            last_mark = now
            if seg[1] < private[1]:
                seg[1] = private[1]
            self._wake.set()
            if seg[1] >= seg[2]:
                return False  # range complete (possibly via the peer)
            if role == "owner" and rng.winner == "dup":
                return False  # lost the duplicate race: stop politely
            return True

        self._active_ranges(origin, +1)
        try:
            await self.retrier.run(
                f"origin:{origin.label}.fetch",
                lambda: self.fetch(origin, private, guard),
                cancel=self.cancel, record=self.record,
                logger=self.logger,
            )
        except (asyncio.CancelledError, JobCancelled):
            raise
        except Exception as err:
            if getattr(err, "race_abort", False) and origin.primary:
                # the PRIMARY's entity changed mid-flight: the whole
                # attempt is stitched against a dead validator — abort
                # and let the caller restart cleanly
                raise
            if getattr(type(err), "code", None) == "ERRDLSTALL":
                raise
            self._release_failed(origin, rng, role, err)
            origin.dead = True
            if not self._live():
                raise  # every origin is gone: the job's own failure
            return
        finally:
            self._active_ranges(origin, -1)
            self._wake.set()
        if seg[1] >= seg[2]:
            self._finish(origin, rng, role)
        else:
            self._release_lost(origin, rng, role)

    async def _drive(self, origin: Origin) -> None:
        while not self._all_done():
            if origin.dead:
                return
            if self._blocked(origin):
                others = [o for o in self._live(exclude=origin)
                          if not self._blocked(o)]
                if not others:
                    # no origin anywhere can take a call right now:
                    # surface BreakerOpen (parked + redelivered without
                    # a poison charge) instead of idling to the watchdog
                    breaker = self._breaker(origin)
                    raise BreakerOpen(f"origin:{origin.label}",
                                      breaker.retry_after())
                await self._sleep_for_work()
                continue
            item = self._pick(origin)
            if item is None:
                await self._sleep_for_work()
                continue
            await self._run_item(origin, item[0], item[1])

    async def _sleep_for_work(self) -> None:
        self._wake.clear()
        try:
            await asyncio.wait_for(self._wake.wait(), _WAKE_POLL)
        except asyncio.TimeoutError:
            pass

    async def run(self) -> None:
        """Fetch every range; returns when all are complete.  Raises the
        failing origin's error only when NO origin remains alive (or on
        cancel/stall/primary-entity-change, which pass straight
        through).  Completion is polled independently of the workers: a
        duplicate-race loser hung inside a black-holed origin must not
        hold the finished download hostage — it is cancelled here."""
        workers = [
            asyncio.create_task(self._drive(origin),
                                name=f"race-{origin.label}")
            for origin in self.origins
        ]
        try:
            pending = set(workers)
            while pending and not self._all_done():
                done, pending = await asyncio.wait(
                    pending, timeout=_WAKE_POLL,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                for task in done:
                    if task.cancelled():
                        raise asyncio.CancelledError()
                    if task.exception() is not None:
                        raise task.exception()
        finally:
            for task in workers:
                task.cancel()
            await asyncio.gather(*workers, return_exceptions=True)
        if not self._all_done():
            # defensive: every worker exited (all origins dead) without
            # an error reaching the poll loop — a silent partial file
            # must never look complete
            raise RuntimeError("racing fetch ended with pending ranges")


class SegmentFetcher:
    """Per-segment origin selection for the manifest ingest.

    Origins are tried in EWMA-throughput order (ties keep submitter
    order, so the primary leads until the mirrors prove faster); each
    attempt runs under the origin's own ``origin:<label>.segment``
    Retrier/breaker seam, with ``origins.hedge_delay`` bounding the
    wait for the response's FIRST byte — a black-holed origin costs a
    hedge window per attempt, not a watchdog timeout, before the next
    origin gets the segment.  ``fetch_one(origin, hedge_s)`` is the
    mechanism callback (the manifest ingest owns the HTTP + disk
    work); a raised error fails over, exhausting every origin fails
    the segment.
    """

    def __init__(self, origins: List[Origin], *, retrier,
                 health: OriginHealth, cancel=None, record=None,
                 metrics=None, logger=None, config=None):
        self.origins = origins
        self.retrier = retrier
        self.health = health
        self.cancel = cancel
        self.record = record
        self.metrics = metrics
        self.logger = logger
        self.hedge_delay = float(cfg_get(
            config, "origins.hedge_delay", 1.0
        ))

    def _ordered(self) -> List[Origin]:
        live = [o for o in self.origins if not o.dead]
        return sorted(live, key=lambda o: -self.health.bps(o.label))

    def _blocked(self, origin: Origin) -> bool:
        breakers = getattr(self.retrier, "breakers", None)
        if breakers is None or not breakers.enabled:
            return False
        breaker = breakers.get(f"origin:{origin.label}")
        return breaker is not None and breaker.blocking

    async def fetch(self, fetch_one: Callable, *, what: str = "") -> int:
        """Run ``fetch_one(origin, hedge_s)`` against the best origin,
        failing over in EWMA order; returns its result (bytes landed).
        ``hedge_s`` is 0 for the LAST candidate — with nobody left to
        hedge toward, the caller should wait the full stall budget."""
        last_err: Optional[BaseException] = None
        candidates = self._ordered()
        usable = [o for o in candidates if not self._blocked(o)]
        if candidates and not usable:
            # every origin's breaker is open: surface BreakerOpen (the
            # park-without-poison posture, same as the racing path) —
            # a bare error here would charge the poison budget for a
            # condition the breakers already promise will heal
            best = candidates[0]
            breaker = self.retrier.breakers.get(f"origin:{best.label}")
            raise BreakerOpen(f"origin:{best.label}",
                              breaker.retry_after())
        for index, origin in enumerate(usable):
            hedge = (self.hedge_delay
                     if index < len(usable) - 1 else 0.0)
            started = time.monotonic()
            try:
                moved = await self.retrier.run(
                    f"origin:{origin.label}.segment",
                    lambda: fetch_one(origin, hedge),
                    cancel=self.cancel, record=self.record,
                    logger=self.logger,
                )
            except (asyncio.CancelledError, JobCancelled):
                raise
            except Exception as err:
                if getattr(type(err), "code", None) == "ERRDLSTALL":
                    raise
                last_err = err
                if self.record is not None:
                    self.record.event("origin_failover",
                                      origin=origin.label, what=what,
                                      error=str(err)[:160],
                                      type=type(err).__name__)
                if self.logger is not None:
                    self.logger.warn("segment origin failed over",
                                     origin=origin.label, what=what,
                                     error=str(err)[:200])
                continue
            self.health.feed(origin.label, moved,
                             time.monotonic() - started)
            if self.metrics is not None and moved:
                self.metrics.origin_bytes.labels(
                    origin=origin.label
                ).inc(moved)
            return moved
        if last_err is not None:
            raise last_err
        raise RuntimeError(
            f"no usable origin for {what or 'segment'}: "
            "every origin dead or breaker-open"
        )
