"""Origin identity + cross-job origin health (EWMA throughput).

An *origin* is one URL serving the entity (the primary
``Media.source_uri`` or a ``Download.mirrors`` entry).  Everything the
fleet keys on an origin — metrics labels, breaker/retry dependency
names, the health table — uses :func:`origin_label`, which is the URL's
host[:port] **bounded** to ``origins.max_labels`` distinct values per
process (overflow collapses to ``"other"``): origin names arrive in job
payloads, and unbounded label cardinality would let submitters mint
Prometheus series and breaker instances at will — the same posture the
tenant table takes with unconfigured tenant names.
"""

from __future__ import annotations

import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..platform.config import cfg_get

DEFAULT_MAX_LABELS = 16
# EWMA smoothing for per-origin throughput samples: high enough to react
# within a few ranges, low enough that one cold TCP slow-start sample
# doesn't erase an origin's history
EWMA_ALPHA = 0.3
OVERFLOW_LABEL = "other"


def origin_label(url: str) -> str:
    """The unbounded raw label for one origin URL: host[:port], with
    dots flattened to dashes — the label rides inside dotted dependency
    seams (``origin:<label>.fetch``) and dotted config paths
    (``breakers.origin:<label>.threshold``), where a literal dot would
    split the host and silently collapse distinct origins onto one
    breaker."""
    try:
        parsed = urllib.parse.urlsplit(url)
        host = (parsed.hostname or "").replace(".", "-")
        if parsed.port:
            return f"{host}:{parsed.port}"
        return host or OVERFLOW_LABEL
    except ValueError:
        return OVERFLOW_LABEL


@dataclass
class Origin:
    """One member of a job's origin set."""

    url: str
    label: str
    primary: bool = False
    # per-JOB liveness: a dead origin is skipped for the rest of the job
    # (its breaker + health table remember it across jobs)
    dead: bool = False
    failures: int = field(default=0, compare=False)


class OriginHealth:
    """Cross-job per-origin throughput EWMA + the bounded label table.

    Fed from the racing fetch's per-chunk progress hook — the same
    observation points that bill the hop ledger — so ``bps(label)`` is
    the observed landing rate, not a request-level guess.  Shared across
    jobs via ``ctx.resources`` (one instance per service), like the
    content cache and the retrier.
    """

    def __init__(self, max_labels: int = DEFAULT_MAX_LABELS):
        self.max_labels = max(int(max_labels), 1)
        # label -> [ewma_bps, total_bytes, last_feed_mono]
        self._table: Dict[str, list] = {}
        self._labels: set = set()

    @classmethod
    def shared(cls, resources: dict, config=None) -> "OriginHealth":
        health = resources.get("origin_health")
        if health is None:
            health = cls(max_labels=int(cfg_get(
                config, "origins.max_labels", DEFAULT_MAX_LABELS
            )))
            resources["origin_health"] = health
        return health

    def label(self, url: str) -> str:
        """Bounded label for ``url`` (stable for the process lifetime)."""
        raw = origin_label(url)
        if raw in self._labels:
            return raw
        if len(self._labels) >= self.max_labels:
            return OVERFLOW_LABEL
        self._labels.add(raw)
        return raw

    def feed(self, label: str, nbytes: int, seconds: float) -> None:
        """One throughput sample: ``nbytes`` landed over ``seconds``."""
        if seconds <= 0 or nbytes < 0:
            return
        rate = nbytes / seconds
        entry = self._table.get(label)
        if entry is None:
            self._table[label] = [rate, nbytes, time.monotonic()]
            return
        entry[0] += EWMA_ALPHA * (rate - entry[0])
        entry[1] += nbytes
        entry[2] = time.monotonic()

    def bps(self, label: str) -> float:
        """EWMA landing rate for ``label`` (0.0 = never observed)."""
        entry = self._table.get(label)
        return entry[0] if entry is not None else 0.0

    def total_bytes(self, label: str) -> int:
        entry = self._table.get(label)
        return int(entry[1]) if entry is not None else 0

    def snapshot(self) -> Dict[str, dict]:
        """label -> {bps, bytes} for logs/debug surfaces."""
        return {
            label: {"bps": round(entry[0], 1), "bytes": int(entry[1])}
            for label, entry in sorted(self._table.items())
        }

    def seed(self, rows: Dict[str, dict]) -> int:
        """Import fleet-shared rows (fleet/plane.py origin-health table)
        for labels this process has NOT yet observed itself — a peer's
        EWMA is a cold-start head start, never an override of local
        evidence.  ``bytes`` stays 0: total_bytes accounts bytes THIS
        worker moved.  Returns the number of labels seeded."""
        seeded = 0
        for label, row in rows.items():
            if not isinstance(label, str) or label in self._table:
                continue
            if (label not in self._labels
                    and len(self._labels) >= self.max_labels):
                continue  # the bounded label table stays bounded
            try:
                bps = float(row.get("bps", 0.0) or 0.0)
            except (TypeError, ValueError, AttributeError):
                continue
            if bps <= 0:
                continue
            self._table[label] = [bps, 0, time.monotonic()]
            self._labels.add(label)
            seeded += 1
        return seeded


def resolve_mirrors(primary_url: str, mirrors,
                    schemes=("http", "https")) -> List[str]:
    """The usable mirror URLs for one job: scheme-filtered, de-duplicated
    against the primary and each other, order preserved (submitters list
    their preferred mirrors first)."""
    seen = {primary_url}
    out: List[str] = []
    for url in mirrors or ():
        if not isinstance(url, str) or url in seen:
            continue
        try:
            scheme = urllib.parse.urlsplit(url).scheme.lower()
        except ValueError:
            continue
        if scheme not in schemes:
            continue
        seen.add(url)
        out.append(url)
    return out


def build_origin_set(primary_url: str, mirrors,
                     health: Optional[OriginHealth] = None) -> List[Origin]:
    """Primary + usable mirrors as :class:`Origin` records (primary
    always first; labels bounded through ``health`` when given)."""
    labeler = health.label if health is not None else origin_label
    origins = [Origin(url=primary_url, label=labeler(primary_url),
                      primary=True)]
    for url in resolve_mirrors(primary_url, mirrors):
        origins.append(Origin(url=url, label=labeler(url)))
    return origins
