"""io_uring spike for the segmented chunk-landing loop (ISSUE 19).

A minimal, dependency-free (ctypes + raw syscalls) io_uring ring that
replaces the one-``pwrite``-syscall-per-chunk landing discipline with a
kernel submission ring.  Scope is deliberately a *spike*:

- synchronous submit-one/wait-one semantics, byte-identical to
  ``os.pwrite`` (the caller — the segmented download's single writer
  thread — sees the same blocking contract);
- ``available()`` probes once per process and memoizes, so a kernel
  without io_uring, a seccomp-filtered container, or a locked-down
  ``io_uring_disabled`` sysctl all degrade silently to ``os.pwrite``;
- opt-in via the ``download.io_uring`` knob (default off) — the knob
  turns the probe on, the probe turns the ring on.

The synchronous pattern leans on the syscall boundary itself as the
memory barrier: our SQ-tail store happens-before ``io_uring_enter``,
and the CQE read happens-after it returns with ``GETEVENTS`` — no
atomics needed from Python.  Single-threaded by contract (one ring per
writer thread; the landing path owns exactly one).
"""

from __future__ import annotations

import ctypes
import errno
import mmap
import os
import struct
import sys
import threading

# x86_64 and aarch64 share these io_uring syscall numbers
_NR_IO_URING_SETUP = 425
_NR_IO_URING_ENTER = 426

_IORING_OFF_SQ_RING = 0
_IORING_OFF_SQES = 0x10000000

_IORING_ENTER_GETEVENTS = 1
_IORING_FEAT_SINGLE_MMAP = 1
_IORING_OP_WRITE = 23

_SQE_SIZE = 64
_CQE_SIZE = 16

_libc = None


def _lib():
    global _libc
    if _libc is None:
        _libc = ctypes.CDLL(None, use_errno=True)
        _libc.syscall.restype = ctypes.c_long
    return _libc


class _SqOffsets(ctypes.Structure):
    _fields_ = [
        ("head", ctypes.c_uint32),
        ("tail", ctypes.c_uint32),
        ("ring_mask", ctypes.c_uint32),
        ("ring_entries", ctypes.c_uint32),
        ("flags", ctypes.c_uint32),
        ("dropped", ctypes.c_uint32),
        ("array", ctypes.c_uint32),
        ("resv1", ctypes.c_uint32),
        ("user_addr", ctypes.c_uint64),
    ]


class _CqOffsets(ctypes.Structure):
    _fields_ = [
        ("head", ctypes.c_uint32),
        ("tail", ctypes.c_uint32),
        ("ring_mask", ctypes.c_uint32),
        ("ring_entries", ctypes.c_uint32),
        ("overflow", ctypes.c_uint32),
        ("cqes", ctypes.c_uint32),
        ("flags", ctypes.c_uint32),
        ("resv1", ctypes.c_uint32),
        ("user_addr", ctypes.c_uint64),
    ]


class _UringParams(ctypes.Structure):
    _fields_ = [
        ("sq_entries", ctypes.c_uint32),
        ("cq_entries", ctypes.c_uint32),
        ("flags", ctypes.c_uint32),
        ("sq_thread_cpu", ctypes.c_uint32),
        ("sq_thread_idle", ctypes.c_uint32),
        ("features", ctypes.c_uint32),
        ("wq_fd", ctypes.c_uint32),
        ("resv", ctypes.c_uint32 * 3),
        ("sq_off", _SqOffsets),
        ("cq_off", _CqOffsets),
    ]


def _setup(entries: int, params: _UringParams) -> int:
    res = _lib().syscall(
        ctypes.c_long(_NR_IO_URING_SETUP),
        ctypes.c_long(entries),
        ctypes.byref(params),
    )
    if res < 0:
        err = ctypes.get_errno()
        raise OSError(err, os.strerror(err))
    return int(res)


def _enter(ring_fd: int, to_submit: int, min_complete: int,
           flags: int) -> int:
    while True:
        res = _lib().syscall(
            ctypes.c_long(_NR_IO_URING_ENTER),
            ctypes.c_long(ring_fd),
            ctypes.c_long(to_submit),
            ctypes.c_long(min_complete),
            ctypes.c_long(flags),
            ctypes.c_void_p(0),
            ctypes.c_long(0),
        )
        if res >= 0:
            return int(res)
        err = ctypes.get_errno()
        if err == errno.EINTR:
            continue
        raise OSError(err, os.strerror(err))


class UringWriter:
    """One io_uring ring exposing a blocking ``pwrite`` equivalent."""

    def __init__(self, entries: int = 8):
        self._fd = -1
        self._ring = None
        self._sqes = None
        if not sys.platform.startswith("linux"):
            raise RuntimeError("io_uring: linux only")
        try:
            params = _UringParams()
            self._fd = _setup(entries, params)
            if not params.features & _IORING_FEAT_SINGLE_MMAP:
                # pre-5.4 two-mapping rings aren't worth supporting in
                # a spike: such kernels predate usable io_uring anyway
                raise RuntimeError("io_uring: kernel lacks single mmap")
            sq_size = params.sq_off.array + params.sq_entries * 4
            cq_size = params.cq_off.cqes + params.cq_entries * _CQE_SIZE
            flags = mmap.MAP_SHARED | getattr(mmap, "MAP_POPULATE", 0)
            self._ring = mmap.mmap(
                self._fd, max(sq_size, cq_size), flags=flags,
                prot=mmap.PROT_READ | mmap.PROT_WRITE,
                offset=_IORING_OFF_SQ_RING,
            )
            self._sqes = mmap.mmap(
                self._fd, params.sq_entries * _SQE_SIZE, flags=flags,
                prot=mmap.PROT_READ | mmap.PROT_WRITE,
                offset=_IORING_OFF_SQES,
            )
            off = params.sq_off
            self._sq_tail = off.tail
            self._sq_array = off.array
            self._sq_mask = struct.unpack_from(
                "<I", self._ring, off.ring_mask)[0]
            coff = params.cq_off
            self._cq_head = coff.head
            self._cq_tail = coff.tail
            self._cq_cqes = coff.cqes
            self._cq_mask = struct.unpack_from(
                "<I", self._ring, coff.ring_mask)[0]
        except BaseException:
            self.close()
            raise

    def pwrite(self, fd: int, data, offset: int) -> int:
        """``os.pwrite(fd, data, offset)`` through the ring.

        Submits IORING_OP_WRITE and waits for its completion before
        returning.  A degraded completion — an error CQE (e.g. ``-EIO``
        from a ring the kernel has soured on this fd) or a short/zero
        write — does NOT re-drive the ring: the remainder lands through
        one plain ``os.pwrite`` loop at the resumed offset, so the
        buffer is landed exactly once at exactly the right bytes and a
        sick ring never gets a second chance to corrupt the landing.
        Errors that are real disk errors (ENOSPC, hard EIO) reproduce
        in the fallback and surface with their ordinary errno.
        """
        if not isinstance(data, bytes):
            data = bytes(data)
        # c_char_p pins the bytes object's own buffer — no copy; the
        # reference (and hence the address) outlives the synchronous
        # submit/complete round-trip below
        ref = ctypes.c_char_p(data)
        addr = ctypes.cast(ref, ctypes.c_void_p).value or 0
        total, length = 0, len(data)
        while total < length:
            res = self._submit_write(
                fd, addr + total, length - total, offset + total)
            if res == length - total:
                total += res
                continue
            if res > 0:
                total += res
            total = self._pwrite_fallback(fd, data, offset, total)
            break
        del ref
        return total

    @staticmethod
    def _pwrite_fallback(fd: int, data: bytes, offset: int,
                         total: int) -> int:
        """Finish ``data[total:]`` with plain ``pwrite`` at the resumed
        offset (through the vfs shim, so disk drills still apply)."""
        from ..platform import vfs

        length = len(data)
        while total < length:
            n = vfs.pwrite(fd, memoryview(data)[total:], offset + total,
                           thread_ok=True)
            if n <= 0:
                raise OSError(errno.EIO, "pwrite fallback: zero-byte write")
            total += n
        return total

    def _submit_write(self, fd: int, addr: int, length: int,
                      offset: int) -> int:
        ring, sqes = self._ring, self._sqes
        tail = struct.unpack_from("<I", ring, self._sq_tail)[0]
        idx = tail & self._sq_mask
        base = idx * _SQE_SIZE
        sqes[base:base + _SQE_SIZE] = b"\x00" * _SQE_SIZE
        # opcode, flags, ioprio, fd, off, addr, len, rw_flags, user_data
        struct.pack_into(
            "<BBHiQQIIQ", sqes, base,
            _IORING_OP_WRITE, 0, 0, fd, offset, addr, length, 0, tail,
        )
        struct.pack_into("<I", ring, self._sq_array + idx * 4, idx)
        struct.pack_into("<I", ring, self._sq_tail, tail + 1)
        _enter(self._fd, 1, 1, _IORING_ENTER_GETEVENTS)
        head = struct.unpack_from("<I", ring, self._cq_head)[0]
        cq_tail = struct.unpack_from("<I", ring, self._cq_tail)[0]
        if head == cq_tail:
            raise RuntimeError("io_uring: enter returned without CQE")
        cqe = self._cq_cqes + (head & self._cq_mask) * _CQE_SIZE
        _user_data, res, _flags = struct.unpack_from("<QiI", ring, cqe)
        struct.pack_into("<I", ring, self._cq_head, head + 1)
        return res

    def close(self) -> None:
        for name in ("_sqes", "_ring"):
            mm = getattr(self, name, None)
            if mm is not None:
                try:
                    mm.close()
                except (BufferError, ValueError):
                    pass
                setattr(self, name, None)
        if self._fd >= 0:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = -1

    def __enter__(self) -> "UringWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_probe_lock = threading.Lock()
_probe: "bool | None" = None


def available() -> bool:
    """True when this kernel/container lets us build and drive a ring.

    Probed once per process with a tiny ring and a real 1-byte write to
    an unlinked temp file — ``io_uring_setup`` succeeding is NOT enough
    (seccomp policies commonly allow setup but kill/deny ``enter``).
    """
    global _probe
    with _probe_lock:
        if _probe is None:
            _probe = _probe_ring()
        return _probe


def _probe_ring() -> bool:
    import tempfile

    try:
        with UringWriter(entries=2) as writer:
            with tempfile.TemporaryFile() as fh:
                if writer.pwrite(fh.fileno(), b"\x00", 0) != 1:
                    return False
                fh.seek(0)
                return fh.read(1) == b"\x00"
    except (OSError, RuntimeError, ValueError, AttributeError):
        return False
