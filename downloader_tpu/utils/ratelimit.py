"""Async token-bucket rate limiting.

Beyond-reference production knob: the reference downloads at whatever the
NIC allows (webtorrent/request have no caps wired up,
/root/reference/lib/download.js), which on a shared media host starves
co-tenant services.  One bucket is shared across all of a service's
transfers, so the cap is per-process, not per-job.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional


class TokenBucket:
    """Classic token bucket: sustained ``rate`` bytes/s, bursts up to
    ``burst`` bytes (default: one second's worth).

    ``consume(n)`` deducts immediately and sleeps off any deficit, which
    paces the *average* rate without chunk-size-dependent stalls: a 1 MiB
    chunk against a 64 KiB/s cap sleeps ~16 s once instead of deadlocking
    on an undersized bucket.
    """

    def __init__(self, rate: float, burst: Optional[float] = None):
        if rate <= 0:
            raise ValueError(f"rate must be positive, got {rate}")
        self.rate = float(rate)
        self.capacity = float(burst if burst is not None else rate)
        self.tokens = self.capacity
        self.updated = time.monotonic()
        self._lock = asyncio.Lock()

    async def consume(self, n: int) -> None:
        if n <= 0:
            return
        async with self._lock:
            now = time.monotonic()
            self.tokens = min(
                self.capacity, self.tokens + (now - self.updated) * self.rate
            )
            self.updated = now
            self.tokens -= n
            deficit = -self.tokens
        if deficit > 0:
            await asyncio.sleep(deficit / self.rate)


class ChainedLimiter:
    """Serial composition of token buckets: a transfer must clear EVERY
    bucket in the chain, so the effective rate is the minimum of the
    chained caps.  Used to stack a per-tenant byte quota
    (control/tenancy.py) on top of the per-service limiter without the
    stages knowing which (if either) is configured.
    """

    def __init__(self, *buckets: Optional[TokenBucket]):
        self.buckets = [b for b in buckets if b is not None]

    async def consume(self, n: int) -> None:
        for bucket in self.buckets:
            await bucket.consume(n)


def chain_limiters(*buckets) -> Optional[object]:
    """Compose limiters, eliding absent ones: None when nothing is
    configured, the single bucket when only one is, else a chain."""
    live = [b for b in buckets if b is not None]
    if not live:
        return None
    if len(live) == 1:
        return live[0]
    return ChainedLimiter(*live)


def bucket_from_config(config, key: str) -> Optional[TokenBucket]:
    """Build a bucket from ``config.instance.<key>`` (bytes/s; absent,
    empty, or 0 disables limiting).

    A malformed or negative value raises instead of silently running
    uncapped — an operator who set a cap must not get unlimited ingress
    because of a typo like ``"128k"``.
    """
    from ..platform.config import cfg_get

    raw = cfg_get(config, f"instance.{key}", None)
    if raw in (None, "", 0):
        return None
    try:
        rate = float(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"config instance.{key}={raw!r} is not a number of bytes/s"
        ) from None
    if rate < 0:
        raise ValueError(f"config instance.{key}={raw!r} must be >= 0")
    if rate == 0:
        return None
    return TokenBucket(rate)


def shared_bucket(resources: dict, config, key: str) -> Optional[TokenBucket]:
    """Per-SERVICE bucket memoized in the cross-job ``resources`` dict.

    Stage factories run once per job, so a bucket built inline there
    would be per-job — N concurrent jobs would each get the full rate,
    multiplying the configured cap by the concurrency.  Memoizing under
    the orchestrator's shared ``stage_resources`` makes the cap genuinely
    per instance (standalone stage use, with a fresh resources dict per
    context, degrades to per-context — the same scope as before).
    """
    cache_key = f"rate_limiter:{key}"
    if cache_key not in resources:
        resources[cache_key] = bucket_from_config(config, key)
    return resources[cache_key]
