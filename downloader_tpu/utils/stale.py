"""Shared reclaim policy for pid-named temp files.

Two subsystems write ``<dst>.<marker>-<pid>.<seq>`` temps that a SIGKILL
can orphan: the fs object store's ingest temps (``store/fs.py``) and the
transcoder's part-files (``compute/transcode.py``).  Both need the same
three-way judgement, kept here so a policy tuning lands in one place:

- the pid probes **live locally** -> not stale (a concurrent writer owns
  the rename race);
- the temp is **younger than the grace** -> not stale even with a dead
  pid, because over NFS the pid probe is host-local and a sibling host's
  in-flight writer would read as dead here;
- the probe is **inconclusive** (EPERM: recycled pid under another uid;
  OverflowError: pid field beyond pid_t) -> stale only past a day-scale
  max age, when no real writer could still be running.
"""

from __future__ import annotations

import os
import re
import time
from typing import Optional, Tuple

STALE_GRACE_S = 300.0
STALE_MAX_AGE_S = 24 * 3600.0

# the transcoder's part-file naming (the seq group is optional so temps
# from the short-lived earlier naming, .part-<pid><ext> with no counter,
# are still reclaimable).  Lives here, not in compute/, because the
# process stage's media walk must skip these without importing the
# compute subsystem (the staging pipeline never imports JAX).
PART_TEMP_RE = re.compile(r"\.part-(\d+)(?:\.\d+)?(\.[^.]+)?$")

# what the media walk skips: ONLY the full two-number form the
# transcoder actually writes (.part-<pid>.<seq><ext>).  The lenient
# pattern above is safe for reclaim because its glob is anchored to a
# known dst, but in a walk it would also swallow legitimate content
# named like "Movie.part-2.mkv" (review r5).
PART_TEMP_STRICT_RE = re.compile(r"\.part-(\d+)\.(\d+)(\.[^.]+)?$")


def probe_stale(path: str, pid: int, *,
                grace: float = STALE_GRACE_S,
                max_age: float = STALE_MAX_AGE_S,
                ) -> Tuple[bool, Optional[float]]:
    """Judge one temp: returns ``(stale, age_seconds)``.

    ``age`` is None when the file vanished under us (concurrent
    replace/reclaim — never stale).  ``stale=False`` with a large age
    means the pid probes live: either a genuine long-running writer or a
    foreign file whose pid field happens to collide (the fs store logs
    the latter).
    """
    try:
        age = time.time() - os.stat(path).st_mtime
    except OSError:
        return False, None
    if age < grace:
        return False, age
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True, age
    except (OSError, OverflowError):
        return age > max_age, age  # inconclusive probe
    return False, age  # provably live local writer
