"""Small shared utilities."""

from .events import EventEmitter

__all__ = ["EventEmitter"]
