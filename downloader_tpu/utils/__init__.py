"""Small shared utilities."""

import datetime

from .events import EventEmitter

__all__ = ["EventEmitter", "utcnow_iso"]


def utcnow_iso() -> str:
    """Millisecond UTC timestamp with a ``Z`` suffix — the one format
    used for ``Convert.created_at`` wire timestamps and control-plane
    job records (a single definition so they can never diverge)."""
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="milliseconds")
        .replace("+00:00", "Z")
    )
