"""Runtime compatibility backports.

The codebase targets Python 3.11+ (``asyncio.timeout`` is used at ~60
call sites across the orchestrator, broker, and torrent stack), but
deployment images sometimes pin 3.10.  Rather than fork every call
site, :func:`install` backports the missing pieces onto the stdlib
module once, at package import (``downloader_tpu/__init__.py``) — a
no-op on 3.11+.

The backported ``timeout`` implements the contract the repo relies on:
a cancellation raised BY the timeout surfaces as builtin
``TimeoutError`` at the ``async with`` exit; an external cancellation
passes through untouched.  The 3.11 ``Task.uncancel`` bookkeeping has
no 3.10 equivalent, so a timeout firing in the same tick as an external
cancel resolves in the timeout's favor — acceptable for the drain/join
loops and test deadlines this repo uses it for.
"""

from __future__ import annotations

import asyncio


class _Timeout:
    __slots__ = ("_delay", "_task", "_handle", "_expired")

    def __init__(self, delay):
        self._delay = delay
        self._task = None
        self._handle = None
        self._expired = False

    async def __aenter__(self):
        self._task = asyncio.current_task()
        if self._delay is not None:
            loop = asyncio.get_running_loop()
            self._handle = loop.call_later(self._delay, self._fire)
        return self

    def _fire(self) -> None:
        self._expired = True
        if self._task is not None:
            self._task.cancel()

    async def __aexit__(self, exc_type, exc, tb):
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if self._expired and exc_type is asyncio.CancelledError:
            raise TimeoutError from exc
        return False


def _timeout(delay):
    """3.10 backport of :func:`asyncio.timeout` (see module docstring)."""
    return _Timeout(delay)


def install() -> None:
    """Install the backports onto :mod:`asyncio`; no-op when present."""
    if not hasattr(asyncio, "timeout"):
        asyncio.timeout = _timeout
