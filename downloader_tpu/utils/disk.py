"""Disk-space preflight shared by the download paths.

Losing a multi-GB transfer to ENOSPC at the tail is the worst way to
find out the volume is small — both the HTTP and torrent fetch paths
check up front and fail with a clear, actionable error instead.  The
``needed`` figure must already credit resumable bytes on disk (each
caller knows its own resume accounting); for sparse preallocated files
use :func:`allocated_bytes`, not ``st_size`` — a sparse truncate makes
apparent size lie about what the volume actually holds.
"""

from __future__ import annotations

import os
import shutil


class InsufficientDiskSpace(OSError):
    """The target volume cannot hold the remaining transfer."""


def allocated_bytes(path: str) -> int:
    """Bytes actually backed by the volume (``st_blocks``), clamped to
    apparent size — sparse preallocation inflates ``st_size`` without
    consuming space, and filesystem metadata can inflate ``st_blocks``
    past the data."""
    try:
        st = os.stat(path)
    except OSError:
        return 0
    blocks = getattr(st, "st_blocks", None)  # absent on e.g. Windows
    if blocks is None:
        return st.st_size
    return min(blocks * 512, st.st_size)


def dir_bytes(dirpath: str) -> int:
    """Total bytes actually backed by the volume under ``dirpath``
    (recursive; 0 for a missing dir).  Uses :func:`allocated_bytes` per
    file so sparse preallocated transfers report what they really hold —
    the per-tenant staging-footprint gauge feeds off this."""
    total = 0
    try:
        entries = os.scandir(dirpath)
    except OSError:
        return 0
    with entries:
        for entry in entries:
            try:
                if entry.is_dir(follow_symlinks=False):
                    total += dir_bytes(entry.path)
                elif entry.is_file(follow_symlinks=False):
                    total += allocated_bytes(entry.path)
            except OSError:
                continue
    return total


def free_bytes(dirpath: str) -> int:
    """Free bytes on ``dirpath``'s volume; 0 when the path is unstatable
    (callers treat that as "no headroom" rather than crashing)."""
    try:
        return shutil.disk_usage(dirpath).free
    except OSError:
        return 0


def ensure_disk_space(dirpath: str, needed: int) -> None:
    """Raise :class:`InsufficientDiskSpace` unless ``dirpath``'s volume
    has ``needed`` bytes free."""
    # fault-injection seam (platform/faults.py): "disk full during
    # staging" drills inject here instead of actually filling the volume
    from ..platform import faults

    if faults.enabled():
        faults.fire_sync("disk.preflight", key=dirpath)
    if needed <= 0:
        return
    free = shutil.disk_usage(dirpath).free
    if needed > free:
        raise InsufficientDiskSpace(
            f"insufficient disk space: download needs {needed} more "
            f"bytes, volume has {free} free"
        )
