"""Download stall detection.

The reference arms two timers around a torrent download: a 240 s metadata
timeout (/root/reference/lib/download.js:21,47-50) and a 240 s no-progress
watchdog that rejects with ``err.code = 'ERRDLSTALL'``
(lib/download.js:90-101).  The orchestrator treats that code as permanent —
ack and drop the job (lib/main.js:144-146).

Here the watchdog is a reusable primitive any transfer can feed.
"""

from __future__ import annotations

import asyncio
from typing import Optional

# Parity constant (reference lib/download.js:21).
STALL_TIMEOUT_SECONDS = 240.0


class DownloadStalledError(Exception):
    """A transfer made no progress for a full watchdog window.

    Carries ``code == 'ERRDLSTALL'`` like the reference error object so the
    orchestrator's drop-vs-retry policy can key on it."""

    code = "ERRDLSTALL"

    def __init__(self, message: str = "Download stalled."):
        super().__init__(message)


class MetadataTimeoutError(Exception):
    """Metadata (or first byte) never arrived within the window
    (reference 'Metadata fetch stalled', lib/download.js:49)."""


class StallWatchdog:
    """Monitors a monotonically-increasing progress value.

    Call :meth:`feed` with the latest progress; :meth:`watch` wraps a
    coroutine and raises :class:`DownloadStalledError` if progress is flat
    across a full ``timeout`` window — same check the reference does by
    comparing ``progress === lastProgress`` every 240 s
    (lib/download.js:92-100).
    """

    def __init__(self, timeout: float = STALL_TIMEOUT_SECONDS,
                 on_feed=None):
        self.timeout = timeout
        # optional per-feed tap: every transfer loop already feeds the
        # watchdog its cumulative byte count, which makes this the one
        # cheap place to mirror live progress into the job's
        # control-plane record (flight-recorder throughput sampling)
        # without touching each chunk loop
        self._on_feed = on_feed
        self._progress: Optional[float] = None

    def feed(self, progress: float) -> None:
        self._progress = progress
        if self._on_feed is not None:
            self._on_feed(progress)

    async def watch(self, coro):
        task = asyncio.ensure_future(coro)
        try:
            last: Optional[float] = None
            while True:
                done, _pending = await asyncio.wait({task}, timeout=self.timeout)
                if done:
                    return task.result()
                if self._progress == last:
                    raise DownloadStalledError()
                last = self._progress
        finally:
            if not task.done():
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
            else:
                # unwinding abnormally (external cancel) with the inner
                # task already settled: retrieve its exception so a
                # simultaneous inner error (e.g. a cooperative
                # JobCancelled racing the cancel) isn't logged as a
                # never-retrieved task exception
                try:
                    task.exception()
                except (asyncio.CancelledError, Exception):
                    pass
