"""A minimal Node-style event emitter.

The reference creates one ``EventEmitter`` per job, registers it in an
``EmitterTable`` keyed by file id, and passes it to every stage factory
(/root/reference/lib/main.js:26,81,103); the orchestrator emits ``progress``
after each stage (lib/main.js:139).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, DefaultDict, List


class EventEmitter:
    def __init__(self) -> None:
        self._listeners: DefaultDict[str, List[Callable]] = collections.defaultdict(list)

    def on(self, event: str, listener: Callable) -> Callable:
        self._listeners[event].append(listener)
        return listener

    def off(self, event: str, listener: Callable) -> None:
        try:
            self._listeners[event].remove(listener)
        except ValueError:
            pass

    def emit(self, event: str, *args: Any) -> bool:
        listeners = list(self._listeners.get(event, ()))
        for listener in listeners:
            listener(*args)
        return bool(listeners)
