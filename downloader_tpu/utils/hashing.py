"""Shared file-hashing helpers.

Both the upload stage's resume probe and the filesystem store's etag
computation must produce identical digests — the resume check compares
one against the other — so they share this single implementation.
"""

from __future__ import annotations

import hashlib

_CHUNK = 1 << 20  # 1 MiB


def md5_file_hex(path: str) -> str:
    """Chunked MD5 of a file, as the lowercase hex S3-style etag."""
    digest = hashlib.md5()
    with open(path, "rb") as fh:
        while chunk := fh.read(_CHUNK):
            digest.update(chunk)
    return digest.hexdigest()
