"""Shared file-hashing helpers.

Both the upload stage's resume probe and the filesystem store's etag
computation must produce identical digests — the resume check compares
one against the other — so they share this single implementation.
"""

from __future__ import annotations

import hashlib

_CHUNK = 1 << 20  # 1 MiB


def md5_file_hex(path: str) -> str:
    """Chunked MD5 of a file, as the lowercase hex S3-style etag."""
    digest = hashlib.md5()
    with open(path, "rb") as fh:
        while chunk := fh.read(_CHUNK):
            digest.update(chunk)
    return digest.hexdigest()


def multipart_etag_hex(path: str, part_size: int) -> str:
    """The S3 multipart ETag for a file at a given part size:
    ``md5(concat(md5(part_i)))-N`` — verifiable locally, so the upload
    stage's resume guard works for multipart objects too."""
    digests = []
    with open(path, "rb") as fh:
        while True:
            part = hashlib.md5()
            remaining = part_size
            got = 0
            while remaining > 0:
                chunk = fh.read(min(_CHUNK, remaining))
                if not chunk:
                    break
                part.update(chunk)
                got += len(chunk)
                remaining -= len(chunk)
            if got == 0:
                break
            digests.append(part.digest())
    combined = hashlib.md5(b"".join(digests)).hexdigest()
    return f"{combined}-{len(digests)}"
