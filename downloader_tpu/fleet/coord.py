"""Coordination store: the fleet's tiny shared key/value substrate.

Every fleet feature — worker liveness, cross-worker leases, shared-tier
manifests — reduces to one primitive: a small JSON document at a key,
written with *conditional-put* semantics (create-if-absent or
compare-and-swap on a write token).  This module provides that primitive
behind :class:`CoordStore` with two backends:

- :class:`MemoryCoordStore` — an in-process dict with truly atomic
  conditional puts.  Tests and single-host multi-orchestrator benches
  share one instance between workers; it is also the hermetic default
  for ``fleet.backend: memory``.
- :class:`BucketCoordStore` — documents stored as objects in the staging
  bucket (default prefix ``.fleet/``), so a fleet needs no coordination
  service beyond the object store it already depends on (the same
  posture as the idempotency marker).  Object stores are last-write-wins,
  so the conditional put is *best-effort*: each write embeds a fresh
  nonce and is verified by reading the key back — the standard
  S3-lock discipline.  A lost race is detected (the read-back shows a
  foreign nonce) in all but a sub-RTT window; the lease layer bounds the
  damage of that window to one duplicate download, and the shared tier's
  manifest-last publish keeps correctness unconditional.

Deletes are tombstones on the bucket backend (the :class:`~..store.base.
ObjectStore` interface has no remove): a deleted key reads as absent and
may be recreated with ``expect=ABSENT``.

Failure posture: every backend error surfaces as :class:`CoordError`
(TRANSIENT under the platform taxonomy).  Callers — the fleet plane —
must treat coordination trouble as *degradation to uncoordinated
operation*, never as job failure: a worker that cannot reach the
coordination store downloads like a pre-fleet worker.  All operations
carry ``coord.*`` fault-injection seams (platform/faults.py) so chaos
plans can blip exactly this dependency.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import itertools
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from ..platform import faults
from ..platform.errors import TRANSIENT
from ..stages.upload import STAGING_BUCKET
from ..store.base import ObjectNotFound

# sentinel for "the key must not exist" conditional puts
ABSENT = "__absent__"
# sentinel for unconditional writes
ANY = "__any__"


class CoordError(RuntimeError):
    """The coordination store could not answer (TRANSIENT: the fleet
    degrades to uncoordinated operation, jobs never fail on this)."""

    fault_class = TRANSIENT


@dataclasses.dataclass(frozen=True)
class WatchEvent:
    """One observed change under a watched prefix.

    ``data is None`` means the key went away (deleted / tombstoned);
    otherwise ``data``/``token`` are the entry's new value and write
    token, exactly what a ``get`` at that instant would have returned.
    """

    key: str
    data: Optional[dict]
    token: Optional[str]


class CoordWatch:
    """Subscription handle returned by :meth:`CoordStore.watch`.

    Etcd-shaped semantics scaled down to this substrate: ``next(
    timeout)`` blocks until something under the prefix changes and
    returns the batched events, or ``[]`` when the timeout lapses with
    nothing new — a *bounded* long-poll, never an unbounded hang, so
    callers' wait budgets stay enforceable.  ``next(0)`` is a
    non-blocking drain.  Store trouble surfaces as :class:`CoordError`
    exactly like the reads a watch replaces; per the degradation
    contract callers fall back to their sleep-poll loop and keep
    working.  ``close()`` detaches the watch; a closed watch returns
    ``[]`` forever.
    """

    prefix: str = ""

    async def next(self, timeout: float) -> List[WatchEvent]:
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


class _PollWatch(CoordWatch):
    """Snapshot-diff bounded long-poll: the universal fallback watch.

    Works against any :class:`CoordStore` (one ``list_keys`` + one
    ``get`` per live key per lap), which makes it the degraded path the
    event-driven backends fall back to — and the only path on backends
    with no native change feed (the bucket stores).  The first ``next``
    seeds the snapshot silently: a watch reports *changes after it was
    opened*, not pre-existing state (callers read current state with
    ``get`` before watching, the standard read-then-watch pattern).
    """

    def __init__(self, store: "CoordStore", prefix: str,
                 interval: float = 0.25):
        self.store = store
        self.prefix = prefix
        self.interval = float(interval)
        self._snapshot: Optional[Dict[str, str]] = None
        self._closed = False

    async def _scan(self) -> Dict[str, Tuple[dict, str]]:
        live: Dict[str, Tuple[dict, str]] = {}
        for key in await self.store.list_keys(self.prefix):
            entry = await self.store.get(key)
            if entry is not None:
                live[key] = entry
        return live

    async def next(self, timeout: float) -> List[WatchEvent]:
        if self._closed:
            return []
        deadline = time.monotonic() + max(float(timeout), 0.0)
        if self._snapshot is None:
            self._snapshot = {
                key: entry[1]
                for key, entry in (await self._scan()).items()
            }
        while not self._closed:
            live = await self._scan()
            events: List[WatchEvent] = []
            for key, (data, token) in live.items():
                if self._snapshot.get(key) != token:
                    events.append(WatchEvent(key, data, token))
            for key in self._snapshot:
                if key not in live:
                    events.append(WatchEvent(key, None, None))
            if events:
                self._snapshot = {k: e[1] for k, e in live.items()}
                return events
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return []
            await asyncio.sleep(min(self.interval, remaining))
        return []

    def close(self) -> None:
        self._closed = True


class CoordStore(abc.ABC):
    """Async conditional-put key/value store of small JSON documents.

    Keys are ``/``-separated paths (``workers/<id>``, ``leases/<key>``).
    Every live entry carries an opaque write *token*; ``put`` with
    ``expect=<token>`` succeeds only against that exact version
    (compare-and-swap), ``expect=ABSENT`` only when the key has no live
    entry, ``expect=ANY`` unconditionally.  ``put``/``delete`` return
    falsy on a lost race — losing a conditional write is a normal
    outcome, not an error; :class:`CoordError` is reserved for the
    store itself misbehaving.
    """

    @abc.abstractmethod
    async def get(self, key: str) -> Optional[Tuple[dict, str]]:
        """``(data, token)`` for a live entry, else None."""

    @abc.abstractmethod
    async def put(self, key: str, data: dict,
                  expect: str = ANY) -> Optional[str]:
        """Conditionally write ``data``; new token, or None on conflict."""

    @abc.abstractmethod
    async def delete(self, key: str, expect: str = ANY) -> bool:
        """Conditionally remove; True when the entry is gone."""

    @abc.abstractmethod
    async def list_keys(self, prefix: str) -> List[str]:
        """Keys with a live entry under ``prefix``."""

    def watch(self, prefix: str, *,
              poll_interval: float = 0.25) -> CoordWatch:
        """Subscribe to changes under ``prefix`` (see :class:`CoordWatch`).

        The default is the snapshot-diff bounded long-poll — correct on
        every backend, paying one scan per ``poll_interval``.  Backends
        with a cheaper change feed (the in-memory store's version bump)
        override this with a true event-driven watch; callers cannot
        tell the difference except in wake-up latency.
        """
        return _PollWatch(self, prefix, poll_interval)


class _MemoryWatch(CoordWatch):
    """Event-driven watch: pushed by the store's mutations, no polling."""

    #: buffered-event cap — a watcher that stops draining must not
    #: grow without bound; overflow drops the OLDEST events, which is
    #: safe because every consumer re-reads current state on wake
    MAX_BUFFER = 256

    def __init__(self, store: "MemoryCoordStore", prefix: str):
        self.store = store
        self.prefix = prefix
        self._buffer: List[WatchEvent] = []
        self._wake = asyncio.Event()
        self._closed = False

    def _push(self, event: WatchEvent) -> None:
        self._buffer.append(event)
        if len(self._buffer) > self.MAX_BUFFER:
            del self._buffer[: len(self._buffer) - self.MAX_BUFFER]
        self._wake.set()

    async def next(self, timeout: float) -> List[WatchEvent]:
        if self._closed:
            return []
        if faults.enabled():
            # same seam a poll lap would hit: a coord brownout slows /
            # breaks watch wake-ups too, so chaos plans can rehearse
            # the watch-to-poll fallback
            await faults.fire("coord.get", key=self.prefix)
        if not self._buffer and timeout > 0:
            try:
                await asyncio.wait_for(self._wake.wait(),
                                       max(float(timeout), 0.0))
            except asyncio.TimeoutError:
                pass
        events, self._buffer = self._buffer, []
        self._wake.clear()
        return events

    def close(self) -> None:
        self._closed = True
        self.store._watchers.discard(self)
        self._wake.set()


class MemoryCoordStore(CoordStore):
    """Atomic in-process backend; share ONE instance across workers."""

    def __init__(self):
        self._entries: Dict[str, Tuple[dict, str]] = {}
        self._lock = asyncio.Lock()
        self._seq = itertools.count(1)
        self._watchers: set = set()

    def watch(self, prefix: str, *,
              poll_interval: float = 0.25) -> CoordWatch:
        handle = _MemoryWatch(self, prefix)
        self._watchers.add(handle)
        return handle

    def _notify(self, key: str, data: Optional[dict],
                token: Optional[str]) -> None:
        for handle in list(self._watchers):
            if key.startswith(handle.prefix):
                handle._push(WatchEvent(key, data, token))

    async def get(self, key: str) -> Optional[Tuple[dict, str]]:
        if faults.enabled():
            await faults.fire("coord.get", key=key)
        async with self._lock:
            entry = self._entries.get(key)
            return (dict(entry[0]), entry[1]) if entry else None

    async def put(self, key: str, data: dict,
                  expect: str = ANY) -> Optional[str]:
        if faults.enabled():
            await faults.fire("coord.put", key=key)
        async with self._lock:
            current = self._entries.get(key)
            if expect == ABSENT and current is not None:
                return None
            if expect not in (ABSENT, ANY) and (
                    current is None or current[1] != expect):
                return None
            token = f"m{next(self._seq)}"
            self._entries[key] = (dict(data), token)
            self._notify(key, dict(data), token)
            return token

    async def delete(self, key: str, expect: str = ANY) -> bool:
        if faults.enabled():
            await faults.fire("coord.delete", key=key)
        async with self._lock:
            current = self._entries.get(key)
            if current is None:
                return True
            if expect != ANY and current[1] != expect:
                return False
            del self._entries[key]
            self._notify(key, None, None)
            return True

    async def list_keys(self, prefix: str) -> List[str]:
        if faults.enabled():
            await faults.fire("coord.list", key=prefix)
        async with self._lock:
            return sorted(k for k in self._entries if k.startswith(prefix))


class BucketCoordStore(CoordStore):
    """Staging-bucket-backed coordination (best-effort conditional put).

    One JSON object per key at ``<prefix><key>``: ``{"data": {...},
    "token": <nonce>}``; a tombstone is the same shape with ``data``
    null.  Writes are verified by read-back (see the module docstring
    for the atomicity contract).
    """

    def __init__(self, store, bucket: str = STAGING_BUCKET,
                 prefix: str = ".fleet/", settle_delay: float = 0.05):
        self.store = store
        self.bucket = bucket
        self.prefix = prefix
        # pause between write and verification read: two writers whose
        # pre-write reads both saw the key free race last-write-wins,
        # and without a settle the EARLIER writer can read back its own
        # value before the later write lands — both would think they
        # won.  Settling longer than the (pre-read -> write) gap of any
        # concurrent writer collapses the double-win window to writers
        # more than ``settle_delay`` apart, which the pre-write read
        # already excludes.  Conditional writes are rare (lease ops,
        # heartbeats), so the latency is noise.
        self.settle_delay = float(settle_delay)
        self._seq = itertools.count()
        self._bucket_ready = False

    def _object(self, key: str) -> str:
        return self.prefix + key

    def _nonce(self) -> str:
        return f"{os.getpid():x}.{next(self._seq)}.{os.urandom(6).hex()}"

    async def _ensure_bucket(self) -> None:
        if self._bucket_ready:
            return
        if not await self.store.bucket_exists(self.bucket):
            await self.store.make_bucket(self.bucket)
        self._bucket_ready = True

    async def _read(self, key: str) -> Optional[Tuple[Optional[dict], str]]:
        """Raw entry including tombstones (data None); None = no object."""
        try:
            raw = await self.store.get_object(self.bucket, self._object(key))
        except ObjectNotFound:
            return None
        try:
            doc = json.loads(raw.decode("utf-8"))
            return doc["data"], str(doc["token"])
        except (ValueError, KeyError, UnicodeDecodeError) as err:
            raise CoordError(f"corrupt coordination entry {key}: {err}")

    async def get(self, key: str) -> Optional[Tuple[dict, str]]:
        if faults.enabled():
            await faults.fire("coord.get", key=key)
        try:
            entry = await self._read(key)
        except CoordError:
            raise
        except Exception as err:
            raise CoordError(f"coord get {key}: {err}") from err
        if entry is None or entry[0] is None:
            return None
        return entry[0], entry[1]

    async def _write_verified(self, key: str,
                              data: Optional[dict]) -> Optional[str]:
        """Write with a fresh nonce; token only when the read-back shows
        OUR write survived (last-write-wins race detection).

        ``at`` stamps the write time so the GC sweep (fleet/plane.py)
        can age tombstones; readers ignore it (only data/token matter).
        """
        token = self._nonce()
        body = json.dumps({
            "data": data, "token": token, "at": round(time.time(), 3),
        }).encode("utf-8")
        try:
            await self._ensure_bucket()
            await self.store.put_object(self.bucket, self._object(key), body)
            if self.settle_delay > 0:
                await asyncio.sleep(self.settle_delay)
            raw = await self.store.get_object(self.bucket, self._object(key))
        except Exception as err:
            raise CoordError(f"coord put {key}: {err}") from err
        try:
            survived = json.loads(raw.decode("utf-8")).get("token") == token
        except (ValueError, UnicodeDecodeError):
            survived = False
        return token if survived else None

    async def put(self, key: str, data: dict,
                  expect: str = ANY) -> Optional[str]:
        if faults.enabled():
            await faults.fire("coord.put", key=key)
        try:
            current = await self._read(key)
        except CoordError:
            # corrupt entry: only an unconditional write may repair it
            if expect != ANY:
                raise
            current = None
        except Exception as err:
            raise CoordError(f"coord put {key}: {err}") from err
        live = current is not None and current[0] is not None
        if expect == ABSENT and live:
            return None
        if expect not in (ABSENT, ANY) and (
                not live or current[1] != expect):
            return None
        return await self._write_verified(key, data)

    async def delete(self, key: str, expect: str = ANY) -> bool:
        if faults.enabled():
            await faults.fire("coord.delete", key=key)
        try:
            current = await self._read(key)
        except CoordError:
            raise
        except Exception as err:
            raise CoordError(f"coord delete {key}: {err}") from err
        if current is None or current[0] is None:
            return True
        if expect != ANY and current[1] != expect:
            return False
        # tombstone, not removal: the ObjectStore interface has no delete
        return await self._write_verified(key, None) is not None

    async def list_keys(self, prefix: str) -> List[str]:
        if faults.enabled():
            await faults.fire("coord.list", key=prefix)
        out = []
        try:
            async for info in self.store.list_objects(
                    self.bucket, self.prefix + prefix):
                if info.name.startswith(self.prefix):
                    out.append(info.name[len(self.prefix):])
        except Exception as err:
            raise CoordError(f"coord list {prefix}: {err}") from err
        # tombstones still list here; callers resolve liveness via get()
        return sorted(out)

    async def sweep_tombstones(self, max_age: float) -> int:
        """Physically remove tombstones older than ``max_age`` seconds.

        Deletes on this backend only tombstone (the ObjectStore interface
        historically had no remove), so churning keys — every released
        lease, every deregistered worker — accrete one object each under
        the prefix forever.  Removing an aged tombstone is semantically
        invisible: a tombstoned key already reads as absent, conditional
        puts against its token already fail, and any CAS that could race
        the removal expired with the lease/liveness TTLs long before
        ``max_age``.  Tombstones written before age-stamping (no ``at``)
        are treated as infinitely old.  Returns the number removed.
        """
        removed = 0
        now = time.time()
        for key in await self.list_keys(""):
            obj = self._object(key)
            try:
                raw = await self.store.get_object(self.bucket, obj)
            except ObjectNotFound:
                continue  # already gone
            except Exception as err:
                raise CoordError(f"coord sweep {key}: {err}") from err
            try:
                doc = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue  # corrupt: repairable only by an operator put
            if doc.get("data") is not None:
                continue  # live document — never touch
            try:
                written_at = float(doc.get("at") or 0.0)
            except (TypeError, ValueError):
                written_at = 0.0
            if now - written_at < max_age:
                continue
            try:
                # re-read immediately before the delete: a fresh LIVE
                # write can land at a churned key between the first read
                # and here, and an unconditional remove would destroy
                # it.  The re-read shrinks the window to sub-RTT — the
                # same best-effort bound as this backend's conditional
                # put, with damage bounded by the lease/liveness TTLs.
                raw2 = await self.store.get_object(self.bucket, obj)
                doc2 = json.loads(raw2.decode("utf-8"))
                if (doc2.get("data") is not None
                        or doc2.get("token") != doc.get("token")):
                    continue  # revived or rewritten: leave it alone
                await self.store.remove_object(self.bucket, obj)
                removed += 1
            except NotImplementedError:
                return removed  # backend cannot delete: nothing to sweep
            except (ValueError, UnicodeDecodeError):
                continue  # rewritten to something unreadable: skip
            except ObjectNotFound:
                continue  # already gone
            except Exception as err:
                raise CoordError(f"coord sweep {key}: {err}") from err
        return removed


class CasBucketCoordStore(BucketCoordStore):
    """Truly-conditional bucket coordination via S3 conditional writes.

    Same document shape, prefix, and tombstone discipline as
    :class:`BucketCoordStore`, but the write token is the object's
    **ETag** and every put is an ``If-Match`` / ``If-None-Match``
    conditional PUT that the *server* arbitrates — no nonce race, no
    settle delay, no read-back window: a lost race is a 412, atomically
    (AWS S3 since 2024-08, MinIO, R2 all implement it; the in-memory
    fake and MiniS3 mirror the semantics).  Select with
    ``fleet.backend: cas``; a store without ``put_object_cas`` raises
    NotImplementedError on first write, surfaced as CoordError, and the
    operator falls back to ``bucket``.
    """

    def __init__(self, store, bucket: str = STAGING_BUCKET,
                 prefix: str = ".fleet/"):
        super().__init__(store, bucket, prefix, settle_delay=0.0)

    #: read-CAS laps for ``expect=ANY`` writes before conceding — ANY
    #: writers are per-key owners (heartbeats, telemetry) in practice,
    #: so one lap is the overwhelmingly common case
    ANY_RETRIES = 8

    def _body(self, data: Optional[dict]) -> bytes:
        # keep the embedded nonce "token" field so documents stay
        # readable by BucketCoordStore peers (mixed fleets) and
        # sweep_tombstones' revival check stays meaningful; the
        # authoritative write token is the etag, not this nonce
        return json.dumps({
            "data": data, "token": self._nonce(),
            "at": round(time.time(), 3),
        }).encode("utf-8")

    async def _read_versioned(
            self, key: str) -> Optional[Tuple[Optional[dict], str]]:
        """``(data|None, etag)`` including tombstones; None = no object."""
        try:
            raw, etag = await self.store.get_object_versioned(
                self.bucket, self._object(key))
        except ObjectNotFound:
            return None
        except Exception as err:
            raise CoordError(f"coord get {key}: {err}") from err
        try:
            doc = json.loads(raw.decode("utf-8"))
            return doc["data"], str(etag)
        except (ValueError, KeyError, UnicodeDecodeError) as err:
            raise CoordError(f"corrupt coordination entry {key}: {err}")

    async def get(self, key: str) -> Optional[Tuple[dict, str]]:
        if faults.enabled():
            await faults.fire("coord.get", key=key)
        entry = await self._read_versioned(key)
        if entry is None or entry[0] is None:
            return None
        return entry[0], entry[1]

    async def _cas_put(self, key: str, body: bytes, *,
                       if_match: Optional[str] = None,
                       if_none_match: bool = False) -> Optional[str]:
        try:
            await self._ensure_bucket()
            return await self.store.put_object_cas(
                self.bucket, self._object(key), body,
                if_match=if_match, if_none_match=if_none_match)
        except Exception as err:
            raise CoordError(f"coord put {key}: {err}") from err

    async def put(self, key: str, data: dict,
                  expect: str = ANY) -> Optional[str]:
        if faults.enabled():
            await faults.fire("coord.put", key=key)
        body = self._body(data)
        if expect == ABSENT:
            token = await self._cas_put(key, body, if_none_match=True)
            if token is not None:
                return token
            # an object exists — but a *tombstone* still counts as
            # absent: retake it by CAS-replacing that exact version
            entry = await self._read_versioned(key)
            if entry is None:
                # removed between attempts (GC sweep): one more create
                return await self._cas_put(key, body, if_none_match=True)
            if entry[0] is not None:
                return None  # genuinely live: lost the race
            return await self._cas_put(key, body, if_match=entry[1])
        if expect != ANY:
            return await self._cas_put(key, body, if_match=expect)
        for _ in range(self.ANY_RETRIES):
            entry = await self._read_versioned(key)
            if entry is None:
                token = await self._cas_put(key, body, if_none_match=True)
            else:
                token = await self._cas_put(key, body, if_match=entry[1])
            if token is not None:
                return token
        return None

    async def delete(self, key: str, expect: str = ANY) -> bool:
        if faults.enabled():
            await faults.fire("coord.delete", key=key)
        body = self._body(None)
        for _ in range(self.ANY_RETRIES):
            entry = await self._read_versioned(key)
            if entry is None or entry[0] is None:
                return True
            if expect != ANY and entry[1] != expect:
                return False
            if await self._cas_put(key, body, if_match=entry[1]) is not None:
                return True
            if expect != ANY:
                return False  # our exact version was replaced: lost
        return False
