"""Fleet plane: worker registry, cross-worker singleflight, shared cache.

Everything before this package coordinates *inside one process*: PR 1's
singleflight coalesces same-content jobs sharing an orchestrator, and N
independent workers draining ``v1.download`` would still each download
the same hot episode.  :class:`FleetPlane` makes a set of worker
processes behave like one cache-coherent downloader, on top of the
:mod:`.coord` store's conditional-put primitive:

- **Worker registry** — each orchestrator registers
  ``workers/<worker_id>`` and re-heartbeats it every
  ``fleet.heartbeat_interval`` seconds with the autoscale signal trio
  (queue depth, oldest-queued age, disk headroom) plus its fleet stats;
  an entry whose heartbeat is older than ``fleet.liveness_ttl`` is
  considered dead and filtered from :meth:`workers` without any
  operator action.
- **Lease-based cross-worker singleflight** — before touching an
  origin, a worker tries a conditional-put on ``leases/<content_key>``
  (the exact :func:`~..store.cache.cache_key` identity the local cache
  uses).  The winner fetches and keeps the lease renewed; losers park
  their job (the control plane's PARKED state) and poll for the
  leader's shared-tier publish.  A lease whose leader stopped renewing
  (crash, partition) expires after ``fleet.lease_ttl`` and is taken
  over by compare-and-swap — a dead leader's work is reclaimed by
  whichever waiter notices first.
- **Shared cache tier** — on fill, the leader spills its local cache
  entry to ``<shared_prefix><key>/files/...`` in the staging bucket and
  seals it with ``manifest.json`` written LAST (the same
  manifest-publishes-the-entry discipline ``store/cache.py`` uses on
  disk: a torn spill is invisible, never served).  Peers materialize a
  hit by streaming the files into their local cache and hardlink-serving
  from there, so a fleet-wide hot object costs one origin download plus
  N-1 intra-infrastructure copies.

Failure posture (the PR 5 contract): the coordination store is a
*dependency like any other* — its calls ride the ``coord`` retry policy
and every unrecoverable :class:`~.coord.CoordError` degrades the worker
to plain uncoordinated fetching (counted on
``fleet_coord_errors_total``), never failing or stalling a job.

**Fencing discipline** (Gray–Cheriton leases; the GC-pause split-brain):
a leader stalled past its lease TTL (SIGSTOP, GC pause, VM migration)
wakes believing it still leads while a peer has taken over with
``fence + 1``.  The fence number is therefore *enforced at every
cross-worker write*, not just allocated at takeover: the shared-tier
manifest, the done-marker seal, and telemetry digests all carry the
writer's fence, and a write is rejected — counted on
``fleet_fenced_writes_total{op}`` — when a higher fence has been
observed (lease-doc read + post-write read-back, the same best-effort
CAS posture as the bucket store's nonce verification; damage in the
sub-RTT window is bounded exactly like a conditional-put race).  A
resumed stale leader must lose.
"""

from __future__ import annotations

import asyncio
import inspect
import json
import os
import posixpath
import shutil
import socket
import time
from typing import Any, Callable, Dict, List, Optional

from ..control.cancel import JobCancelled
from ..platform.config import cfg_get
from ..platform.tracing import parse_traceparent
from ..stages.upload import STAGING_BUCKET
from ..store.base import ObjectNotFound
from ..utils.hashing import md5_file_hex
from .coord import (ABSENT, ANY, BucketCoordStore, CasBucketCoordStore,
                    CoordError, CoordStore, CoordWatch, MemoryCoordStore)

# coordination-store key namespaces
WORKERS_PREFIX = "workers/"
LEASES_PREFIX = "leases/"
# per-job trace digests: telemetry/<trace_id>/<worker_id>/<job_id> (on the bucket
# backend that is `.fleet/telemetry/...` in the staging bucket)
TELEMETRY_PREFIX = "telemetry/"
# the one fleet-overview document the elected aggregator folds live
# members into each heartbeat (ISSUE 15: the first fleet-WIDE view —
# burn rates, breakers, tenant queue shares — any worker can serve)
OVERVIEW_PREFIX = "overview/"
OVERVIEW_KEY = OVERVIEW_PREFIX + "fleet"
# the one placement/autoscale plan document the elected controller
# (fleet/controller.py) publishes each heartbeat; every worker watches
# it and consults the cached copy at admission (ISSUE 17)
PLAN_PREFIX = "plan/"
PLAN_KEY = PLAN_PREFIX + "fleet"
# the fleet-shared origin-health table: per-origin throughput EWMAs
# merged from every worker, seeded into each worker's OriginHealth at
# boot (a worker that watched an origin die spares its peers the probe)
ORIGINS_PREFIX = "origins/"
ORIGIN_HEALTH_KEY = ORIGINS_PREFIX + "health"
# shared-tier object layout in the staging bucket
SHARED_PREFIX = ".fleet-cache/"
MANIFEST_NAME = "manifest.json"


def _fput_supports(store, parameter: str) -> bool:
    """Signature probe for optional fput_object capabilities (tests
    monkeypatch fput freely, so probe per call, not at construction)."""
    try:
        return parameter in inspect.signature(
            store.fput_object).parameters
    except (TypeError, ValueError):
        return False

DEFAULT_HEARTBEAT_INTERVAL = 5.0
DEFAULT_LIVENESS_TTL = 15.0
DEFAULT_LEASE_TTL = 20.0
DEFAULT_POLL_INTERVAL = 0.25
# a waiter parked on a peer's lease gives up coordinating (and fetches
# for itself) after this long — a livelock bound, not a hot-path knob
DEFAULT_MAX_WAIT = 600.0
# shared-tier / tombstone GC (the sweep keeping .fleet-cache/ and
# .fleet/ growth bounded); interval 0 disables the loop entirely
DEFAULT_GC_INTERVAL = 300.0
DEFAULT_SHARED_MAX_AGE = 24 * 3600.0
DEFAULT_SHARED_MAX_BYTES = 0  # 0 = no size budget (age bound only)
# per-job trace digests published at settle live this long before the
# fleet GC reclaims them (0 disables publishing entirely)
DEFAULT_TELEMETRY_TTL = 1800.0
# seconds between merges of this worker's per-origin EWMAs into the
# fleet-shared origin-health table (0 disables sharing)
DEFAULT_ORIGIN_SHARE_INTERVAL = 60.0
# a fleet-shared origin-health row older than this is stale history,
# not a head start: boot seeding skips it
ORIGIN_HEALTH_MAX_AGE = 6 * 3600.0
# events kept in one digest: enough for the lifecycle + failure tail,
# bounded so a digest document stays a few KB
DIGEST_EVENT_LIMIT = 48
# per-read budget on the overview fetch (the trace assembler's
# PEER_TIMEOUT posture): a browned-out coordination store must cost a
# bounded wait and a degraded response, never a hung admin read
OVERVIEW_FETCH_BUDGET = 5.0

# a lease is only treated as dead once expired by this fraction of the
# TTL: lease math compares the WRITER's wall clock against the READER's,
# so modest cross-host clock skew must not let a waiter steal a lease
# its live leader is still renewing (renewals land every ttl/3; skew
# beyond grace + renewal margin needs NTP, which the docs require)
TAKEOVER_GRACE_FRAC = 0.25

# coordinate() outcomes
LED = "led"                     # this worker held the lease and fetched
SHARED = "shared"               # served from the fleet shared tier
UNCOORDINATED = "uncoordinated"  # coordination unavailable: fetch alone

# bound on the per-key observed-fence memo (insertion-order eviction;
# a key's fence re-learns from the lease doc / manifest on next touch)
_FENCE_SEEN_MAX = 1024


def resolve_worker_id(config) -> str:
    """Stable-for-the-process worker identity: env ``WORKER_ID``, config
    ``fleet.worker_id``, else ``<host>-<pid>-<nonce>`` (the nonce keeps
    N orchestrators in one test process distinct)."""
    configured = os.environ.get("WORKER_ID") or cfg_get(
        config, "fleet.worker_id", None
    )
    if configured:
        return str(configured)
    return f"{socket.gethostname()}-{os.getpid()}-{os.urandom(3).hex()}"


class _GcLeaseViewUnavailable(Exception):
    """The GC sweep could not read the lease view (asymmetric
    partition): the shared-tier eviction pass must stand down rather
    than evict keys that may be under a live peer's lease."""


class _Lease:
    """One held lease: its CAS token and the renewal task keeping it."""

    __slots__ = ("key", "token", "fence", "renewer", "trace",
                 "route_key")

    def __init__(self, key: str, token: str, fence: int,
                 trace: Optional[dict] = None,
                 route_key: Optional[str] = None):
        self.key = key
        self.token = token
        self.fence = fence
        # the leading job's W3C trace context, re-stamped on every
        # renewal so waiters always see which trace their wait joins
        self.trace = trace
        # the admission-edge routing identity (cache_key over the
        # source URI) — stamped into the lease doc so every worker's
        # watch-fed lease view can steer same-content deliveries to
        # this holder (ISSUE 17 content-aware routing)
        self.route_key = route_key
        self.renewer: Optional[asyncio.Task] = None


class FleetPlane:
    """One worker's handle on the fleet (see module docstring)."""

    def __init__(
        self,
        coord: CoordStore,
        worker_id: str,
        *,
        store=None,
        shared_bucket: str = STAGING_BUCKET,
        shared_prefix: str = SHARED_PREFIX,
        heartbeat_interval: float = DEFAULT_HEARTBEAT_INTERVAL,
        liveness_ttl: float = DEFAULT_LIVENESS_TTL,
        lease_ttl: float = DEFAULT_LEASE_TTL,
        poll_interval: float = DEFAULT_POLL_INTERVAL,
        max_wait: float = DEFAULT_MAX_WAIT,
        gc_interval: float = DEFAULT_GC_INTERVAL,
        shared_max_age: float = DEFAULT_SHARED_MAX_AGE,
        shared_max_bytes: int = DEFAULT_SHARED_MAX_BYTES,
        telemetry_ttl: float = DEFAULT_TELEMETRY_TTL,
        advertise_url: Optional[str] = None,
        watch_enabled: bool = True,
        origin_share_interval: float = DEFAULT_ORIGIN_SHARE_INTERVAL,
        metrics=None,
        logger=None,
        retrier=None,
        payload_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        digest_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        origin_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ):
        if liveness_ttl <= heartbeat_interval:
            raise ValueError(
                f"fleet.liveness_ttl ({liveness_ttl}) must exceed "
                f"fleet.heartbeat_interval ({heartbeat_interval})"
            )
        if lease_ttl <= 0 or poll_interval <= 0:
            raise ValueError("fleet lease_ttl/poll_interval must be > 0")
        self.coord = coord
        self.worker_id = worker_id
        self.store = store
        self.shared_bucket = shared_bucket
        self.shared_prefix = shared_prefix
        self.heartbeat_interval = float(heartbeat_interval)
        self.liveness_ttl = float(liveness_ttl)
        self.lease_ttl = float(lease_ttl)
        self.poll_interval = float(poll_interval)
        self.max_wait = float(max_wait)
        self.gc_interval = float(gc_interval)
        self.shared_max_age = float(shared_max_age)
        self.shared_max_bytes = int(shared_max_bytes)
        # cross-worker trace digests (``fleet.telemetry_ttl``; 0 = off):
        # settled jobs publish a compact timeline digest the fleet's
        # trace assembly (control/trace.py) joins across workers
        self.telemetry_ttl = float(telemetry_ttl)
        # this worker's admin-API base URL, advertised in heartbeats so
        # peers can assemble LIVE (pre-settle) trace segments over HTTP
        # (``fleet.advertise_url``; None = digests/local only)
        self.advertise_url = advertise_url
        self.metrics = metrics
        self.logger = logger
        self.retrier = retrier
        self.payload_fn = payload_fn
        # compact SLO/health digest carried in every heartbeat
        # (orchestrator.slo_digest: burn rates, open breakers, top
        # hops, tenant queue shares) — the raw material the elected
        # aggregator folds into the fleet-overview doc.  Optional by
        # contract: a pre-PR-15 worker's heartbeat simply has no
        # digest, and build_overview lists it with ``digest: null``.
        self.digest_fn = digest_fn
        # per-origin throughput snapshot for the fleet-shared
        # origin-health table (orchestrator wires OriginHealth.snapshot;
        # None = this worker does not share)
        self.origin_fn = origin_fn
        self.origin_share_interval = float(origin_share_interval)
        self._origin_shared_mono = 0.0
        # watch/subscribe plane (ISSUE 17): event-driven on backends
        # that can, snapshot-diff long-poll otherwise, and OFF entirely
        # (pure sleep-poll, the PR 9 degraded path) when disabled
        self.watch_enabled = bool(watch_enabled)
        self._overview_watch: Optional[CoordWatch] = None
        self._overview_doc: Optional[dict] = None
        self._plan_watch: Optional[CoordWatch] = None
        self._plan_doc: Optional[dict] = None
        self._lease_watch: Optional[CoordWatch] = None
        # lease-doc cache fed by the lease watch (content key -> doc):
        # the content router's holder lookups must not cost a store RTT
        # per delivery
        self._lease_view: Dict[str, dict] = {}
        self._lease_view_ready = False
        # wall-clock ``updatedAt`` of the overview doc this worker last
        # published or read (None until either happens) — the
        # ``fleet_overview_age_seconds`` gauge's source
        self._overview_updated_at: Optional[float] = None
        self.started_at = time.time()
        self._heartbeat_task: Optional[asyncio.Task] = None
        self._gc_task: Optional[asyncio.Task] = None
        self._worker_token: Optional[str] = None
        self._gauge_sampled_mono = 0.0
        self._held: Dict[str, _Lease] = {}
        # shared-tier entries seen manifest-less on the previous sweep:
        # two consecutive manifest-less sightings (>= gc_interval apart)
        # mark a torn/abandoned spill safe to reclaim (listings carry no
        # mtime, so "seen twice" is the age proxy)
        self._gc_manifestless: set = set()
        # manifest "created" stamps memoized across sweeps (immutable
        # once published; pruned to the current listing each sweep)
        self._gc_created: Dict[str, float] = {}
        # highest lease fence OBSERVED per content key (from lease
        # reads, takeovers, and manifest read-backs) — the local half
        # of fencing enforcement: a write whose fence is below this is
        # stale even when the lease doc is already gone.  Bounded
        # (insertion-order eviction past _FENCE_SEEN_MAX).
        self._fence_seen: Dict[str, int] = {}
        # local stats, also carried in every heartbeat payload
        self.stats: Dict[str, int] = {
            "leasesLed": 0, "leaseWaits": 0, "leaseTakeovers": 0,
            "sharedHits": 0, "sharedFills": 0,
            "sharedBytesIn": 0, "sharedBytesOut": 0,
            "sharedCorrupt": 0,
            "coordErrors": 0, "uncoordinatedFallbacks": 0,
            "gcSharedEvicted": 0, "gcTombstonesCompacted": 0,
            "gcBytesReclaimed": 0,
            "telemetryPublished": 0, "gcTelemetryEvicted": 0,
            "fencedWrites": 0, "originHealthShared": 0,
        }

    # -- config ---------------------------------------------------------
    @classmethod
    def from_config(cls, config, *, worker_id: str, store=None, coord=None,
                    metrics=None, logger=None, retrier=None,
                    payload_fn=None, digest_fn=None, origin_fn=None
                    ) -> Optional["FleetPlane"]:
        """Build from ``fleet.*`` / env; None when the fleet is disabled
        (the default — a lone worker pays nothing for this subsystem).

        Knobs: ``FLEET_ENABLED``/``fleet.enabled``, ``fleet.backend``
        (``bucket`` default | ``cas`` | ``memory``),
        ``fleet.heartbeat_interval``, ``fleet.liveness_ttl``,
        ``fleet.lease_ttl``, ``fleet.poll_interval``, ``fleet.max_wait``,
        ``fleet.shared_tier`` (false keeps leases but skips the spill),
        ``fleet.gc_interval`` (0 disables the GC sweep),
        ``fleet.shared_max_age`` / ``fleet.shared_max_bytes`` (shared-
        tier eviction bounds), ``fleet.watch_enabled`` (false pins the
        degraded sleep-poll path), ``fleet.origin_share_interval``
        (0 disables the shared origin-health table).
        """
        enabled = os.environ.get("FLEET_ENABLED")
        if enabled is None:
            enabled = bool(cfg_get(config, "fleet.enabled", False))
        else:
            enabled = enabled.lower() in ("1", "true", "yes")
        if not enabled:
            return None
        if coord is None:
            backend = os.environ.get("FLEET_BACKEND") or cfg_get(
                config, "fleet.backend", "bucket"
            )
            if backend == "memory":
                # hermetic, single-process: workers must SHARE a store
                # to coordinate, so this is for tests/benches that pass
                # their own — a per-worker one coordinates only itself
                coord = MemoryCoordStore()
            elif backend == "bucket":
                if store is None:
                    raise ValueError(
                        "fleet.backend: bucket needs an object store"
                    )
                coord = BucketCoordStore(store)
            elif backend == "cas":
                # real conditional puts (S3 If-Match / If-None-Match):
                # server-arbitrated CAS, no settle delay, no read-back
                if store is None:
                    raise ValueError(
                        "fleet.backend: cas needs an object store"
                    )
                coord = CasBucketCoordStore(store)
            else:
                raise ValueError(
                    f"fleet.backend must be bucket|cas|memory, "
                    f"got {backend!r}"
                )
        shared = bool(cfg_get(config, "fleet.shared_tier", True))
        return cls(
            coord, worker_id,
            store=store if shared else None,
            heartbeat_interval=float(cfg_get(
                config, "fleet.heartbeat_interval",
                DEFAULT_HEARTBEAT_INTERVAL)),
            liveness_ttl=float(cfg_get(
                config, "fleet.liveness_ttl", DEFAULT_LIVENESS_TTL)),
            lease_ttl=float(cfg_get(
                config, "fleet.lease_ttl", DEFAULT_LEASE_TTL)),
            poll_interval=float(cfg_get(
                config, "fleet.poll_interval", DEFAULT_POLL_INTERVAL)),
            max_wait=float(cfg_get(
                config, "fleet.max_wait", DEFAULT_MAX_WAIT)),
            gc_interval=float(cfg_get(
                config, "fleet.gc_interval", DEFAULT_GC_INTERVAL)),
            shared_max_age=float(cfg_get(
                config, "fleet.shared_max_age", DEFAULT_SHARED_MAX_AGE)),
            shared_max_bytes=int(cfg_get(
                config, "fleet.shared_max_bytes",
                DEFAULT_SHARED_MAX_BYTES)),
            telemetry_ttl=float(cfg_get(
                config, "fleet.telemetry_ttl", DEFAULT_TELEMETRY_TTL)),
            advertise_url=cfg_get(config, "fleet.advertise_url", None),
            watch_enabled=bool(cfg_get(
                config, "fleet.watch_enabled", True)),
            origin_share_interval=float(cfg_get(
                config, "fleet.origin_share_interval",
                DEFAULT_ORIGIN_SHARE_INTERVAL)),
            metrics=metrics, logger=logger, retrier=retrier,
            payload_fn=payload_fn, digest_fn=digest_fn,
            origin_fn=origin_fn,
        )

    # -- plumbing -------------------------------------------------------
    def _note_coord_error(self, op: str, err: BaseException) -> None:
        self.stats["coordErrors"] += 1
        if self.metrics is not None:
            self.metrics.fleet_coord_errors.labels(op=op).inc()
        if self.logger is not None:
            self.logger.warn("fleet coordination error",
                             op=op, error=str(err)[:200])

    async def _coord_op(self, seam: str, factory, cancel=None):
        """Run one coordination call under the ``coord`` retry policy
        (when a retrier is attached) so a single store blip does not
        instantly degrade the worker to uncoordinated fetching."""
        if self.retrier is None:
            return await factory()
        return await self.retrier.run(seam, factory, cancel=cancel,
                                      logger=self.logger)

    # -- watch/subscribe plumbing ---------------------------------------
    def _note_watch_wakeup(self, mode: str) -> None:
        """Count one watch-plane wake-up: ``event`` (the watch
        delivered changes), ``timeout`` (bounded long-poll lapsed), or
        ``poll`` (degraded to sleep-poll — watch unavailable/broken)."""
        if self.metrics is not None:
            self.metrics.fleet_watch_wakeups.labels(mode=mode).inc()

    def _open_watch(self, prefix: str) -> Optional[CoordWatch]:
        """A watch on ``prefix``, or None when the watch plane is off
        or the store refused — the caller's poll loop is the fallback."""
        if not self.watch_enabled:
            return None
        try:
            return self.coord.watch(prefix,
                                    poll_interval=self.poll_interval)
        except Exception as err:
            self._note_coord_error("watch_open", err)
            return None

    def telemetry_watch(self) -> Optional[CoordWatch]:
        """A watch over the fleet's per-job telemetry digests — every
        settle publishes one, so a wake here is 'a peer just finished
        something'.  The staged-probe loop (orchestrator) rides this to
        retire recovery placeholders promptly instead of waiting out
        its fallback interval.  None = watch plane off/refused."""
        return self._open_watch(TELEMETRY_PREFIX)

    async def _drain_watch(self, watch: Optional[CoordWatch]
                           ) -> Optional[list]:
        """Non-blocking drain of one maintained watch; None = watch
        unusable this lap (closed/broken), [] = open but quiet."""
        if watch is None:
            return None
        try:
            return await watch.next(0)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            self._note_coord_error("watch", err)
            return None

    async def _waiter_wait(self, watch: Optional[CoordWatch],
                           deadline: float) -> Optional[CoordWatch]:
        """One parked-waiter lap: block until the watched lease doc
        changes (the leader released/renewed, a takeover rewrote it),
        a bounded long-poll lapses, or — no watch — one poll-interval
        sleep, the PR 9 degraded path.  Returns the watch to keep
        using; None once it broke (sleep-poll from there on)."""
        if watch is None:
            self._note_watch_wakeup("poll")
            await asyncio.sleep(self.poll_interval)
            return None
        # bounded lap: a missed event (brownout, watch races) must not
        # outwait lease EXPIRY — cap at the takeover grace so a dead
        # leader is still noticed promptly; floor at poll_interval so
        # a nearly-due deadline cannot busy-spin the watch
        timeout = max(self.poll_interval,
                      min(self.lease_ttl * TAKEOVER_GRACE_FRAC,
                          deadline - time.monotonic()))
        try:
            events = await watch.next(timeout)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            self._note_coord_error("watch", err)
            watch.close()
            self._note_watch_wakeup("poll")
            await asyncio.sleep(self.poll_interval)
            return None
        self._note_watch_wakeup("event" if events else "timeout")
        return watch

    # -- fencing --------------------------------------------------------
    def _observe_fence(self, key: str, fence) -> None:
        """Max-merge one observed lease fence for ``key`` (bounded memo)."""
        try:
            fence = int(fence)
        except (TypeError, ValueError):
            return
        if fence <= 0:
            return
        if fence > self._fence_seen.get(key, 0):
            self._fence_seen.pop(key, None)
            self._fence_seen[key] = fence
            while len(self._fence_seen) > _FENCE_SEEN_MAX:
                self._fence_seen.pop(next(iter(self._fence_seen)))

    def observed_fence(self, key: str) -> int:
        """Highest fence this worker has seen for ``key`` (0 = none)."""
        return self._fence_seen.get(key, 0)

    def _note_fenced_write(self, op: str, key: str, fence: int,
                           newer: int) -> None:
        """Count one rejected stale write — the split-brain save."""
        self.stats["fencedWrites"] += 1
        if self.metrics is not None:
            self.metrics.fleet_fenced_writes.labels(op=op).inc()
        if self.logger is not None:
            self.logger.warn("fleet: fenced off stale write",
                             op=op, key=key[:16], fence=fence,
                             newer=newer)

    async def fence_holds(self, key: str, fence) -> bool:
        """Is ``fence`` still the write authority for ``key``?

        False once a higher fence has been observed — locally, or by a
        fresh read of the lease doc (the cross-worker observation: a
        resumed stale leader learns of its takeover here).  Best-effort
        like every coordination read: a store failure degrades to the
        local memo (fencing is defense-in-depth on top of content-hash
        resume + manifest-last publish, not the sole correctness line).
        """
        try:
            fence = int(fence)
        except (TypeError, ValueError):
            return True  # no fence context: nothing to enforce
        if fence <= 0:
            return True
        if self.observed_fence(key) > fence:
            return False
        try:
            entry = await self.coord.get(LEASES_PREFIX + key)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            self._note_coord_error("fence_check", err)
            return True  # degrade to the local memo's verdict above
        if entry is not None:
            doc = entry[0]
            self._observe_fence(key, doc.get("fence"))
            doc_fence = doc.get("fence")
            if isinstance(doc_fence, int) and doc_fence > fence:
                return False
        # strictly-greater only: our own claimed fence always came from
        # a lease we held (the leader path is the only place it is
        # stamped), so an EQUAL number elsewhere is cross-epoch reuse
        # after a full release — fencing the healthy later writer there
        # drops real work to save nothing
        return self.observed_fence(key) <= fence

    # -- worker registry ------------------------------------------------
    def _worker_doc(self) -> dict:
        now = time.time()
        doc = {
            "workerId": self.worker_id,
            "startedAt": round(self.started_at, 3),
            "heartbeatAt": round(now, 3),
            "expiresAt": round(now + self.liveness_ttl, 3),
            "leases": sorted(self._held),
            "stats": dict(self.stats),
        }
        if self.advertise_url:
            # peers use this to assemble LIVE cross-worker traces over
            # the admin API (control/trace.py); absent = digests only
            doc["adminUrl"] = self.advertise_url
        if self.payload_fn is not None:
            try:
                doc["signals"] = dict(self.payload_fn())
            except Exception as err:  # a bad signal must not kill beats
                doc["signalsError"] = str(err)[:120]
        if self.digest_fn is not None:
            # the SLO/health digest (burn rates, open breakers, top
            # hops, tenant queue shares) — same failure posture as the
            # autoscale signals: a broken digest must not kill beats
            try:
                doc["digest"] = dict(self.digest_fn())
            except Exception as err:
                doc["digestError"] = str(err)[:120]
        return doc

    async def _beat_once(self) -> None:
        doc = self._worker_doc()
        key = WORKERS_PREFIX + self.worker_id
        token = await self.coord.put(
            key, doc,
            expect=self._worker_token if self._worker_token else ANY,
        )
        if token is None:
            # our entry was replaced (e.g. swept, or an id collision):
            # reclaim it unconditionally — this worker IS the identity
            token = await self.coord.put(key, doc, expect=ANY)
        self._worker_token = token
        # membership enumeration is list + one get per key (including
        # tombstones on the bucket backend), so the gauge samples at a
        # bounded cadence instead of every beat
        now = time.monotonic()
        if (self.metrics is not None and token is not None
                and now - self._gauge_sampled_mono
                >= max(self.heartbeat_interval, 15.0)):
            self._gauge_sampled_mono = now
            try:
                live = len(await self.workers())
                self.metrics.fleet_workers_live.set(live)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # the gauge just keeps its last sample

    async def _heartbeat_loop(self) -> None:
        while True:
            try:
                await self._beat_once()
            except CoordError as err:
                self._note_coord_error("heartbeat", err)
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self._note_coord_error("heartbeat", err)
            try:
                # fold (or track) the fleet overview on the same
                # cadence — its own try: overview trouble must never
                # starve the liveness beat above
                await self._overview_tick()
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self._note_coord_error("overview", err)
            try:
                # refresh the watch-fed lease/plan caches the content
                # router consults at admission (same posture: cache
                # trouble degrades routing, never the beat)
                await self._refresh_views()
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self._note_coord_error("views", err)
            try:
                await self._origin_health_tick()
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self._note_coord_error("origin_health", err)
            await asyncio.sleep(self.heartbeat_interval)

    async def start(self) -> None:
        """Register this worker and begin heartbeating (+ GC sweeping)."""
        try:
            await self._beat_once()
        except asyncio.CancelledError:
            raise
        except Exception as err:
            # registration trouble is not fatal: the loop keeps trying
            self._note_coord_error("register", err)
        self._heartbeat_task = asyncio.create_task(
            self._heartbeat_loop(), name=f"fleet-heartbeat-{self.worker_id}"
        )
        if self.gc_interval > 0 and self.store is not None:
            self._gc_task = asyncio.create_task(
                self._gc_loop(), name=f"fleet-gc-{self.worker_id}"
            )

    async def stop(self) -> None:
        """Deregister and release every held lease (clean drain: peers
        see this worker vanish immediately, not after liveness_ttl)."""
        if self._gc_task is not None:
            self._gc_task.cancel()
            try:
                await self._gc_task
            except (asyncio.CancelledError, Exception):
                pass
            self._gc_task = None
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            try:
                await self._heartbeat_task
            except (asyncio.CancelledError, Exception):
                pass
            self._heartbeat_task = None
        for watch in (self._overview_watch, self._plan_watch,
                      self._lease_watch):
            if watch is not None:
                watch.close()
        self._overview_watch = None
        self._plan_watch = None
        self._lease_watch = None
        for key in list(self._held):
            await self.release_lease(key)
        try:
            await self.coord.delete(WORKERS_PREFIX + self.worker_id)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            # the registry entry simply expires after liveness_ttl
            self._note_coord_error("deregister", err)

    async def _get_all(self, prefix: str) -> "List[tuple[str, dict]]":
        """Live ``(key, document)`` pairs under ``prefix``, resolved
        concurrently (one listing + gathered gets — the bucket backend
        pays one RTT, not one per key; tombstoned keys resolve to None
        and drop out)."""
        keys = await self.coord.list_keys(prefix)
        entries = await asyncio.gather(
            *(self.coord.get(key) for key in keys)
        )
        return [(key, entry[0]) for key, entry in zip(keys, entries)
                if entry is not None]

    async def workers(self) -> List[dict]:
        """Live workers (heartbeat within liveness_ttl), oldest first."""
        now = time.time()
        out = [doc for _key, doc in await self._get_all(WORKERS_PREFIX)
               if float(doc.get("expiresAt", 0)) >= now]
        out.sort(key=lambda d: d.get("startedAt", 0))
        return out

    async def worker(self, worker_id: str) -> Optional[dict]:
        entry = await self.coord.get(WORKERS_PREFIX + worker_id)
        if entry is None:
            return None
        doc = entry[0]
        doc["live"] = float(doc.get("expiresAt", 0)) >= time.time()
        return doc

    async def leases(self) -> List[dict]:
        """Every live lease (owner, fence, expiry) — the stuck-lease
        triage view ``cli fleet list`` renders."""
        now = time.time()
        out = []
        for key, doc in await self._get_all(LEASES_PREFIX):
            doc["key"] = key[len(LEASES_PREFIX):]
            doc["expired"] = float(doc.get("expiresAt", 0)) < now
            out.append(doc)
        return out

    # -- leases ---------------------------------------------------------
    def _trace_context(self, record) -> Optional[dict]:
        """The job's W3C trace context as a small carry-able document —
        what lease docs and shared-tier manifests propagate so the
        cross-worker trace assembly can join waiter and leader."""
        trace_id = getattr(record, "trace_id", None)
        span_id = getattr(record, "span_id", None)
        if not trace_id or not span_id:
            # no span id, no context: an all-zero placeholder would
            # round-trip into a traceparent that parse_traceparent
            # rejects by spec — a silently unfollowable link
            return None
        return {
            "traceparent": f"00-{trace_id}-{span_id}-01",
            "jobId": getattr(record, "job_id", None),
            "worker": self.worker_id,
        }

    def _lease_doc(self, fence: int, trace: Optional[dict] = None,
                   route_key: Optional[str] = None) -> dict:
        now = time.time()
        doc = {
            "owner": self.worker_id,
            "fence": fence,
            "acquiredAt": round(now, 3),
            "expiresAt": round(now + self.lease_ttl, 3),
        }
        if trace:
            # the leading job's traceparent rides the lease: a waiter
            # parked on this key knows exactly which trace (and which
            # worker's fetch) it is waiting on
            doc["trace"] = dict(trace)
        if route_key:
            # the content router's lookup identity: peers consult their
            # watch-fed lease view for this and hand same-content
            # deliveries to the holder instead of parking N-1 workers
            doc["routeKey"] = route_key
        return doc

    async def try_acquire_lease(self, key: str,
                                trace: Optional[dict] = None,
                                route_key: Optional[str] = None
                                ) -> Optional[_Lease]:
        """One conditional-put attempt on ``leases/<key>``.

        Returns the held lease, or None when a live peer holds it.  An
        expired lease is taken over by CAS against the dead leader's
        token — the fence number increments so the takeover is visible
        in the lease history."""
        lease_key = LEASES_PREFIX + key
        entry = await self.coord.get(lease_key)
        if entry is None:
            # seed ABOVE any fence this worker has ever observed for
            # the key: release_lease deletes the doc, so a naive fresh
            # acquire would restart at 1 and the writer would fence
            # ITSELF off against its own memo of the previous epoch.
            # (Cross-worker number reuse after both the doc and the
            # manifest are gone remains possible — the same bounded
            # best-effort window as the bucket store's conditional put;
            # a stale writer's horizon is one job lifetime.)
            fence = self.observed_fence(key) + 1
            token = await self.coord.put(
                lease_key, self._lease_doc(fence, trace, route_key),
                expect=ABSENT
            )
            takeover = False
        else:
            doc, old_token = entry
            self._observe_fence(key, doc.get("fence"))
            # a lease owned by OUR id that we do not hold is orphaned by
            # definition (its renewer died with the previous process —
            # stable worker_ids survive restarts): reclaim immediately
            # instead of waiting out our own TTL
            own_orphan = (doc.get("owner") == self.worker_id
                          and key not in self._held)
            grace = self.lease_ttl * TAKEOVER_GRACE_FRAC
            if not own_orphan and (
                    float(doc.get("expiresAt", 0)) + grace >= time.time()):
                return None  # live (or skew-ambiguous) leader
            # max against the local memo too: the doc's fence is the
            # floor, but this worker may have observed a newer epoch
            # (e.g. a manifest read-back) the doc never carried
            fence = max(int(doc.get("fence", 0)),
                        self.observed_fence(key)) + 1
            token = await self.coord.put(
                lease_key, self._lease_doc(fence, trace, route_key),
                expect=old_token
            )
            takeover = True
        if token is None:
            return None  # lost the race: someone else just took it
        self._observe_fence(key, fence)
        lease = _Lease(key, token, fence, trace=trace,
                       route_key=route_key)
        self._held[key] = lease
        lease.renewer = asyncio.create_task(
            self._renew_loop(lease), name=f"fleet-lease-{key[:12]}"
        )
        if self.metrics is not None:
            self.metrics.fleet_leases_acquired.labels(
                mode="takeover" if takeover else "fresh"
            ).inc()
        if takeover:
            self.stats["leaseTakeovers"] += 1
            if self.logger is not None:
                self.logger.warn("fleet: took over expired lease",
                                 key=key[:16], fence=fence)
        self.stats["leasesLed"] += 1
        return lease

    async def _renew_loop(self, lease: _Lease) -> None:
        """Keep a held lease alive while its fetch runs.  A failed renew
        (store trouble or the lease was stolen) stops renewing but never
        interrupts the fetch — worst case a peer duplicates the
        download, which is the uncoordinated baseline."""
        interval = max(self.lease_ttl / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                token = await self.coord.put(
                    LEASES_PREFIX + lease.key,
                    self._lease_doc(lease.fence, lease.trace,
                                    lease.route_key),
                    expect=lease.token,
                )
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self._note_coord_error("lease_renew", err)
                return
            if token is None:
                if self.logger is not None:
                    self.logger.warn("fleet: lease renewal lost",
                                     key=lease.key[:16])
                return
            lease.token = token

    async def release_lease(self, key: str) -> None:
        lease = self._held.pop(key, None)
        if lease is None:
            return
        if lease.renewer is not None:
            lease.renewer.cancel()
            try:
                await lease.renewer
            except (asyncio.CancelledError, Exception):
                pass
        try:
            await self.coord.delete(LEASES_PREFIX + key, expect=lease.token)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            # the lease simply expires after its TTL: waiters recover
            self._note_coord_error("lease_release", err)

    def lease_snapshot(self) -> List[str]:
        """Content keys this worker currently leads (for heartbeats and
        the admin API)."""
        return sorted(self._held)

    async def reclaim_own_leases(self) -> int:
        """Release leases a previous incarnation of this worker died
        holding (crash-recovery boot path, orchestrator ``_recover``).

        A lease owned by our ``worker_id`` that this process does not
        hold has no renewer — waiters would otherwise sit out the full
        TTL + takeover grace before failing over.  ``try_acquire_lease``
        already reclaims such an orphan when WE next want the content;
        this sweep handles the case where we never will, deleting the
        doc by CAS token so a racing peer takeover is never clobbered.
        Returns the number reclaimed; coordination trouble just stops
        the sweep (expiry remains the backstop).
        """
        reclaimed = 0
        try:
            for key, doc in await self._get_all(LEASES_PREFIX):
                content_key = key[len(LEASES_PREFIX):]
                if doc.get("owner") != self.worker_id:
                    continue
                if content_key in self._held:
                    continue  # live, renewed by this process
                entry = await self.coord.get(key)
                if entry is None or entry[0].get("owner") != self.worker_id:
                    continue  # raced: expired away or taken over
                if not await self.coord.delete(key, expect=entry[1]):
                    continue  # raced: a peer takeover rewrote the token
                reclaimed += 1
                if self.logger is not None:
                    self.logger.info("fleet: reclaimed orphan lease",
                                     key=content_key[:16])
        except asyncio.CancelledError:
            raise
        except Exception as err:
            self._note_coord_error("lease_reclaim", err)
        return reclaimed

    # -- shared cache tier ----------------------------------------------
    def _shared_name(self, key: str, rel: str = "") -> str:
        if rel:
            return posixpath.join(self.shared_prefix + key, "files", rel)
        return posixpath.join(self.shared_prefix + key, MANIFEST_NAME)

    def shared_name(self, key: str, rel: str = "") -> str:
        """Public object-name resolver for external walkers (the
        integrity scrubber re-hashes shared-tier payloads by name)."""
        return self._shared_name(key, rel)

    async def publish_entry(self, key: str, cache,
                            trace: Optional[dict] = None,
                            fence: Optional[int] = None) -> bool:
        """Spill the local cache entry for ``key`` to the shared tier.

        Payload objects first, ``manifest.json`` LAST — the manifest is
        the publish, exactly like the local cache's rename.  Idempotent:
        an existing manifest means a peer (or an earlier attempt)
        already published this content.  Best-effort: failures are
        logged and counted, never raised into the job.

        ``fence`` is the writer's lease fence.  The spill is FENCED:
        rejected before a single payload byte moves when a higher fence
        has been observed (a peer took over this lease while we were
        stalled — our entry is presumptively stale), stamped into the
        manifest, and read-back-verified after the publish so a
        concurrent newer writer's manifest is never mistaken for ours.
        """
        if self.store is None:
            return False
        try:
            raw = await self.store.get_object(
                self.shared_bucket, self._shared_name(key))
            try:
                self._observe_fence(key, _json_load(raw).get("fence"))
            except (ValueError, KeyError, TypeError):
                pass
            return True  # already published
        except ObjectNotFound:
            pass
        except Exception as err:
            self._note_coord_error("shared_probe", err)
            return False
        if fence is not None and not await self.fence_holds(key, fence):
            # a stale leader must lose BEFORE staging bytes: zero
            # payload objects land, not just a suppressed manifest
            self._note_fenced_write("shared_manifest", key, int(fence),
                                    self.observed_fence(key))
            return False
        try:
            async with cache.pinned(key):
                # pin BEFORE the lookup: the entry cannot be evicted
                # between reading its manifest and streaming its files
                entry = await cache.lookup(key)
                if entry is None:
                    return False
                src_dir = cache.entry_path(key)
                # consume=True where the store takes it: a sealed cache
                # entry is immutable (aliasing is all the contract
                # permits), so a co-located filesystem store ingests the
                # spill by hardlink — O(1) instead of a byte copy per
                # file.  Eviction later just unlinks the cache's name;
                # the store's link keeps the inode alive.
                spill_kwargs = (
                    {"consume": True}
                    if _fput_supports(self.store, "consume") else {})
                for rel in entry.files:
                    await self.store.fput_object(
                        self.shared_bucket, self._shared_name(key, rel),
                        os.path.join(src_dir, *rel.split("/")),
                        **spill_kwargs,
                    )
                manifest = {
                    "key": key,
                    "size": entry.size,
                    "files": list(entry.files),
                    "worker": self.worker_id,
                    "created": round(time.time(), 3),
                }
                if getattr(entry, "digests", None):
                    # per-file landing digests: fetchers verify BEFORE
                    # serving (a corrupt leader copy must not hand out
                    # bytes — or its inode), and the scrubber re-walks
                    # these forever
                    manifest["digests"] = dict(entry.digests)
                if fence is not None:
                    # the writer's authority, carried on the document
                    # so any reader (and the read-back below) can
                    # order competing publishes
                    manifest["fence"] = int(fence)
                if trace:
                    # the filling job's traceparent: peers materializing
                    # this entry can name the exact origin fetch (trace
                    # + worker) their bytes came from
                    manifest["trace"] = dict(trace)
                await self.store.put_object(
                    self.shared_bucket, self._shared_name(key),
                    _json_bytes(manifest),
                )
                if fence is not None:
                    # CAS-style read-verify (the nonce read-back
                    # posture): if a NEWER-fenced manifest shows on the
                    # read-back, our publish lost the race — count the
                    # save and report failure so nobody trusts our spill
                    raw = await self.store.get_object(
                        self.shared_bucket, self._shared_name(key))
                    try:
                        back = _json_load(raw)
                    except ValueError:
                        back = {}
                    back_fence = back.get("fence")
                    self._observe_fence(key, back_fence)
                    if (isinstance(back_fence, int)
                            and back_fence > int(fence)):
                        self._note_fenced_write(
                            "shared_manifest", key, int(fence),
                            back_fence)
                        return False
        except Exception as err:
            self._note_coord_error("shared_publish", err)
            return False
        self.stats["sharedFills"] += 1
        self.stats["sharedBytesOut"] += entry.size
        if self.metrics is not None:
            self.metrics.fleet_shared_fills.inc()
            self.metrics.fleet_shared_bytes.labels(
                direction="out").inc(entry.size)
        if self.logger is not None:
            self.logger.info("fleet: published cache entry to shared tier",
                             key=key[:16], bytes=entry.size)
        return True

    async def fetch_entry(self, key: str, cache, record=None) -> bool:
        """Materialize a shared-tier entry into the LOCAL cache.

        Streams the manifest's files into a pid-tagged staging dir on
        the cache volume (crash-orphans are swept by the cache's own
        startup policy) and fills via :meth:`ContentCache.insert`, so
        the job then hardlink-serves from the local cache like any warm
        hit.  False on miss or any trouble — never raises.
        """
        if self.store is None:
            return False
        try:
            raw = await self.store.get_object(
                self.shared_bucket, self._shared_name(key))
        except ObjectNotFound:
            return False
        except Exception as err:
            self._note_coord_error("shared_probe", err)
            return False
        try:
            manifest = _json_load(raw)
            files = list(manifest["files"])
        except (ValueError, KeyError, TypeError):
            if self.logger is not None:
                self.logger.warn("fleet: corrupt shared-tier manifest",
                                 key=key[:16])
            return False
        # remember the publisher's fence: a later stale write attempt
        # for this key is rejectable from the local memo alone
        self._observe_fence(key, manifest.get("fence"))
        if await cache.lookup(key) is not None:
            return True  # already local (a concurrent fill won)
        staging = os.path.join(
            cache.staging_dir,
            f"{key}.{os.getpid()}.fleet{os.urandom(3).hex()}",
        )
        # peer hardlink tier: a CO-LOCATED store (filesystem-backed,
        # same host/volume fleet) exposes the object's on-disk path —
        # materialize by hardlink, zero bucket round-trip and zero byte
        # movement.  Anything else (remote store, cross-device cache
        # volume, no-hardlink fs) streams a copy exactly as before.
        local_path = getattr(self.store, "local_object_path", None)

        def _materialize_linked(src: str, dst: str) -> bool:
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            try:
                os.link(src, dst)
                return True
            except OSError:
                # EXDEV / EPERM / EMLINK: the streaming path below is
                # the byte-exact fallback
                return False

        digests = manifest.get("digests")
        if not isinstance(digests, dict):
            digests = {}
        try:
            size = 0
            linked = 0
            for rel in files:
                parts = [p for p in rel.split("/")
                         if p not in ("", ".", "..")]
                if not parts:
                    continue
                local = os.path.join(staging, *parts)
                name = self._shared_name(key, rel)
                src = local_path(self.shared_bucket, name) \
                    if local_path is not None else None
                used_link = bool(
                    src is not None and await asyncio.to_thread(
                        _materialize_linked, src, local))
                if used_link:
                    linked += 1
                else:
                    await self.store.fget_object(
                        self.shared_bucket, name, local)
                want = digests.get(rel)
                if want is not None:
                    # integrity gate BEFORE the bytes become servable
                    # (and before cache.insert can hardlink them into
                    # workdirs): a corrupt leader copy falls back to
                    # the origin path, it never hands out its inode
                    mark = time.monotonic()
                    got_md5 = await asyncio.to_thread(md5_file_hex,
                                                      local)
                    if record is not None:
                        record.note_hop("hash", os.path.getsize(local),
                                        time.monotonic() - mark)
                    if got_md5 != want:
                        self.stats["sharedCorrupt"] += 1
                        if record is not None:
                            record.event("shared_corrupt", key=key[:16],
                                         rel=rel, linked=used_link)
                        if self.logger is not None:
                            self.logger.warn(
                                "fleet: shared-tier entry failed digest "
                                "verification, falling back to origin",
                                key=key[:16], rel=rel, linked=used_link)
                        return False
                size += os.path.getsize(local)
            entry = await cache.insert(key, staging, digests=digests)
        except Exception as err:
            self._note_coord_error("shared_fetch", err)
            return False
        finally:
            await asyncio.to_thread(shutil.rmtree, staging, True)
        got = entry.size if entry is not None else size
        if record is not None:
            # byte weight for the shared_fetch hop: coordinate() bills
            # the seconds, this note carries the bytes, and together the
            # ledger gets a real seconds-per-GB for peer materialization
            record.note_hop("shared_fetch", got, 0.0)
        if record is not None:
            # provenance on the waiter's own timeline: whose origin
            # fetch (worker + trace) these bytes actually came from
            origin = {"worker": manifest.get("worker")}
            remote = parse_traceparent(
                (manifest.get("trace") or {}).get("traceparent"))
            if remote is not None:
                origin["originTraceId"] = remote.trace_id
                origin["originJobId"] = (manifest.get("trace")
                                         or {}).get("jobId")
            record.event("shared_origin", key=key[:16], bytes=got,
                         linked=linked, **origin)
        self.stats["sharedHits"] += 1
        self.stats["sharedBytesIn"] += got
        if self.metrics is not None:
            self.metrics.fleet_shared_hits.inc()
            self.metrics.fleet_shared_bytes.labels(
                direction="in").inc(got)
        if self.logger is not None:
            self.logger.info("fleet: materialized shared-tier entry",
                             key=key[:16], bytes=got)
        return True

    # -- cross-worker trace digests -------------------------------------
    def _digest(self, record) -> dict:
        """One settled job's compact timeline digest — the document the
        cross-worker trace assembly (control/trace.py) joins with the
        other workers' segments.  Bounded: the event tail is capped at
        :data:`DIGEST_EVENT_LIMIT` (events are already small, truncated
        dicts), so a digest stays a few KB."""
        hops = getattr(record, "hops", None)
        digest = {
            "traceId": record.trace_id,
            "spanId": record.span_id,
            "jobId": record.job_id,
            "workerId": self.worker_id,
            "state": record.state,
            "stage": record.stage,
            "stageSeconds": {k: round(v, 3)
                             for k, v in record.stage_seconds.items()},
            "hopLedger": hops.summary() if hops is not None and hops
            else None,
            "events": record.recorder.tail(DIGEST_EVENT_LIMIT),
            "settledAt": round(time.time(), 3),
        }
        fence = getattr(record, "fleet_fence", None)
        if fence:
            # the lease fence this job's authority derived from: a
            # stale leader's late digest must not clobber the digest
            # the real (higher-fenced) settle already published
            digest["fence"] = int(fence)
        return digest

    async def publish_telemetry(self, record) -> bool:
        """Publish a settled job's timeline digest to the coordination
        store at ``telemetry/<trace_id>/<worker_id>/<job_id>``.

        Keyed per JOB: a submitter may stamp one traceparent across a
        whole batch, and one worker settling several of those jobs must
        not clobber its earlier digests in a shared per-worker slot.
        Best-effort (a digest is observability, never worth a job or a
        settle delay — the orchestrator fires this as a detached task)
        and bounded: digests age out of the store after
        ``fleet.telemetry_ttl`` via the fleet GC sweep.
        """
        trace_id = getattr(record, "trace_id", None)
        if self.telemetry_ttl <= 0 or not trace_id:
            return False
        key = (f"{TELEMETRY_PREFIX}{trace_id}/{self.worker_id}/"
               f"{record.job_id}")
        fence = getattr(record, "fleet_fence", None)
        content_key = getattr(record, "fleet_fence_key", None)
        if fence and content_key and not await self.fence_holds(
                content_key, fence):
            # a stale leader's settle: its timeline describes work a
            # higher-fenced peer superseded — reject rather than
            # present split-brain observability as truth
            self._note_fenced_write("telemetry", content_key,
                                    int(fence),
                                    self.observed_fence(content_key))
            return False
        try:
            # unconditional otherwise: this worker owns its own digest
            # slot, and a redelivered job's later settle should win
            await self.coord.put(key, self._digest(record), expect=ANY)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            if self.metrics is not None:
                self.metrics.fleet_telemetry.labels(op="error").inc()
            self._note_coord_error("telemetry_publish", err)
            return False
        self.stats["telemetryPublished"] += 1
        if self.metrics is not None:
            self.metrics.fleet_telemetry.labels(op="published").inc()
        return True

    async def fetch_telemetry(self, trace_id: str) -> List[dict]:
        """Every worker's digest for ``trace_id`` (empty when none).
        Coordination trouble RAISES — the trace assembler downgrades to
        its local-only view and says so, instead of silently presenting
        a partial fleet picture as complete."""
        docs = [doc for _key, doc in await self._get_all(
            TELEMETRY_PREFIX + trace_id + "/")]
        if self.metrics is not None and docs:
            self.metrics.fleet_telemetry.labels(
                op="fetched").inc(len(docs))
        return docs

    # -- fleet overview --------------------------------------------------
    def overview_age(self) -> Optional[float]:
        """Seconds since the overview doc this worker last published or
        read was written (wall clocks — heartbeats already compare
        them); None until any overview has been seen.  The
        ``fleet_overview_age_seconds`` gauge's source: in steady state
        every worker refreshes its stamp each heartbeat, so a climbing
        age means the aggregation (or the coordination store) stalled.
        """
        if self._overview_updated_at is None:
            return None
        return max(time.time() - self._overview_updated_at, 0.0)

    def _note_overview(self, doc: Optional[dict]) -> None:
        if doc is None:
            return
        try:
            self._overview_updated_at = float(doc.get("updatedAt", 0))
        except (TypeError, ValueError):
            pass

    async def _overview_tick(self) -> None:
        """One heartbeat's worth of overview work.

        Cheap-by-default election (the PR 7 GC-sweeper discipline,
        without paying a membership listing on every worker every
        beat): read the one overview doc first — if it is FRESH and
        someone else wrote it, this worker's job is just to note the
        age.  Only when the doc is stale/absent (the aggregator died)
        or this worker wrote it last does it pay the listing, re-check
        the oldest-live-worker election, and fold.  Self-stabilizing:
        an aggregator's death makes the doc stale within ~2 beats,
        every survivor then runs the election, the oldest wins, the
        rest settle back to one GET per beat.
        """
        doc = await self._overview_read_cached()
        self._note_overview(doc)
        if doc is not None and doc.get("updatedBy") != self.worker_id:
            age = time.time() - float(doc.get("updatedAt", 0) or 0)
            if age < 2.0 * self.heartbeat_interval:
                return  # a live aggregator owns it
        workers = await self.workers()
        if not workers or workers[0].get("workerId") != self.worker_id:
            # not the oldest live worker — or an EMPTY liveness view
            # (our own registration failed, or a partition/clock issue
            # expired every heartbeat doc): stand down rather than
            # have every worker "win" the election and publish an
            # empty-members overview each beat mid-incident.  The doc
            # just ages, which the staleness gauge surfaces honestly.
            return
        fresh = build_overview(self.worker_id, workers)
        await self.coord.put(OVERVIEW_KEY, fresh, expect=ANY)
        self._overview_doc = fresh
        self._note_overview(fresh)

    async def _overview_read_cached(self) -> Optional[dict]:
        """The overview doc via the watch plane.

        Drains pending change events into the local cache (free on the
        event-driven backend, one bounded scan on the poll-watch one)
        instead of a fresh GET per read; falls back to the direct GET —
        the degraded poll path, counted on
        ``fleet_watch_wakeups_total{mode="poll"}`` — whenever the watch
        is unavailable or broke.  Store trouble on that fallback RAISES
        exactly like the read this replaced.
        """
        if self._overview_watch is None and self.watch_enabled:
            watch = self._open_watch(OVERVIEW_KEY)
            if watch is not None:
                self._overview_watch = watch
                try:
                    # read-then-watch: arm the snapshot, seed the cache
                    # once, then live on change events alone
                    await watch.next(0)
                    entry = await self.coord.get(OVERVIEW_KEY)
                    self._overview_doc = (entry[0] if entry is not None
                                          else None)
                    return self._overview_doc
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    self._note_coord_error("watch", err)
                    watch.close()
                    self._overview_watch = None
        events = await self._drain_watch(self._overview_watch)
        if events is None:
            if self._overview_watch is not None:
                self._overview_watch.close()
                self._overview_watch = None
            self._note_watch_wakeup("poll")
            entry = await self.coord.get(OVERVIEW_KEY)
            self._overview_doc = entry[0] if entry is not None else None
            return self._overview_doc
        for event in events:
            if event.key == OVERVIEW_KEY:
                self._overview_doc = event.data
        if events:
            self._note_watch_wakeup("event")
        return self._overview_doc

    async def fetch_overview(self) -> Optional[dict]:
        """The current fleet-overview doc (None when absent), bounded
        by :data:`OVERVIEW_FETCH_BUDGET` — a browned-out coordination
        store costs one bounded wait, never a hung admin read.  Served
        through the watch plane's cache (a quiet watch costs zero store
        round trips on the event-driven backend); raises on
        coordination trouble when the degraded read path has to run
        (incl. the budget expiring): the endpoint downgrades to its
        local view and says so, the trace-assembly degradation
        contract."""
        async with asyncio.timeout(OVERVIEW_FETCH_BUDGET):
            doc = await self._overview_read_cached()
        self._note_overview(doc)
        return doc

    def cached_overview(self, max_age: Optional[float] = None
                        ) -> Optional[dict]:
        """The watch-cached overview doc when fresh enough (default
        bound: 4x the heartbeat interval), else None — the router's
        zero-RTT read; staleness degrades to 'no fleet view', never to
        acting on history."""
        doc = self._overview_doc
        if doc is None:
            return None
        if max_age is None:
            max_age = 4.0 * self.heartbeat_interval
        try:
            age = time.time() - float(doc.get("updatedAt", 0) or 0)
        except (TypeError, ValueError):
            return None
        return doc if age <= max_age else None

    # -- watch-fed views (the router/controller's zero-RTT reads) -------
    async def _refresh_views(self) -> None:
        """One heartbeat's refresh of the lease and plan caches.

        The content router consults both at ADMISSION — once per
        delivery — so they must never cost a store round trip there.
        Instead the heartbeat drains each watch non-blockingly (free on
        the event-driven backend, one bounded scan on the poll-watch
        one) and admission reads plain dicts.  No watch — disabled,
        refused, or broken — degrades to one listing/GET per beat: the
        poll path, counted, never a routing failure.
        """
        await self._refresh_lease_view()
        await self._refresh_plan_view()

    async def _refresh_lease_view(self) -> None:
        opened = False
        if self._lease_watch is None and self.watch_enabled:
            self._lease_watch = self._open_watch(LEASES_PREFIX)
            if self._lease_watch is not None:
                opened = True
                try:
                    await self._lease_watch.next(0)  # arm the snapshot
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    self._note_coord_error("watch", err)
                    self._lease_watch.close()
                    self._lease_watch = None
                    opened = False
        if not opened and self._lease_watch is not None:
            events = await self._drain_watch(self._lease_watch)
            if events is None:
                self._lease_watch.close()
                self._lease_watch = None
            elif events:
                for event in events:
                    ckey = event.key[len(LEASES_PREFIX):]
                    if event.data is None:
                        self._lease_view.pop(ckey, None)
                    else:
                        self._lease_view[ckey] = event.data
                self._lease_view_ready = True
                self._note_watch_wakeup("event")
                return
            elif self._lease_view_ready:
                return  # watch alive and quiet: the cache is current
        # (re)seed: no watch, a broken one, or one just opened — one
        # listing rebuilds the whole view (read-then-watch / poll path)
        self._lease_view = {
            key[len(LEASES_PREFIX):]: doc
            for key, doc in await self._get_all(LEASES_PREFIX)
        }
        self._lease_view_ready = True
        if self._lease_watch is None:
            self._note_watch_wakeup("poll")

    async def _refresh_plan_view(self) -> None:
        opened = False
        if self._plan_watch is None and self.watch_enabled:
            self._plan_watch = self._open_watch(PLAN_KEY)
            if self._plan_watch is not None:
                opened = True
                try:
                    await self._plan_watch.next(0)  # arm the snapshot
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    self._note_coord_error("watch", err)
                    self._plan_watch.close()
                    self._plan_watch = None
                    opened = False
        if not opened and self._plan_watch is not None:
            events = await self._drain_watch(self._plan_watch)
            if events is None:
                self._plan_watch.close()
                self._plan_watch = None
            else:
                for event in events:
                    if event.key == PLAN_KEY:
                        self._plan_doc = event.data
                if events:
                    self._note_watch_wakeup("event")
                self._note_plan_age()
                return
        entry = await self.coord.get(PLAN_KEY)
        self._plan_doc = entry[0] if entry is not None else None
        if self._plan_watch is None:
            self._note_watch_wakeup("poll")
        self._note_plan_age()

    def _note_plan_age(self) -> None:
        if self.metrics is None:
            return
        doc = self._plan_doc
        if doc is None:
            self.metrics.fleet_plan_age.set(-1.0)
            return
        try:
            age = max(time.time() - float(doc.get("updatedAt", 0) or 0),
                      0.0)
        except (TypeError, ValueError):
            return
        self.metrics.fleet_plan_age.set(age)

    def current_plan(self, max_age: Optional[float] = None
                     ) -> Optional[dict]:
        """The controller's latest plan doc from the watch-fed cache —
        None when absent or older than ``max_age`` (default 4x the
        heartbeat interval): a controller that stopped planning must
        not steer admission forever on history."""
        doc = self._plan_doc
        if doc is None:
            return None
        if max_age is None:
            max_age = 4.0 * self.heartbeat_interval
        try:
            age = time.time() - float(doc.get("updatedAt", 0) or 0)
        except (TypeError, ValueError):
            return None
        return doc if age <= max_age else None

    def plan_in_force(self) -> Optional[dict]:
        """The plan doc to attribute admissions to: the fresh plan when
        the controller is live, else the last cached doc (a stale plan
        no longer STEERS admission, but it is still the right answer to
        "what plan was in force" for forensic stamping — incident
        bundles and ``slo_breach`` placement context, ISSUE 18)."""
        fresh = self.current_plan()
        return fresh if fresh is not None else self._plan_doc

    def plan_epoch(self) -> Optional[int]:
        """The epoch of the plan in force, or None before any plan."""
        doc = self.plan_in_force()
        if doc is None:
            return None
        epoch = doc.get("epoch")
        try:
            return int(epoch)
        except (TypeError, ValueError):
            return None

    def route_holder(self, route_key: str) -> Optional[dict]:
        """The live lease doc whose ``routeKey`` matches, served from
        the watch-fed cache (zero store RTTs at admission); None when
        no live holder is known — including before the first view
        refresh, when deferring on a guess would be wrong both ways."""
        if not route_key or not self._lease_view_ready:
            return None
        now = time.time()
        grace = self.lease_ttl * TAKEOVER_GRACE_FRAC
        for ckey, doc in self._lease_view.items():
            if doc.get("routeKey") != route_key:
                continue
            if float(doc.get("expiresAt", 0) or 0) + grace < now:
                continue  # expired: the holder is dead or done
            out = dict(doc)
            out["key"] = ckey
            return out
        return None

    # -- fleet-shared origin-health table -------------------------------
    async def _origin_health_tick(self) -> None:
        """Merge this worker's per-origin EWMAs into the shared table
        every ``fleet.origin_share_interval`` seconds (heartbeat-driven,
        so the cadence floor is the heartbeat interval)."""
        if self.origin_fn is None or self.origin_share_interval <= 0:
            return
        now = time.monotonic()
        if now - self._origin_shared_mono < self.origin_share_interval:
            return
        self._origin_shared_mono = now
        try:
            snapshot = dict(self.origin_fn())
        except Exception as err:  # a bad snapshot must not kill beats
            self._note_coord_error("origin_snapshot", err)
            return
        if snapshot:
            await self.publish_origin_health(snapshot)

    async def publish_origin_health(self, snapshot: Dict[str, dict]
                                    ) -> bool:
        """CAS-merge per-origin throughput rows into ``origins/health``.

        The table has no lease, so the doc's write token IS the fence:
        read, merge newest-observation-wins per origin label, write
        back conditional on the token read.  A lost race re-reads and
        re-merges (bounded laps) — two workers merging concurrently
        both land, neither clobbers.  Best-effort like all coordination:
        False on trouble, never a raised error.
        """
        now = round(time.time(), 3)
        rows: Dict[str, dict] = {}
        for label, row in snapshot.items():
            try:
                rows[str(label)] = {
                    "bps": float(row.get("bps", 0.0) or 0.0),
                    "bytes": int(row.get("bytes", 0) or 0),
                    "at": now,
                    "by": self.worker_id,
                }
            except (TypeError, ValueError, AttributeError):
                continue
        if not rows:
            return False
        try:
            for _ in range(4):
                entry = await self.coord.get(ORIGIN_HEALTH_KEY)
                merged: Dict[str, dict] = {}
                if entry is not None:
                    current = entry[0].get("labels")
                    if isinstance(current, dict):
                        merged.update(current)
                for label, row in rows.items():
                    have = merged.get(label)
                    try:
                        have_at = float((have or {}).get("at", 0) or 0)
                    except (TypeError, ValueError):
                        have_at = 0.0
                    if have is None or have_at <= row["at"]:
                        merged[label] = row
                doc = {"labels": merged, "updatedAt": now,
                       "updatedBy": self.worker_id}
                expect = entry[1] if entry is not None else ABSENT
                if await self.coord.put(ORIGIN_HEALTH_KEY, doc,
                                        expect=expect) is not None:
                    self.stats["originHealthShared"] += 1
                    if self.metrics is not None:
                        self.metrics.fleet_origin_health.labels(
                            op="published").inc()
                    return True
        except asyncio.CancelledError:
            raise
        except Exception as err:
            self._note_coord_error("origin_health", err)
            return False
        self._note_coord_error(
            "origin_health",
            CoordError("origin-health CAS merge: retries exhausted"))
        return False

    async def fetch_origin_health(
            self, max_age: float = ORIGIN_HEALTH_MAX_AGE
    ) -> Dict[str, dict]:
        """Fleet origin-health rows fresh enough to seed a booting
        worker's OriginHealth ({} on any trouble — the seed is a
        best-effort head start, never worth delaying boot)."""
        try:
            entry = await self.coord.get(ORIGIN_HEALTH_KEY)
        except asyncio.CancelledError:
            raise
        except Exception as err:
            self._note_coord_error("origin_health", err)
            return {}
        if entry is None:
            return {}
        labels = entry[0].get("labels")
        if not isinstance(labels, dict):
            return {}
        now = time.time()
        out: Dict[str, dict] = {}
        for label, row in labels.items():
            try:
                if (max_age > 0
                        and now - float(row.get("at", 0) or 0) > max_age):
                    continue  # stale history, not a head start
                out[str(label)] = dict(row)
            except (TypeError, ValueError, AttributeError):
                continue
        if out and self.metrics is not None:
            self.metrics.fleet_origin_health.labels(op="seeded").inc()
        return out

    # -- shared-tier / tombstone GC -------------------------------------
    async def _should_gc(self) -> bool:
        """Elect one sweeper per interval: the OLDEST live worker.

        Every worker running the identical global sweep would multiply
        the same listing + per-key reads N times for no extra garbage
        collected; the registry's liveness view is already a cheap,
        crash-tolerant election (the oldest worker dying just hands the
        sweep to the next-oldest within liveness_ttl).  Solo workers —
        and workers that cannot read the registry at all — sweep: a
        degraded registry must not also mean unbounded garbage.
        """
        try:
            live = await self.workers()
        except Exception:
            return True
        if not live:
            return True
        return live[0].get("workerId") == self.worker_id

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gc_interval)
            try:
                if await self._should_gc():
                    await self.gc_once()
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self._note_coord_error("gc", err)

    async def _remove_entry(self, key: str, names_sizes) -> int:
        """Evict one shared-tier entry: manifest FIRST (unpublish — a
        reader mid-materialize already holds the file list and tolerates
        missing objects as a failed fetch), then the payload objects.
        Returns the bytes reclaimed."""
        reclaimed = 0
        manifest_name = self._shared_name(key)
        ordered = sorted(names_sizes, key=lambda ns: ns[0] != manifest_name)
        for name, size in ordered:
            await self.store.remove_object(self.shared_bucket, name)
            reclaimed += size
        return reclaimed

    async def gc_once(self) -> dict:
        """One bounded sweep over the shared tier + coordination prefix.

        - evicts ``.fleet-cache/<key>/`` entries whose manifest is older
          than ``fleet.shared_max_age``, then (oldest first) until total
          size fits ``fleet.shared_max_bytes`` (0 = age bound only);
        - reclaims manifest-less entries (torn spills) seen on two
          consecutive sweeps — listings carry no mtime, so "survived a
          full gc_interval without a manifest" is the abandonment proxy;
        - compacts aged ``.fleet/`` tombstones on the bucket coordination
          backend (deletes there only tombstone, so churned lease/worker
          keys otherwise accrete forever).

        Never raises on store backends without delete support — the
        sweep is then a no-op.  Entries under a live content lease —
        this worker's or a peer's (a slow multi-GB spill is manifest-
        less for its whole upload) — are skipped.
        """
        out = {"shared_evicted": 0, "bytes_reclaimed": 0, "tombstones": 0,
               "telemetry": 0}
        if self.store is not None:
            try:
                entries: Dict[str, list] = {}
                async for info in self.store.list_objects(
                        self.shared_bucket, self.shared_prefix):
                    rest = info.name[len(self.shared_prefix):]
                    key = rest.split("/", 1)[0]
                    if key:
                        entries.setdefault(key, []).append(
                            (info.name, info.size))
                # keys under a LIVE content lease are being re-fetched /
                # re-published by some worker right now: never reclaim
                # them mid-flight (the torn-spill heuristic especially —
                # a peer's slow multi-GB spill is manifest-less for its
                # whole upload).  Lease trouble — e.g. an asymmetric
                # partition where shared-tier reads work but the
                # coordination prefix does not — means we CANNOT know
                # what peers hold: skip this sweep's eviction pass
                # entirely rather than treat every key as unleased and
                # evict a live peer's in-flight spill.  Garbage waits
                # one interval; destroyed peer work does not come back.
                try:
                    leased = {doc.get("key") for doc in await self.leases()
                              if not doc.get("expired")}
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    self._note_coord_error("gc_lease_view", err)
                    raise _GcLeaseViewUnavailable from err
                now = time.time()
                # manifest "created" stamps are immutable once published:
                # remember them across sweeps so a steady-state sweep is
                # one LIST + GETs only for newly-appeared keys
                created_memo = self._gc_created
                aged: "List[tuple[float, str]]" = []  # (created, key)
                manifestless: set = set()
                for key, names_sizes in entries.items():
                    if key in self._held or key in leased:
                        continue  # mid-publish (ours or a peer's)
                    manifest_name = self._shared_name(key)
                    if not any(n == manifest_name for n, _s in names_sizes):
                        manifestless.add(key)
                        continue
                    created = created_memo.get(key)
                    if created is None:
                        try:
                            manifest = _json_load(
                                await self.store.get_object(
                                    self.shared_bucket, manifest_name))
                            created = float(manifest.get("created", 0.0))
                        except (ValueError, KeyError, TypeError):
                            created = 0.0  # CORRUPT manifest: ancient
                        except Exception:
                            # store trouble reading a healthy-looking
                            # manifest must not read as "ancient" and
                            # evict good bytes: skip it this sweep
                            continue
                        created_memo[key] = created
                    aged.append((created, key))
                # drop memo entries for keys no longer listed
                self._gc_created = {k: v for k, v in created_memo.items()
                                    if k in entries}
                evict: List[str] = []
                kept: List[tuple] = []
                for created, key in sorted(aged):
                    if (self.shared_max_age > 0
                            and now - created >= self.shared_max_age):
                        evict.append(key)
                    else:
                        kept.append((created, key))
                if self.shared_max_bytes > 0:
                    total = sum(
                        sum(s for _n, s in entries[key])
                        for _c, key in kept
                    )
                    for _created, key in kept:  # oldest first
                        if total <= self.shared_max_bytes:
                            break
                        evict.append(key)
                        total -= sum(s for _n, s in entries[key])
                # torn spills: reclaim only on the second consecutive
                # manifest-less sighting
                evict.extend(k for k in manifestless
                             if k in self._gc_manifestless)
                self._gc_manifestless = manifestless
                for key in evict:
                    try:
                        reclaimed = await self._remove_entry(
                            key, entries[key])
                    except NotImplementedError:
                        break  # backend cannot delete: GC is a no-op
                    out["shared_evicted"] += 1
                    out["bytes_reclaimed"] += reclaimed
                    if self.logger is not None:
                        self.logger.info("fleet gc: evicted shared entry",
                                         key=key[:16], bytes=reclaimed)
            except asyncio.CancelledError:
                raise
            except _GcLeaseViewUnavailable:
                pass  # noted as gc_lease_view; eviction waits a sweep
            except Exception as err:
                self._note_coord_error("gc_shared", err)
        # per-job trace digests: every settled job writes one, so without
        # this sweep the telemetry prefix grows one doc per job forever.
        # A digest's useful life is an incident window, not an archive —
        # aged ones are deleted (token-CAS, so a concurrent republish
        # from a redelivery is never clobbered).  Swept at the DEFAULT
        # ttl even by a worker whose own publishing is off
        # (telemetry_ttl 0): peers may still publish, and the elected
        # sweeper is the only one who ever cleans up after them.
        telemetry_ttl = (self.telemetry_ttl if self.telemetry_ttl > 0
                         else DEFAULT_TELEMETRY_TTL)
        try:
            now = time.time()
            for key in await self.coord.list_keys(TELEMETRY_PREFIX):
                entry = await self.coord.get(key)
                if entry is None:
                    continue
                doc, token = entry
                if now - float(doc.get("settledAt", 0) or 0) \
                        < telemetry_ttl:
                    continue
                if await self.coord.delete(key, expect=token):
                    out["telemetry"] += 1
        except asyncio.CancelledError:
            raise
        except Exception as err:
            self._note_coord_error("gc_telemetry", err)
        # coordination-store census (``fleet_coord_docs_total{prefix}``):
        # sampled here — post-sweep, by the elected sweeper only — so the
        # growth gauges cost list RTTs once per gc_interval, never per
        # scrape.  A census failure degrades to stale gauges, not a
        # failed sweep.
        if self.metrics is not None:
            for prefix in (WORKERS_PREFIX, LEASES_PREFIX,
                           TELEMETRY_PREFIX):
                try:
                    docs = len(await self.coord.list_keys(prefix))
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    self._note_coord_error("gc_census", err)
                    break
                self.metrics.coord_docs.labels(
                    prefix=prefix.rstrip("/")).set(docs)
        sweep = getattr(self.coord, "sweep_tombstones", None)
        if sweep is not None:
            # a tombstone is compactable once every CAS that could have
            # referenced its token has aged out with the lease/liveness
            # TTLs; 4x the larger one is comfortably past any skew grace
            try:
                out["tombstones"] = await sweep(
                    max(self.lease_ttl, self.liveness_ttl) * 4
                )
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self._note_coord_error("gc_tombstones", err)
        self.stats["gcSharedEvicted"] += out["shared_evicted"]
        self.stats["gcBytesReclaimed"] += out["bytes_reclaimed"]
        self.stats["gcTombstonesCompacted"] += out["tombstones"]
        self.stats["gcTelemetryEvicted"] += out["telemetry"]
        if self.metrics is not None:
            if out["shared_evicted"]:
                self.metrics.fleet_gc_removed.labels(
                    kind="shared_entry").inc(out["shared_evicted"])
            if out["tombstones"]:
                self.metrics.fleet_gc_removed.labels(
                    kind="tombstone").inc(out["tombstones"])
            if out["telemetry"]:
                self.metrics.fleet_gc_removed.labels(
                    kind="telemetry").inc(out["telemetry"])
            if out["bytes_reclaimed"]:
                self.metrics.fleet_gc_bytes.inc(out["bytes_reclaimed"])
        return out

    # -- the cross-worker singleflight protocol -------------------------
    async def coordinate(self, key: str, cache, origin_fill, *,
                         cancel=None, record=None, registry=None,
                         slot=None, logger=None,
                         route_key: Optional[str] = None) -> str:
        """Fetch-or-wait for content ``key`` fleet-wide.

        ``origin_fill`` is the caller's fetch-and-fill-local-cache
        coroutine factory; it runs iff this worker wins the lease.
        Returns :data:`LED` (we fetched and spilled), :data:`SHARED`
        (a peer's bytes are now in the LOCAL cache — the caller
        materializes from there), or :data:`UNCOORDINATED`
        (coordination unavailable / wait bound hit: the caller fetches
        alone).  Coordination-store trouble can never raise out of
        here; ``origin_fill``'s own errors propagate (they are job
        errors, and the lease is released so a peer takes over).

        A waiter is pure idle time, so alongside the PARKED transition
        it gives back its run slot (``slot`` — a
        :class:`~..control.scheduler.RunSlot`) for runnable jobs and
        reacquires it before resuming.  The *delivery* stays unsettled
        throughout: with ``scheduler_backlog`` 0 and one run slot the
        broker's prefetch window still serializes behind the waiter —
        fan-in deployments size ``max_concurrent_jobs``/backlog for it.
        """
        log = logger or self.logger
        # the livelock bound is a per-JOB budget, not per-attempt: a
        # flapping coordination store used to re-park every redelivery
        # with a fresh max_wait, so the bound never bound.  The record
        # carries the cumulative parked time across coordination errors
        # and redeliveries; an exhausted budget skips parking entirely.
        already_waited = float(getattr(record, "fleet_waited_s", 0.0)
                               or 0.0)
        deadline = time.monotonic() + max(
            self.max_wait - already_waited, 0.0)
        # coordination attribution (the soak's hop-ledger
        # reconciliation flushed this out): lease acquire/release, the
        # shared-entry probe, and shared-tier transfers are real
        # wall-clock inside the download stage — unbilled, they made a
        # coordinated job's ledger account for a fraction of its stage
        # wall.  Three seconds-only hops, by what the time actually
        # was: ``coord`` = the lease ceremony + probe misses (moves no
        # payload bytes, like origin_wait), ``shared_fetch`` = a
        # waiter materializing a peer's content from the shared tier,
        # ``shared_spill`` = the leader publishing its entry there
        # (byte counts for both ride fleet_shared_tier_bytes_total).
        hop_seconds: Dict[str, float] = {}

        def _bill(hop: str, seconds: float) -> None:
            hop_seconds[hop] = hop_seconds.get(hop, 0.0) + seconds

        async def _billed(coro, hop="coord"):
            t0 = time.monotonic()
            try:
                return await coro
            finally:
                _bill(hop, time.monotonic() - t0)

        # the job's W3C trace context rides the lease doc and the
        # shared-tier manifest, so waiters (and later trace assembly)
        # can join this fetch to the trace that caused it
        trace = self._trace_context(record)
        try:
            return await self._coordinate(
                key, cache, origin_fill, cancel=cancel, record=record,
                registry=registry, slot=slot, log=log,
                deadline=deadline, trace=trace, billed=_billed,
                bill=_bill, route_key=route_key)
        finally:
            if record is not None:
                for hop, seconds in hop_seconds.items():
                    if seconds > 0:
                        record.note_hop(hop, 0, seconds)

    async def _coordinate(self, key, cache, origin_fill, *, cancel,
                          record, registry, slot, log, deadline, trace,
                          billed, bill, route_key=None):
        parked = False
        waited = False
        wait_started = None  # first parked wait: the aging clock starts
        lease_watch: Optional[CoordWatch] = None
        try:
            while True:
                try:
                    # 1) a finished leader's bytes beat any lease dance
                    # (a HIT transfers the peer's content — billed as
                    # shared_fetch, not coordination ceremony; the
                    # cheap miss probe stays on the coord hop)
                    probe_started = time.monotonic()
                    hit = await self.fetch_entry(key, cache,
                                                 record=record)
                    bill("shared_fetch" if hit else "coord",
                         time.monotonic() - probe_started)
                    if hit:
                        if record is not None:
                            record.event("fleet", outcome="shared",
                                         key=key[:16])
                        return SHARED
                    # 2) contend for the content lease
                    lease = await billed(self._coord_op(
                        "coord.lease",
                        lambda: self.try_acquire_lease(
                            key, trace, route_key=route_key),
                        cancel=cancel,
                    ))
                except (JobCancelled, asyncio.CancelledError):
                    raise  # cancellation settles the job, not the fleet
                except Exception as err:
                    # CoordError, an open "coord" breaker, anything the
                    # store threw raw: degrade, never fail the job
                    self._note_coord_error("lease_acquire", err)
                    self.stats["uncoordinatedFallbacks"] += 1
                    if record is not None:
                        record.event("fleet", outcome="uncoordinated",
                                     key=key[:16])
                    return UNCOORDINATED
                if lease is not None:
                    break  # we lead
                # 3) a live peer leads: park and poll for its publish
                if not waited:
                    waited = True
                    self.stats["leaseWaits"] += 1
                    if self.metrics is not None:
                        self.metrics.fleet_lease_waits.inc()
                    if record is not None:
                        # name the leader this wait is actually behind:
                        # its worker id and — when the lease doc carries
                        # a traceparent — the leader job's trace id, the
                        # link GET /v1/trace follows to merge the
                        # leader's fetch into this waiter's timeline
                        leader_fields: Dict[str, Any] = {}
                        try:
                            entry = await self.coord.get(
                                LEASES_PREFIX + key)
                        except Exception:
                            entry = None  # wait event still emits bare
                        if entry is not None:
                            doc = entry[0]
                            self._observe_fence(key, doc.get("fence"))
                            leader_fields["leaderWorker"] = doc.get(
                                "owner")
                            remote = parse_traceparent(
                                (doc.get("trace") or {}).get(
                                    "traceparent"))
                            if remote is not None:
                                leader_fields["leaderTraceId"] = \
                                    remote.trace_id
                                leader_fields["leaderJobId"] = (
                                    doc.get("trace") or {}).get("jobId")
                        record.event("fleet", outcome="wait",
                                     key=key[:16], **leader_fields)
                if not parked and record is not None and registry is not None:
                    parked = True
                    if self.metrics is not None:
                        self.metrics.jobs_parked.labels(reason="fleet").inc()
                    registry.transition(
                        record, "PARKED",
                        reason=f"fleet_lease_wait: {key[:16]}",
                    )
                    if slot is not None:
                        # idle wait: a runnable job must not queue
                        # behind it (same discipline as the delayed-
                        # redelivery park)
                        slot.release()
                if time.monotonic() >= deadline:
                    if log is not None:
                        log.warn("fleet: lease wait bound hit, fetching "
                                 "uncoordinated", key=key[:16])
                    self.stats["uncoordinatedFallbacks"] += 1
                    if record is not None:
                        record.event("fleet", outcome="wait_timeout",
                                     key=key[:16])
                    return UNCOORDINATED
                if wait_started is None:
                    wait_started = time.monotonic()
                    if self.watch_enabled and lease_watch is None:
                        # subscribe to the ONE lease doc this wait is
                        # parked on: the leader's release wakes the
                        # waiter immediately instead of on the next
                        # poll lap (None = watch refused: sleep-poll)
                        lease_watch = self._open_watch(
                            LEASES_PREFIX + key)
                waiter = self._waiter_wait(lease_watch, deadline)
                if cancel is not None:
                    lease_watch = await cancel.guard(waiter)
                else:
                    lease_watch = await waiter
        finally:
            if lease_watch is not None:
                lease_watch.close()
            if record is not None and wait_started is not None:
                # age the per-job wait budget on EVERY exit — lease won,
                # degraded to uncoordinated, timed out, cancelled — so
                # the next attempt (after a flap or redelivery) resumes
                # the countdown instead of restarting it
                record.fleet_waited_s = (
                    float(getattr(record, "fleet_waited_s", 0.0) or 0.0)
                    + (time.monotonic() - wait_started))
            if parked:
                try:
                    if slot is not None:
                        # queue for a run slot again (priority + aging
                        # apply as usual) before resuming the stage; a
                        # cancellation here still closes the record via
                        # the transition below + the orchestrator
                        if cancel is not None:
                            await cancel.guard(slot.reacquire())
                        else:
                            await slot.reacquire()
                finally:
                    # back to RUNNING under the stage we parked in (the
                    # PARKED -> RUNNING edge exists for exactly this
                    # resume)
                    registry.transition(record, "RUNNING",
                                        stage=record.stage)
        # -- leader path --------------------------------------------------
        if record is not None:
            # the fence this job's write authority derives from: rides
            # the record into the shared-tier spill, the done-marker
            # seal (stages/upload.py), and the telemetry digest
            record.fleet_fence = lease.fence
            record.fleet_fence_key = key
            record.event("fleet", outcome="lead", key=key[:16],
                         fence=lease.fence)
        try:
            await origin_fill()
            await billed(self.publish_entry(key, cache, trace=trace,
                                            fence=lease.fence),
                         "shared_spill")
        finally:
            await billed(self.release_lease(key))
        return LED


def build_overview(worker_id: str, workers: List[dict]) -> dict:
    """Fold live worker heartbeat docs into the one fleet-overview doc.

    Pure (unit-testable without a store).  Rolling-upgrade tolerant by
    contract: a worker publishing the pre-digest heartbeat shape is
    listed with ``digest: null`` and simply contributes nothing to the
    digest-derived totals — a mixed fleet aggregates, never errors.

    Totals:
    - ``queueDepth``/``activeJobs`` — summed autoscale signals;
    - ``tenantQueued``/``tenantShares`` — the first fleet-WIDE tenant
      fairness view (each worker only ever saw its own apportionment);
    - ``burn`` (worst-of-fleet per objective/window) and ``budget``
      (min-of-fleet) — one sick worker must show, not average away;
    - ``openBreakers`` — per worker, with open reasons;
    - ``topHops`` — fleet seconds-per-GB per hop (summed seconds over
      summed bytes), worst three: where the fleet's gigabyte-time goes;
    - ``cpuSPerGb`` — the fleet's staging copy cost (summed COPY_HOPS
      seconds over the widest copy hop's bytes): the zero-copy
      ratchet's live headline, null until enough bytes moved;
    - ``hopReconcileRatioMixed`` — summed hop seconds over summed
      stage seconds across the fleet (the soak's unguarded mixed-phase
      attribution stat, surfaced live so drift is at least visible);
    - ``scrub`` — summed integrity-scrubber verdict counters
      (clean/repaired/quarantined) across the fleet: repaired/
      quarantined climbing is a disk going bad somewhere.
    """
    from ..control.slo import top_hops

    members: List[dict] = []
    tenant_queued: Dict[str, int] = {}
    burn: Dict[str, Dict[str, float]] = {}
    budget: Dict[str, float] = {}
    open_breakers: Dict[str, dict] = {}
    hop_totals: Dict[str, dict] = {}
    queue_depth = 0
    active_jobs = 0
    hop_seconds_sum = 0.0
    stage_seconds_sum = 0.0
    scrub_totals = {"clean": 0, "repaired": 0, "quarantined": 0}
    for doc in workers:
        wid = doc.get("workerId")
        signals = doc.get("signals")
        digest = doc.get("digest")
        if not isinstance(digest, dict):
            digest = None  # pre-PR-15 heartbeat shape: listed, null
        members.append({
            "workerId": wid,
            "startedAt": doc.get("startedAt"),
            "heartbeatAt": doc.get("heartbeatAt"),
            "leases": len(doc.get("leases") or []),
            "signals": dict(signals) if isinstance(signals, dict)
            else None,
            "digest": digest,
        })
        if isinstance(signals, dict):
            queue_depth += int(signals.get("queue_depth", 0) or 0)
            active_jobs += int(signals.get("active_jobs", 0) or 0)
        if digest is None:
            continue
        for name, rates in (digest.get("burn") or {}).items():
            worst = burn.setdefault(name, {"fast": 0.0, "slow": 0.0})
            for window in ("fast", "slow"):
                try:
                    worst[window] = max(
                        worst[window],
                        float((rates or {}).get(window, 0.0) or 0.0))
                except (TypeError, ValueError):
                    pass
        for name, remaining in (digest.get("budget") or {}).items():
            try:
                remaining = float(remaining)
            except (TypeError, ValueError):
                continue
            budget[name] = min(budget.get(name, 1.0), remaining)
        breakers = digest.get("openBreakers") or {}
        if breakers:
            open_breakers[wid] = dict(breakers)
        for tenant, depth in (digest.get("tenantQueued") or {}).items():
            try:
                tenant_queued[tenant] = (tenant_queued.get(tenant, 0)
                                         + int(depth))
            except (TypeError, ValueError):
                pass
        scrub_doc = digest.get("scrub")
        if isinstance(scrub_doc, dict):
            for outcome in ("clean", "repaired", "quarantined"):
                try:
                    scrub_totals[outcome] += int(
                        scrub_doc.get(outcome, 0) or 0)
                except (TypeError, ValueError):
                    pass
        for hop, entry in (digest.get("hops") or {}).items():
            if not isinstance(entry, dict):
                continue
            total = hop_totals.setdefault(
                hop, {"bytes": 0, "seconds": 0.0})
            try:
                total["bytes"] += int(entry.get("bytes", 0) or 0)
                total["seconds"] += float(entry.get("seconds", 0.0)
                                          or 0.0)
            except (TypeError, ValueError):
                pass
        try:
            hop_seconds_sum += float(digest.get("hopSeconds", 0.0)
                                     or 0.0)
            stage_seconds_sum += float(digest.get("stageSeconds", 0.0)
                                       or 0.0)
        except (TypeError, ValueError):
            pass
    # fleet staging copy cost: same COPY_HOPS/widest-hop discipline as
    # HopLedger.copy_seconds_per_gb, over the fleet-summed totals
    from ..platform.obs import COPY_HOPS, HopLedger

    copy_seconds = 0.0
    copy_weight = 0
    for hop, entry in hop_totals.items():
        if hop in COPY_HOPS:
            copy_seconds += entry["seconds"]
            copy_weight = max(copy_weight, entry["bytes"])
    cpu_s_per_gb = (
        round(copy_seconds / (copy_weight / 1e9), 3)
        if copy_weight >= HopLedger.MIN_OBSERVE_BYTES else None
    )
    total_queued = sum(tenant_queued.values())
    tenant_shares = {
        tenant: round(depth / total_queued, 4)
        for tenant, depth in sorted(tenant_queued.items())
    } if total_queued else {}
    return {
        "updatedAt": round(time.time(), 3),
        "updatedBy": worker_id,
        "workers": members,
        "totals": {
            "workers": len(members),
            "queueDepth": queue_depth,
            "activeJobs": active_jobs,
            "tenantQueued": tenant_queued,
            "tenantShares": tenant_shares,
            "burn": burn,
            "budget": budget,
            "openBreakers": open_breakers,
            "topHops": top_hops(hop_totals),
            "cpuSPerGb": cpu_s_per_gb,
            "scrub": scrub_totals,
            "hopReconcileRatioMixed": round(
                hop_seconds_sum / stage_seconds_sum, 4)
            if stage_seconds_sum > 0 else None,
        },
    }


def _json_bytes(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True).encode("utf-8")


def _json_load(raw: bytes) -> dict:
    return json.loads(raw.decode("utf-8"))


# re-exported for callers that build planes by hand (tests, bench)
__all__ = [
    "FleetPlane", "resolve_worker_id", "MemoryCoordStore",
    "BucketCoordStore", "CasBucketCoordStore", "CoordError",
    "LED", "SHARED", "UNCOORDINATED",
    "build_overview", "OVERVIEW_KEY", "PLAN_KEY", "ORIGIN_HEALTH_KEY",
]
