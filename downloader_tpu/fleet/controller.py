"""The closed-loop placement/autoscale controller (ISSUE 17).

PR 15 built the sensor: an elected aggregator folds every worker's
burn rates, queue depths, breakers, and tenant shares into ONE fleet
overview document.  This module closes the loop: an elected controller
consumes that overview each heartbeat and publishes ``plan/fleet`` — a
first-class plan document every worker's watch-fed cache serves at
admission (``FleetPlane.current_plan``):

- **Admission** (``admission.shedBulk``): when the fleet's worst SLO
  burn runs hot on BOTH windows — or the remaining error budget falls
  under the floor — BULK is shed at the admission edge *before* the
  budget exhausts, instead of after the damage (the PR 15 burn-rate
  ladder, actuated).
- **Scale** (``desiredWorkers`` / ``scale``): queue-depth-driven worker
  count for external autoscalers, hysteresis'd so one bursty beat never
  flaps the fleet (also on ``fleet_desired_workers``).
- **Placement** (``drain``): workers browning out (open dependency
  breakers) are listed for drain — the content router stops deferring
  NEW work toward them, so their queues empty while they recover.

Election and fencing reuse the overview aggregator's discipline: the
plan doc's freshness is the cheap pre-check, the oldest live worker
wins the full election, and every publish is token-CAS — a lost CAS
means a concurrent controller exists and THIS one stands down (the
write token is the fence; no plan is ever clobbered).  ``epoch``
increments on takeover so a resumed stale controller's plan is
recognizably ancient.

Every decision EDGE (shed on/off, drain set changes, desired-worker
moves) is logged, counted on ``fleet_controller_decisions_total`` and
carried in the plan's ``decisions`` tail — the operator reads the whys
from ``GET /v1/fleet/plan``, not from correlating dashboards.

Failure posture: the controller is an optimizer, never a gate.  No
overview, a stale overview, or coordination trouble SKIPS the tick
(counted via the plane's coord-error accounting); workers that see no
fresh plan simply run today's uncontrolled admission.
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional

from ..platform.config import cfg_get
from .coord import ABSENT
from .plane import PLAN_KEY

# burn-rate ceiling: shed BULK while ANY objective burns faster than
# this on BOTH windows (the PR 15 page condition, acted on early)
DEFAULT_SHED_BURN = 2.0
# error-budget floor: shed BULK while ANY objective's remaining budget
# sits under this fraction (shedding BEFORE exhaustion, the ISSUE's
# acceptance shape)
DEFAULT_BUDGET_FLOOR = 0.25
# queued jobs one worker is expected to chew through: the scale signal
# is ceil(queueDepth / this), hysteresis'd
DEFAULT_TARGET_DEPTH = 8.0
DEFAULT_MAX_WORKERS = 16
# consecutive ticks a scale move must hold before the plan adopts it
# (flap damping: one bursty beat must not resize the fleet)
DEFAULT_SCALE_HOLD_TICKS = 3
# decision-edge tail carried on the plan doc (bounded: the plan stays
# a few KB; the full history is in logs/metrics)
DECISIONS_LIMIT = 16


class PlacementController:
    """The elected closed-loop controller (one active per fleet)."""

    def __init__(self, plane, *,
                 shed_burn: float = DEFAULT_SHED_BURN,
                 budget_floor: float = DEFAULT_BUDGET_FLOOR,
                 target_depth: float = DEFAULT_TARGET_DEPTH,
                 max_workers: int = DEFAULT_MAX_WORKERS,
                 scale_hold_ticks: int = DEFAULT_SCALE_HOLD_TICKS,
                 metrics=None, logger=None):
        self.plane = plane
        self.shed_burn = float(shed_burn)
        self.budget_floor = float(budget_floor)
        self.target_depth = max(float(target_depth), 1.0)
        self.max_workers = max(int(max_workers), 1)
        self.scale_hold_ticks = max(int(scale_hold_ticks), 1)
        self.metrics = metrics
        self.logger = logger
        self._task: Optional[asyncio.Task] = None
        # hysteresis: (candidate desired count, consecutive ticks held)
        self._scale_candidate: Optional[int] = None
        self._scale_held = 0
        # last adopted values, for decision-EDGE detection
        self._last_shed: Optional[bool] = None
        self._last_drain: frozenset = frozenset()
        self._last_desired: Optional[int] = None
        self._decisions: List[dict] = []
        self.ticks = 0
        self.plans_published = 0

    @classmethod
    def from_config(cls, config, plane, *, metrics=None, logger=None
                    ) -> Optional["PlacementController"]:
        """Build from ``fleet.controller.*``; None when disabled
        (``fleet.controller.enabled``, default True with a fleet) or
        there is no fleet plane to control."""
        if plane is None:
            return None
        if not bool(cfg_get(config, "fleet.controller.enabled", True)):
            return None
        return cls(
            plane,
            shed_burn=float(cfg_get(
                config, "fleet.controller.shed_burn",
                DEFAULT_SHED_BURN)),
            budget_floor=float(cfg_get(
                config, "fleet.controller.budget_floor",
                DEFAULT_BUDGET_FLOOR)),
            target_depth=float(cfg_get(
                config, "fleet.controller.target_depth",
                DEFAULT_TARGET_DEPTH)),
            max_workers=int(cfg_get(
                config, "fleet.controller.max_workers",
                DEFAULT_MAX_WORKERS)),
            scale_hold_ticks=int(cfg_get(
                config, "fleet.controller.scale_hold_ticks",
                DEFAULT_SCALE_HOLD_TICKS)),
            metrics=metrics, logger=logger,
        )

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(
                self._loop(),
                name=f"fleet-controller-{self.plane.worker_id}")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _loop(self) -> None:
        # offset from the heartbeat so a tick consumes the views the
        # beat just refreshed, not the previous generation's
        interval = self.plane.heartbeat_interval
        while True:
            await asyncio.sleep(interval)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self.plane._note_coord_error("controller", err)

    # -- one control tick -----------------------------------------------
    async def tick(self) -> bool:
        """One closed-loop pass: elect, decide, CAS-publish.  Returns
        True when this worker published the plan this tick."""
        self.ticks += 1
        plane = self.plane
        # cheap election pre-check, the overview aggregator discipline:
        # a FRESH plan someone else wrote means a live controller owns
        # the loop — this worker's tick is free
        entry = await plane.coord.get(PLAN_KEY)
        doc = entry[0] if entry is not None else None
        if doc is not None and doc.get("updatedBy") != plane.worker_id:
            age = time.time() - float(doc.get("updatedAt", 0) or 0)
            if age < 2.0 * plane.heartbeat_interval:
                return False
        workers = await plane.workers()
        if not workers or workers[0].get("workerId") != plane.worker_id:
            return False  # not the oldest live worker: stand down
        overview = plane.cached_overview()
        if overview is None:
            # no fresh fleet evidence: publishing a plan would steer
            # the fleet on history.  Skip — workers degrade to
            # uncontrolled admission once the old plan ages out.
            return False
        plan = self.build_plan(overview, workers, previous=doc)
        # token-CAS publish: the write token is the fence.  A lost race
        # means a concurrent controller exists (split-brain window);
        # stand down and let the freshness pre-check re-elect.
        expect = entry[1] if entry is not None else ABSENT
        token = await plane.coord.put(PLAN_KEY, plan, expect=expect)
        if token is None:
            if self.logger is not None:
                self.logger.warn("fleet controller: plan CAS lost; "
                                 "standing down")
            return False
        self.plans_published += 1
        self._note("plan")
        if self.metrics is not None:
            self.metrics.fleet_desired_workers.set(
                plan["desiredWorkers"])
        # the publisher's own cache serves the new plan immediately
        plane._plan_doc = plan
        return True

    # -- the decision table (pure; unit-tested by hand) -----------------
    def build_plan(self, overview: dict, workers: List[dict],
                   previous: Optional[dict] = None) -> dict:
        """Fold one overview into one plan document.  Pure decision
        logic — no I/O, no clocks beyond the stamp — so the decision
        table is unit-testable against hand-computed cases."""
        totals = overview.get("totals") or {}
        now = time.time()
        # the plan epoch: unchanged while one controller keeps the
        # loop, +1 on takeover — a resumed stale controller's plan is
        # recognizably from a dead epoch
        epoch = 1
        if previous is not None:
            try:
                prev_epoch = int(previous.get("epoch", 0) or 0)
            except (TypeError, ValueError):
                prev_epoch = 0
            takeover = previous.get("updatedBy") != self.plane.worker_id
            epoch = max(prev_epoch + (1 if takeover else 0), 1)

        shed, shed_reason = self._admission_decision(totals)
        drain = self._drain_decision(totals, workers)
        desired, scale = self._scale_decision(totals, workers)

        # decision EDGES -> the bounded tail + metrics + logs
        if shed != self._last_shed:
            self._record_decision(
                "shed_bulk" if shed else "shed_clear",
                shed_reason if shed else "pressure cleared", now)
            self._last_shed = shed
        drain_set = frozenset(drain)
        if drain_set != self._last_drain:
            self._record_decision(
                "drain", ",".join(sorted(drain)) or "none", now)
            self._last_drain = drain_set
        if desired != self._last_desired:
            if self._last_desired is not None:
                self._record_decision(
                    "scale_up" if desired > self._last_desired
                    else "scale_down",
                    f"desired {self._last_desired} -> {desired}", now)
            self._last_desired = desired

        return {
            "updatedAt": round(now, 3),
            "updatedBy": self.plane.worker_id,
            "epoch": epoch,
            "admission": {"shedBulk": shed, "reason": shed_reason},
            "drain": sorted(drain),
            "desiredWorkers": desired,
            "scale": scale,
            "liveWorkers": len(workers),
            "decisions": list(self._decisions),
        }

    def _admission_decision(self, totals: dict):
        """Shed BULK while any objective burns hot on BOTH windows or
        its remaining budget is under the floor — BEFORE exhaustion."""
        for name, rates in (totals.get("burn") or {}).items():
            try:
                fast = float((rates or {}).get("fast", 0.0) or 0.0)
                slow = float((rates or {}).get("slow", 0.0) or 0.0)
            except (TypeError, ValueError):
                continue
            if fast >= self.shed_burn and slow >= self.shed_burn:
                return True, (f"burn {name} fast {fast:.2f} slow "
                              f"{slow:.2f} >= {self.shed_burn:g}")
        for name, remaining in (totals.get("budget") or {}).items():
            try:
                remaining = float(remaining)
            except (TypeError, ValueError):
                continue
            if remaining <= self.budget_floor:
                return True, (f"budget {name} {remaining:.2f} <= "
                              f"floor {self.budget_floor:g}")
        return False, ""

    def _drain_decision(self, totals: dict,
                        workers: List[dict]) -> List[str]:
        """Drain workers with open dependency breakers (browning out):
        new leases steer away so their queue empties while they heal.
        Never drains the whole fleet — with every worker browning out
        there is nowhere better to steer, so nobody drains."""
        open_breakers = totals.get("openBreakers") or {}
        live = {doc.get("workerId") for doc in workers}
        drain = [wid for wid in open_breakers if wid in live]
        if len(drain) >= len(live):
            return []
        return drain

    def _scale_decision(self, totals: dict, workers: List[dict]):
        """ceil(queueDepth / target_depth) clamped to [1, max_workers],
        adopted only after ``scale_hold_ticks`` consecutive agreeing
        ticks (hysteresis) — plus never below the live count while any
        worker still queues work (scale-down is advisory draining, not
        eviction of busy workers)."""
        try:
            depth = int(totals.get("queueDepth", 0) or 0)
            active = int(totals.get("activeJobs", 0) or 0)
        except (TypeError, ValueError):
            depth, active = 0, 0
        live = max(len(workers), 1)
        want = max(1, min(self.max_workers,
                          -(-(depth + active) // int(self.target_depth))
                          if (depth + active) else 1))
        if want == self._scale_candidate:
            self._scale_held += 1
        else:
            self._scale_candidate = want
            self._scale_held = 1
        adopted = self._last_desired if self._last_desired else live
        if self._scale_held >= self.scale_hold_ticks:
            adopted = want
        if adopted > live:
            scale = "up"
        elif adopted < live:
            scale = "down"
        else:
            scale = "hold"
        return adopted, scale

    # -- bookkeeping ----------------------------------------------------
    def _record_decision(self, kind: str, why: str, now: float) -> None:
        self._decisions.append(
            {"kind": kind, "why": why, "at": round(now, 3)})
        del self._decisions[:-DECISIONS_LIMIT]
        self._note(kind)
        if self.logger is not None:
            self.logger.info("fleet controller decision",
                             kind=kind, why=why)

    def _note(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.fleet_controller_decisions.labels(
                kind=kind).inc()


__all__ = ["PlacementController"]
