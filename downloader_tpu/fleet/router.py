"""Content-aware routing at the admission edge (ISSUE 17).

The lease protocol (``plane.coordinate``) already stops duplicate
origin fetches — but it stops them LATE: by the time a worker discovers
a peer's lease it has consumed a delivery, burned admission, queued for
a run slot, and parked a whole job for the leader's publish.  On a
same-content-heavy workload that parks N-1 of the fleet's run slots
behind one download.

:class:`ContentRouter` moves the discovery to admission.  Every lease
doc carries the leader job's ``routeKey`` (a :func:`route_key_for` hash
over the message's source URI — computable from the delivery alone, no
origin probe), and every worker's :class:`~.plane.FleetPlane` maintains
a watch-fed lease view.  At admission the router looks the delivery's
route key up in that view — zero store round trips — and when a LIVE
peer already leads the content, the delivery is handed back to the
broker (park-then-nack, the PR 5 shed discipline: never FAILED-counted,
never poison-charged) to land on the holder, whose in-process
singleflight coalesces it for free.

Two fleet-level concerns ride the same decision point:

- **Tenant fairness, fleet-wide** — each worker's scheduler only ever
  apportioned its OWN queue.  The router checks a BULK delivery's
  tenant against the fleet-wide queued shares on the overview doc and
  defers tenants hogging the fleet (bounded by ``fairness_factor``
  times their weighted fair share).
- **The controller's plan** — when the placement controller
  (``fleet/controller.py``) publishes ``admission.shedBulk`` (burn-rate
  pressure) the router sheds BULK at the edge, and a holder listed in
  the plan's ``drain`` set is NOT deferred to (new work steers away
  from a browning-out worker; the delivery runs here and coalesces
  through the lease protocol as before).

Failure posture: every input is a cached view that may be stale or
absent — absent view, absent plan, unknown holder all decide ``run``
(exactly today's behavior).  The router can only ever *decline to
optimize*; it never blocks work.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

from ..platform.config import cfg_get
from ..store.cache import cache_key

# decision outcomes (``fleet_router_decisions_total{outcome}``)
RUN = "run"                      # no routing concern: admit normally
LOCAL = "local"                  # this worker already leads the content
DEFER = "defer"                  # hand to the current lease holder
FAIRNESS_DEFER = "fairness_defer"  # BULK over its fleet-wide fair share
SHED = "shed"                    # the controller's plan sheds BULK

DEFAULT_FAIRNESS_FACTOR = 2.0
# park-then-nack backoff for a routed delivery: long enough that the
# redelivery usually lands after the holder's next heartbeat refreshed
# every view, short enough that a finished holder's content is re-tried
# promptly (the defer loop is bounded by the lease lifetime — holder
# done => lease gone => shared-tier hit on redelivery)
DEFAULT_DEFER_BACKOFF = 2.0


def route_key_for(source_uri: str) -> Optional[str]:
    """The admission-edge routing identity for a delivery.

    Deliberately NOT the cache key: the http cache key embeds an origin
    validator (ETag/Last-Modified) only known after a HEAD probe, which
    admission must never pay.  A pure hash over the source URI is
    computable by every worker from the message alone and identical on
    both sides — the router here and the lease holder stamping it via
    ``stages/download.py``.  Same content behind two URIs simply
    doesn't route (the lease protocol still coalesces it later).
    """
    if not source_uri:
        return None
    return cache_key("route", source_uri)


@dataclasses.dataclass(frozen=True)
class RouteDecision:
    """One admission routing decision (flight-recorder material)."""

    outcome: str
    reason: str = ""
    holder: Optional[str] = None   # worker id the content routed toward
    backoff: float = 0.0           # park before the nack, seconds

    @property
    def settles(self) -> bool:
        """True when the delivery leaves this worker (park+nack)."""
        return self.outcome in (DEFER, FAIRNESS_DEFER, SHED)


class ContentRouter:
    """Per-worker router over the fleet plane's watch-fed views."""

    def __init__(self, plane, tenants=None, *,
                 fairness_factor: float = DEFAULT_FAIRNESS_FACTOR,
                 defer_backoff: float = DEFAULT_DEFER_BACKOFF,
                 metrics=None, logger=None):
        if fairness_factor < 1.0:
            # < 1 would defer a tenant sitting exactly at its fair
            # share — a single-tenant fleet would livelock its own BULK
            raise ValueError(
                f"fleet.router.fairness_factor must be >= 1.0, "
                f"got {fairness_factor}")
        self.plane = plane
        self.tenants = tenants
        self.fairness_factor = float(fairness_factor)
        self.defer_backoff = float(defer_backoff)
        self.metrics = metrics
        self.logger = logger
        # last non-run decision, for the heartbeat digest -> the
        # overview doc's per-worker DECISION column (`cli fleet top`)
        self.last: Optional[dict] = None
        # outcome -> count, the plane-stats idiom (metrics carry the
        # same numbers; this dict is the test/debug surface)
        self.stats: Dict[str, int] = {}

    @classmethod
    def from_config(cls, config, plane, tenants=None, *,
                    metrics=None, logger=None
                    ) -> Optional["ContentRouter"]:
        """Build from ``fleet.router.*``; None when routing is off
        (``fleet.router.enabled``, default True — but only ever called
        with a live fleet plane, so the lone-worker default cost stays
        zero)."""
        if plane is None:
            return None
        if not bool(cfg_get(config, "fleet.router.enabled", True)):
            return None
        return cls(
            plane, tenants,
            fairness_factor=float(cfg_get(
                config, "fleet.router.fairness_factor",
                DEFAULT_FAIRNESS_FACTOR)),
            defer_backoff=float(cfg_get(
                config, "fleet.router.defer_backoff",
                DEFAULT_DEFER_BACKOFF)),
            metrics=metrics, logger=logger,
        )

    # -- the decision ---------------------------------------------------
    def decide(self, source_uri: str, *, priority: str,
               tenant: str = "default") -> RouteDecision:
        """Route one delivery.  Pure reads over cached views — safe on
        the admission hot path, never awaits, never raises."""
        try:
            decision = self._decide(source_uri, priority, tenant)
        except Exception as err:  # a routing bug must not drop intake
            if self.logger is not None:
                self.logger.warn("content router error; admitting",
                                 error=str(err)[:200])
            decision = RouteDecision(RUN, reason="router_error")
        if self.metrics is not None:
            self.metrics.fleet_router_decisions.labels(
                outcome=decision.outcome).inc()
        self.stats[decision.outcome] = (
            self.stats.get(decision.outcome, 0) + 1)
        if decision.outcome != RUN:
            self.last = {"outcome": decision.outcome,
                         "reason": decision.reason,
                         "at": round(time.time(), 3)}
        return decision

    def _decide(self, source_uri: str, priority: str,
                tenant: str) -> RouteDecision:
        plan = self.plane.current_plan()
        # 1) the controller's admission plan: shed BULK at the edge
        #    BEFORE the SLO budget burns (the closed loop's actuator)
        if priority == "BULK" and plan is not None:
            admission = plan.get("admission") or {}
            if admission.get("shedBulk"):
                return RouteDecision(
                    SHED,
                    reason=str(admission.get("reason") or "plan"),
                    backoff=self.defer_backoff)
        # 2) content affinity: a live peer already leads this content
        route_key = route_key_for(source_uri)
        holder = (self.plane.route_holder(route_key)
                  if route_key else None)
        if holder is not None:
            owner = holder.get("owner")
            if owner == self.plane.worker_id:
                # our own lease: admit — the in-process singleflight
                # coalesces this delivery onto the running fetch
                return RouteDecision(LOCAL, reason="own_lease")
            if owner and not self._steer_away(owner, plan):
                return RouteDecision(
                    DEFER, reason="lease_holder", holder=owner,
                    backoff=self.defer_backoff)
            # holder browning out / draining: fall through — today's
            # lease-park coalescing still dedupes the origin fetch
        # 3) fleet-wide tenant fairness (BULK only: user-facing work is
        #    never deferred for queue-share bookkeeping)
        if priority == "BULK":
            over, share, fair = self._over_share(tenant)
            if over:
                return RouteDecision(
                    FAIRNESS_DEFER,
                    reason=(f"tenant {tenant} at {share:.0%} of fleet "
                            f"queue, fair {fair:.0%}"),
                    backoff=self.defer_backoff)
        return RouteDecision(RUN)

    def _steer_away(self, owner: str, plan: Optional[dict]) -> bool:
        """Should NEW work avoid ``owner``?  True when the controller's
        plan drains it (brownout, scale-down) — deferring a delivery TO
        a draining worker would feed the very queue placement is trying
        to empty."""
        if plan is None:
            return False
        drain = plan.get("drain")
        return isinstance(drain, (list, tuple)) and owner in drain

    def _over_share(self, tenant: str):
        """Is ``tenant`` over its fleet-wide weighted fair share of the
        queued backlog?  Returns (over, observed_share, fair_share).
        Absent/stale overview, unlisted tenant, or a trivially small
        backlog all decide False — fairness needs fleet evidence."""
        overview = self.plane.cached_overview()
        if overview is None:
            return False, 0.0, 0.0
        queued = (overview.get("totals") or {}).get("tenantQueued") or {}
        try:
            total = sum(int(v) for v in queued.values())
            mine = int(queued.get(tenant, 0))
        except (TypeError, ValueError):
            return False, 0.0, 0.0
        # a fleet with a near-empty backlog has nothing to apportion;
        # deferring the only queued job to enforce a ratio is absurd
        if total < 4 or mine <= 1:
            return False, 0.0, 0.0
        share = mine / total

        def weight(name: str) -> float:
            if self.tenants is None:
                return 1.0
            try:
                return float(self.tenants.weight(name))
            except Exception:
                return 1.0

        weights = sum(weight(name) for name in queued) or 1.0
        fair = weight(tenant) / weights
        return share > self.fairness_factor * fair, share, fair


__all__ = ["ContentRouter", "RouteDecision", "route_key_for",
           "RUN", "LOCAL", "DEFER", "FAIRNESS_DEFER", "SHED"]
