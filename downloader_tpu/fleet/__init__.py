"""Fleet coordination plane: worker registry, cross-worker singleflight,
and a shared cache tier.

PR 1's cache+singleflight, PR 2's control plane, and PR 5's breakers all
live inside one process; this package is the layer that makes N worker
processes draining ``v1.download`` behave like one cache-coherent
downloader:

- :mod:`.coord` — the conditional-put key/value substrate (in-memory
  backend for tests/benches, staging-bucket backend for production).
- :mod:`.plane` — :class:`~.plane.FleetPlane`: worker
  registration/heartbeats with liveness expiry, content-key leases with
  TTL + takeover (cross-worker singleflight), and the shared cache tier
  (manifest-last spill of local cache entries, peer materialization).

Disabled by default (``fleet.enabled`` / env ``FLEET_ENABLED``); a lone
worker pays nothing for it.
"""

from .coord import (
    ABSENT,
    ANY,
    BucketCoordStore,
    CasBucketCoordStore,
    CoordError,
    CoordStore,
    MemoryCoordStore,
)
from .plane import (
    LED,
    SHARED,
    UNCOORDINATED,
    FleetPlane,
    resolve_worker_id,
)

__all__ = [
    "ABSENT", "ANY", "LED", "SHARED", "UNCOORDINATED",
    "BucketCoordStore", "CasBucketCoordStore", "CoordError",
    "CoordStore", "FleetPlane", "MemoryCoordStore",
    "resolve_worker_id",
]
