"""Drift rules: code vs the docs/OPERATIONS.md catalogs and the seam map.

Three repo-scope checkers, each encoding a recurring review-round
finding (knobs and metrics shipped without catalog rows; seams wired
without fault-injection reachability):

- **knob-drift** — every ``cfg_get("a.b.c")`` key must be documented in
  docs/OPERATIONS.md (a catalog row, a config example, or the dotted
  path in prose), and every knob the OPERATIONS config examples
  document must be read somewhere (dead-knob reverse check).
- **metric-drift** — every metric family registered in
  platform/metrics.py must have a row in the OPERATIONS "Metrics
  catalog" section, and label sets must be literal and drawn from the
  bounded-label allowlist (job payloads must not mint Prometheus
  series — the tenant/origin posture).
- **seam-coverage** — every ``Retrier.run("<seam>")`` seam must key on
  a known dependency family (the ``retry.*`` config families
  platform/errors.py resolves) that the OPERATIONS failure-model docs
  name, and must be reachable by the fault-injection plan (a
  ``faults.fire``/``fire_sync`` hook exists for the same family, so
  ``make chaos`` can actually drill it).
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, ModuleSource, RepoContext, repo_checker

# -- shared extraction helpers -----------------------------------------


def _literal_or_pattern(expr: ast.expr) -> Optional[str]:
    """A string Constant as-is; an f-string with ``*`` for each
    placeholder (``f"retry.{dep}.{k}"`` -> ``retry.*.*``); else None."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for value in expr.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _attr_chain(node: ast.Attribute) -> List[str]:
    """Outermost attribute chain names, root-first (``config.a.b`` ->
    ``["a", "b"]`` — the root expression itself is ignored so
    ``self.config.a.b`` and ``ctx.config.a.b`` normalize the same)."""
    chain: List[str] = []
    current: ast.expr = node
    while isinstance(current, ast.Attribute):
        chain.append(current.attr)
        current = current.value
    chain.reverse()
    return chain


# -- knob drift ---------------------------------------------------------

#: top-level config sections (platform/config.py DEFAULTS + the
#: documented opt-in sections).  Attribute chains / .get() keys rooted
#: here count as config reads; a *new* top-level section must be added
#: both here and to the OPERATIONS docs.
CONFIG_SECTIONS = frozenset({
    "instance", "minio", "rabbitmq", "services", "store", "tracing",
    "health", "control", "retry", "breakers", "faults", "tenants",
    "overload", "origins", "fleet", "journal", "integrity", "obs",
    "wire_remap", "slo", "incident", "download", "scrub",
})

#: documented knobs that are deliberately not read via cfg_get /
#: attribute traversal — each entry names the mechanism that consumes
#: it, so the dead-knob check stays honest instead of silently skipped.
DOCUMENTED_ONLY_KNOBS: Dict[str, str] = {
    # the store backends receive the whole `minio` section as
    # constructor kwargs (store/__init__.py builds from config["minio"])
    "minio.backend": "consumed wholesale by store backend factory",
    "minio.access_key": "consumed wholesale by store backend factory",
    "minio.secret_key": "consumed wholesale by store backend factory",
    # dyn() resolves service names against the whole `services` map
    "services.rabbitmq": "read dynamically via dyn('rabbitmq')",
    "services.minio": "read dynamically via dyn('minio')",
}

_DOTTED_TOKEN_RE = re.compile(
    r"[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z0-9_<>*-]+)+")
_YAML_FENCE_RE = re.compile(r"```yaml\n(.*?)```", re.DOTALL)
_YAML_KEY_RE = re.compile(r"^(\s*)([A-Za-z_][A-Za-z0-9_]*):(.*)$")


def _doc_tokens(doc: str) -> Set[str]:
    """Dotted config paths mentioned anywhere in the doc text, with
    ``<placeholder>`` segments normalized to ``*``."""
    out = set()
    for token in _DOTTED_TOKEN_RE.findall(doc):
        out.add(re.sub(r"<[^.>]*>", "*", token))
    return out


def _yaml_block_paths(doc: str) -> List[Tuple[str, int]]:
    """(dotted path, doc line) for every key in the doc's fenced yaml
    config examples — parsed with a comment-stripping indentation
    stack, because the examples carry ``...`` placeholders real YAML
    loaders reject."""
    paths: List[Tuple[str, int]] = []
    for match in _YAML_FENCE_RE.finditer(doc):
        start_line = doc[:match.start(1)].count("\n") + 1
        stack: List[Tuple[int, str]] = []  # (indent, key)
        list_indent: Optional[int] = None  # inside a "- item" list
        for offset, raw in enumerate(match.group(1).splitlines()):
            line = raw.split("#", 1)[0].rstrip()
            stripped = line.strip()
            indent_now = len(line) - len(line.lstrip())
            if stripped.startswith("-"):
                # a list: its items are payload shapes (fault-plan rule
                # fields, tenant examples), not config knob paths
                list_indent = indent_now
                continue
            if list_indent is not None:
                if stripped and indent_now > list_indent:
                    continue
                list_indent = None
            key_match = _YAML_KEY_RE.match(line)
            if key_match is None:
                continue
            indent = len(key_match.group(1))
            key = key_match.group(2)
            while stack and stack[-1][0] >= indent:
                stack.pop()
            stack.append((indent, key))
            path = ".".join(k for _, k in stack)
            paths.append((path, start_line + offset))
            # inline mappings ({backend: amqp}) contribute their keys too
            rest = key_match.group(3).strip()
            if rest.startswith("{") and rest.endswith("}"):
                for part in rest[1:-1].split(","):
                    inner = part.split(":", 1)[0].strip()
                    if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", inner):
                        paths.append((f"{path}.{inner}",
                                      start_line + offset))
    return paths


class _KnobReads(ast.NodeVisitor):
    """Collects every way a module reads config: cfg_get literals,
    cfg_get f-string patterns, attribute chains rooted in a known
    section, and ``.get("section")`` literals."""

    def __init__(self, rel_path: str):
        self.rel_path = rel_path
        self.exact: List[Tuple[str, int]] = []
        self.patterns: List[Tuple[str, int]] = []
        self.prefixes: Set[str] = set()
        self._attr_seen: Set[int] = set()

    def visit_Call(self, node: ast.Call):
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name == "cfg_get" and len(node.args) >= 2:
            key = _literal_or_pattern(node.args[1])
            if key is not None:
                if "*" in key:
                    self.patterns.append((key, node.lineno))
                else:
                    self.exact.append((key, node.lineno))
        elif name == "get" and node.args:
            arg = node.args[0]
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value in CONFIG_SECTIONS):
                self.prefixes.add(arg.value)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if id(node) in self._attr_seen:
            # interior link of a chain already recorded: don't re-root
            # a shorter (over-broad) prefix, but keep walking into the
            # root expression (it may hold calls/chains of its own)
            self.generic_visit(node)
            return
        chain = _attr_chain(node)
        # mark only this chain's own SPINE as seen — chains nested in
        # the subtree (call arguments, subscripts) must still be rooted
        # when the visitor reaches them
        current: ast.expr = node
        while isinstance(current, ast.Attribute):
            self._attr_seen.add(id(current))
            current = current.value
        for start, first in enumerate(chain):
            if first in CONFIG_SECTIONS:
                tail = chain[start:]
                # a bare one-element chain (self.store, ctx.origins, …)
                # is almost never a config read — any attribute named
                # like a section would otherwise blanket-mark the whole
                # section as live and make the dead-knob check vacuous
                if len(tail) >= 2:
                    self.prefixes.add(".".join(tail))
                break
        self.generic_visit(node)


def _collect_knob_reads(modules: Iterable[ModuleSource]):
    exact: Dict[str, Tuple[str, int]] = {}
    patterns: Dict[str, Tuple[str, int]] = {}
    prefixes: Set[str] = set()
    for module in modules:
        if module.tree is None:
            continue
        visitor = _KnobReads(module.rel_path)
        visitor.visit(module.tree)
        for key, line in visitor.exact:
            exact.setdefault(key, (module.rel_path, line))
        for key, line in visitor.patterns:
            patterns.setdefault(key, (module.rel_path, line))
        prefixes |= visitor.prefixes
    return exact, patterns, prefixes


@repo_checker(
    "knob-drift",
    "cfg_get keys must have a docs/OPERATIONS.md row; documented config "
    "knobs must be read somewhere (dead-knob reverse check).  "
    "Deliberate exceptions live in drift.DOCUMENTED_ONLY_KNOBS with "
    "the consuming mechanism on record.")
def check_knob_drift(ctx: RepoContext) -> List[Finding]:
    out: List[Finding] = []
    doc = ctx.operations_md
    tokens = _doc_tokens(doc)
    yaml_paths = _yaml_block_paths(doc)
    documented: Set[str] = tokens | {path for path, _ in yaml_paths}

    def is_documented(key: str) -> bool:
        if key in documented:
            return True
        return any("*" in tok and fnmatch.fnmatch(key, tok)
                   for tok in documented)

    exact, patterns, prefixes = _collect_knob_reads(
        ctx.package_modules())

    # forward: every read knob has a doc row (single-component keys —
    # whole sections like "tenants" — count as documented when the
    # bare word appears in the doc)
    for key, (path, line) in sorted(exact.items()):
        if not is_documented(key) and not (
                "." not in key and re.search(
                    rf"(?:^|[\s`\"']){re.escape(key)}(?:$|[\s:`\"'.])",
                    doc)):
            out.append(Finding(
                "knob-drift", path, line,
                f'config knob "{key}" has no docs/OPERATIONS.md row — '
                "document it (knob table or config example) before it "
                "ships"))
    for key, (path, line) in sorted(patterns.items()):
        family = key.split("*", 1)[0].rstrip(".")
        if family and not any(tok == family or tok.startswith(family + ".")
                              for tok in documented):
            out.append(Finding(
                "knob-drift", path, line,
                f'config knob family "{family}.*" has no '
                "docs/OPERATIONS.md coverage"))

    # reverse: every documented yaml-example knob is read somewhere.
    # Only LEAF paths count (section headers are structure, not knobs).
    all_paths = {path for path, _ in yaml_paths}
    seen: Set[str] = set()
    for path, line in yaml_paths:
        if path in seen:
            continue
        seen.add(path)
        if any(other != path and other.startswith(path + ".")
               for other in all_paths):
            continue  # interior node
        if path.split(".", 1)[0] not in CONFIG_SECTIONS:
            continue
        if path in DOCUMENTED_ONLY_KNOBS:
            continue
        used = (
            path in exact
            or any(fnmatch.fnmatch(path, pattern) for pattern in patterns)
            or any(path == p or path.startswith(p + ".")
                   or p.startswith(path + ".") for p in prefixes)
        )
        if not used:
            out.append(Finding(
                "knob-drift", ctx.operations_path, line,
                f'documented knob "{path}" is read nowhere in '
                "downloader_tpu/ — dead doc row, stale name, or a "
                "mechanism drift.DOCUMENTED_ONLY_KNOBS must name"))
    return out


# -- metric drift -------------------------------------------------------

#: label names whose value sets are bounded by construction (config,
#: enums, code literals) — the only sources allowed to mint Prometheus
#: series.  Adding a label here asserts its cardinality is bounded;
#: say where the bound comes from.
BOUNDED_LABELS = frozenset({
    "state",        # control-plane lifecycle enum
    "from_state", "to_state",   # same enum
    "reason",       # code literals at each inc() site
    "seam", "dependency", "op",  # seam/dependency names (code literals;
                                 # origin:<label> bounded by
                                 # origins.max_labels)
    "outcome",      # taxonomy enum / terminal states
    "stage",        # pipeline stage names
    "hop",          # hop ledger's fixed hop set
    "queue",        # the two queue names
    "protocol",     # download protocol literals
    "direction",    # in/out
    "kind", "mode",  # code literals
    "tenant",       # config-bounded tenant table
    "origin",       # bounded by origins.max_labels (overflow -> other)
    "prefix",       # the three coordination-store key prefixes
                    # (workers/leases/telemetry — fleet/plane.py literals)
    "class",        # SLO objective names: the priority-class enum plus
                    # config-bounded tenant-objective keys
                    # (control/slo.py SloTracker.from_config)
    "window",       # the fast|slow burn-rate window pair (literals)
    "trigger",      # the breach|manual export-trigger pair
                    # (incident/bundle.py TRIGGER_* literals)
})

_METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram", "Summary"})


def _metric_registrations(module: ModuleSource):
    """(family name, labels expr, lineno) for each prometheus metric
    constructed in ``module``.  Family names follow the repo idiom
    ``f"{ns}_<family>"``."""
    for node in module.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = node.func.id if isinstance(node.func, ast.Name) else (
            node.func.attr if isinstance(node.func, ast.Attribute) else "")
        if name not in _METRIC_CTORS or not node.args:
            continue
        family = _literal_or_pattern(node.args[0])
        if family is None:
            continue
        family = family.lstrip("*_")
        labels_expr: Optional[ast.expr] = None
        for arg in node.args[1:]:
            if isinstance(arg, (ast.List, ast.Tuple)):
                labels_expr = arg
        for kw in node.keywords:
            if kw.arg == "labelnames":
                labels_expr = kw.value
        yield family, labels_expr, node.lineno


def _catalog_section(doc: str) -> str:
    match = re.search(r"^## Metrics catalog.*?(?=^## |\Z)", doc,
                      re.DOTALL | re.MULTILINE)
    return match.group(0) if match else ""


@repo_checker(
    "metric-drift",
    "Every metric family registered with prometheus_client in "
    "downloader_tpu/ must have a row in the OPERATIONS 'Metrics "
    "catalog' section, and label sets must be literal names from "
    "drift.BOUNDED_LABELS (bounded sources only — payloads must not "
    "mint series).")
def check_metric_drift(ctx: RepoContext) -> List[Finding]:
    out: List[Finding] = []
    catalog = _catalog_section(ctx.operations_md)
    for module in ctx.package_modules():
        if module.tree is None:
            continue
        if "prometheus_client" not in module.text:
            continue
        for family, labels_expr, line in _metric_registrations(module):
            # word-bounded match: "cache_hits" must NOT ride on the
            # "cache_hits_total" row (underscores are \w, so a partial
            # family name fails the lookahead)
            if family and not re.search(
                    rf"(?<!\w){re.escape(family)}(?!\w)", catalog):
                out.append(Finding(
                    "metric-drift", module.rel_path, line,
                    f'metric "{family}" has no row in the '
                    "docs/OPERATIONS.md metrics catalog"))
            if labels_expr is None:
                continue
            if not isinstance(labels_expr, (ast.List, ast.Tuple)):
                out.append(Finding(
                    "metric-drift", module.rel_path, line,
                    f'metric "{family}" labels are not a literal list '
                    "— label sets must be statically bounded"))
                continue
            for elt in labels_expr.elts:
                if not (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    out.append(Finding(
                        "metric-drift", module.rel_path, line,
                        f'metric "{family}" has a non-literal label'))
                elif elt.value not in BOUNDED_LABELS:
                    out.append(Finding(
                        "metric-drift", module.rel_path, line,
                        f'metric "{family}" label "{elt.value}" is not '
                        "in the bounded-label allowlist "
                        "(drift.BOUNDED_LABELS) — prove its value set "
                        "is bounded, then add it there"))
    return out


# -- seam coverage ------------------------------------------------------

#: dependency families platform/errors.py's retry/breaker config covers
#: (``retry.<family>`` / ``breakers.<family>``).  ``settle`` is the
#: crash-only pre-ack fault seam (no Retrier rides it).
KNOWN_DEPENDENCIES = frozenset({
    "store", "publish", "http", "tracker", "disk", "coord", "origin",
    "settle", "compute",
})

#: families exempt from the WINDOWED-drillability requirement (every
#: family must carry at least one async ``faults.fire`` hook so the
#: windowed kinds — brownout latency, blackhole partitions — can
#: inject; ``fire_sync`` cannot sleep without stalling the event
#: loop).  EMPTY since the storage fault plane landed: ``disk`` — the
#: last holdout — now carries the async ``disk.land`` hook in the
#: landing loop (stages/download.py) plus thread-side latency drills
#: through the vfs shim, so every dependency family is windowed-
#: drillable.  A new sync-only family is a finding, not a silent gap;
#: adding an entry here requires naming why the exemption is sound.
WINDOWED_EXEMPT: Dict[str, str] = {}


def _seam_dependency(seam: str) -> str:
    dependency = seam.split(".", 1)[0]
    return dependency.split(":", 1)[0]


def _collect_seams(modules, attr_names: frozenset,
                   require_retrier: bool):
    """(seam-or-pattern, path, line) for each literal/f-string seam
    passed to a matching call.  ``require_retrier`` narrows ``.run``
    to receivers named ``retrier`` (Retrier.run), since ``.run`` alone
    is too common a method name."""
    out = []
    for module in modules:
        if module.tree is None:
            continue
        for node in module.nodes:
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else "")
            if name not in attr_names:
                continue
            if require_retrier:
                receiver = func.value if isinstance(func, ast.Attribute) \
                    else None
                rname = receiver.attr if isinstance(
                    receiver, ast.Attribute) else (
                    receiver.id if isinstance(receiver, ast.Name)
                    else "")
                # suffix match so self._retrier / probe_retrier stay
                # covered — a renamed instance must not blind the rule
                if not rname.lower().endswith("retrier"):
                    continue
            seam = _literal_or_pattern(node.args[0])
            if seam is None:
                continue
            out.append((seam, module.rel_path, node.lineno))
    return out


@repo_checker(
    "seam-coverage",
    "Retrier seams must key on a known dependency family "
    "(drift.KNOWN_DEPENDENCIES — the retry.* config families), the "
    "family must be named in the OPERATIONS failure-model/runbook "
    "docs, and a faults.fire()/fire_sync() hook must exist for the "
    "family so the chaos suite can actually drill the seam.  Families "
    "must also be drillable by the WINDOWED kinds (brownout/partition/"
    "flap): at least one async faults.fire() hook — a seam you cannot "
    "brownout is a seam you cannot rehearse.  Sync-only families need "
    "a justified entry in drift.WINDOWED_EXEMPT.")
def check_seam_coverage(ctx: RepoContext) -> List[Finding]:
    out: List[Finding] = []
    modules = ctx.package_modules()
    retrier_seams = _collect_seams(modules, frozenset({"run"}),
                                   require_retrier=True)
    async_fault_seams = _collect_seams(modules, frozenset({"fire"}),
                                       require_retrier=False)
    sync_fault_seams = _collect_seams(modules, frozenset({"fire_sync"}),
                                      require_retrier=False)
    fault_seams = async_fault_seams + sync_fault_seams
    fault_families = {_seam_dependency(seam) for seam, _, _ in fault_seams}
    async_families = {_seam_dependency(seam)
                      for seam, _, _ in async_fault_seams}

    # windowed drillability: a family whose only hooks are fire_sync
    # cannot take brownout latency or a blackhole partition — `make
    # degraded` would silently skip it.  Anchored at the family's first
    # sync hook (the place an async hook belongs next to).
    flagged_windowed: Set[str] = set()
    for seam, path, line in sync_fault_seams:
        family = _seam_dependency(seam)
        if (family in KNOWN_DEPENDENCIES
                and family not in async_families
                and family not in WINDOWED_EXEMPT
                and family not in flagged_windowed):
            flagged_windowed.add(family)
            out.append(Finding(
                "seam-coverage", path, line,
                f'dependency family "{family}" is only drillable '
                "synchronously (fire_sync) — the windowed fault kinds "
                "(brownout/partition/flap) cannot inject latency here; "
                "add an async faults.fire() hook or a justified "
                "drift.WINDOWED_EXEMPT entry"))

    for seam, path, line in fault_seams:
        family = _seam_dependency(seam)
        if family not in KNOWN_DEPENDENCIES:
            out.append(Finding(
                "seam-coverage", path, line,
                f'fault seam "{seam}" keys on unknown dependency '
                f'family "{family}" — add it to '
                "drift.KNOWN_DEPENDENCIES and the OPERATIONS docs"))

    for seam, path, line in retrier_seams:
        family = _seam_dependency(seam)
        if family not in KNOWN_DEPENDENCIES:
            out.append(Finding(
                "seam-coverage", path, line,
                f'Retrier seam "{seam}" keys on unknown dependency '
                f'family "{family}" — retry.{family}/breakers.{family} '
                "config would silently fall back to defaults; add the "
                "family to drift.KNOWN_DEPENDENCIES + OPERATIONS"))
            continue
        if not re.search(rf"\b{re.escape(family)}\b",
                         ctx.operations_md):
            out.append(Finding(
                "seam-coverage", path, line,
                f'Retrier dependency family "{family}" is not named in '
                "docs/OPERATIONS.md — operators cannot tune what the "
                "docs do not admit exists"))
        if family != "settle" and family not in fault_families:
            out.append(Finding(
                "seam-coverage", path, line,
                f'Retrier seam "{seam}" has no faults.fire() hook in '
                f'its family "{family}" — the chaos suite cannot '
                "inject failures at this seam (make chaos blind spot)"))
    return out


# -- event drift --------------------------------------------------------

#: regex for a catalog-able event name (the flight-recorder kinds are
#: all lower_snake identifiers)
_EVENT_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*)`")


def _catalog_events(architecture_md: str) -> Set[str]:
    """Event kinds documented in the ARCHITECTURE.md event-schema
    catalog: every backticked identifier in the FIRST column of the
    markdown table rows inside the flight-recorder section (rows like
    ``| `queue_wait` / `sched_wait` | ... |`` contribute both names)."""
    match = re.search(
        r"^### Per-job flight recorder.*?(?=^### |^## |\Z)",
        architecture_md, re.DOTALL | re.MULTILINE)
    section = match.group(0) if match else ""
    out: Set[str] = set()
    for line in section.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = stripped.split("|")
        if len(cells) < 3:
            continue
        out.update(_EVENT_NAME_RE.findall(cells[1]))
    return out


def _emitted_events(modules: Iterable[ModuleSource]):
    """(event name, path, line) for every literal flight-recorder event
    emitted in the package: ``<record>.event("<kind>", ...)`` and the
    origin plane's ``self._event("<kind>", ...)`` wrapper, plus direct
    ``<recorder>.record("<kind>", ...)`` calls (receiver named
    *recorder — a bare ``.record`` is too common a method name)."""
    out = []
    for module in modules:
        if module.tree is None:
            continue
        for node in module.nodes:
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr == "record":
                receiver = func.value
                rname = receiver.attr if isinstance(
                    receiver, ast.Attribute) else (
                    receiver.id if isinstance(receiver, ast.Name)
                    else "")
                if not rname.lower().endswith("recorder"):
                    continue
            elif func.attr not in ("event", "_event"):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue  # dynamic kind: the wrapper seams themselves
            out.append((arg.value, module.rel_path, node.lineno))
    return out


@repo_checker(
    "event-drift",
    "Every FlightRecorder event kind emitted in downloader_tpu/ "
    "(record.event(\"<kind>\") / self._event(\"<kind>\") / "
    "recorder.record(\"<kind>\")) must appear in the "
    "docs/ARCHITECTURE.md event-schema catalog table — the per-job "
    "timeline is an operator API, and PRs 10/14 shipped "
    "origin_probe/range_assign/fenced-write events that drifted past "
    "the PR 3 docs unnoticed.")
def check_event_drift(ctx: RepoContext) -> List[Finding]:
    out: List[Finding] = []
    catalog = _catalog_events(getattr(ctx, "architecture_md", ""))
    flagged: Set[str] = set()
    for name, path, line in _emitted_events(ctx.package_modules()):
        if name in catalog or name in flagged:
            continue
        flagged.add(name)  # one finding per kind, at its first emitter
        out.append(Finding(
            "event-drift", path, line,
            f'flight-recorder event "{name}" is not in the '
            "docs/ARCHITECTURE.md event catalog (the Per-job flight "
            "recorder table) — document its fields and emitter before "
            "it ships"))
    return out
