"""proto-additive-only: the wire schema may only grow, never mutate.

The queues are consumed by mixed-version replicas (and, per PARITY.md,
by a foreign triton-core fleet), so ``schemas/downloader.proto`` is
additive-only: a field number, once shipped, is burned forever — it
may never be renumbered, retyped, relabeled, or reused by a different
name.  tests/test_wire_freeze.py proves this dynamically against the
*generated* module; this checker proves it statically against the
``.proto`` source, so a bad edit fails ``make lint`` before anyone
regenerates or publishes a byte.

:data:`FROZEN_MESSAGES` / :data:`FROZEN_ENUMS` is the wire high-water
mark: every field shipped through PR 10.  Extending a message is legal
only at numbers ABOVE its frozen maximum (numbers at or below it are
all accounted for — a "new" field down there is a reuse).  When a PR
deliberately adds fields, it must extend these tables in the same
commit (mirroring the test_wire_freeze.py row it also adds).
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from .core import Finding, RepoContext, repo_checker

# (type, label, number) per field; label is "" or "repeated"/"optional"
FROZEN_MESSAGES: Dict[str, Dict[str, Tuple[str, str, int]]] = {
    "Media": {
        "id": ("string", "", 1),
        "creator_id": ("string", "", 2),
        "name": ("string", "", 3),
        "type": ("MediaType", "", 4),
        "source": ("SourceType", "", 5),
        "source_uri": ("string", "", 6),
    },
    "Download": {
        "media": ("Media", "", 1),
        "created_at": ("string", "", 2),
        "priority": ("JobPriority", "", 3),
        "tenant": ("string", "", 4),
        "ttl_seconds": ("double", "", 5),
        "mirrors": ("string", "repeated", 6),
        "source_kind": ("SourceKind", "", 7),
    },
    "Convert": {
        "created_at": ("string", "", 1),
        "media": ("Media", "", 2),
        "deadline_seconds": ("double", "", 3),
    },
    "TelemetryStatusEvent": {
        "media_id": ("string", "", 1),
        "status": ("TelemetryStatus", "", 2),
    },
    "TelemetryProgressEvent": {
        "media_id": ("string", "", 1),
        "status": ("TelemetryStatus", "", 2),
        "percent": ("int32", "", 3),
    },
}

FROZEN_ENUMS: Dict[str, Dict[str, int]] = {
    "SourceType": {"TORRENT": 0, "HTTP": 1, "FILE": 2, "BUCKET": 3},
    "MediaType": {"TV": 0, "MOVIE": 1},
    "TelemetryStatus": {
        "CREATED": 0, "QUEUED": 1, "DOWNLOADING": 2, "CONVERTING": 3,
        "UPLOADING": 4, "DEPLOYED": 5, "ERRORED": 6, "CANCELLED": 7,
    },
    "JobPriority": {"NORMAL": 0, "HIGH": 1, "BULK": 2},
    "SourceKind": {"AUTO": 0, "DIRECT": 1, "MANIFEST": 2},
}

_BLOCK_RE = re.compile(r"^\s*(message|enum)\s+(\w+)\s*\{", re.MULTILINE)
_FIELD_RE = re.compile(
    r"^\s*(?:(repeated|optional)\s+)?([\w.]+)\s+(\w+)\s*=\s*(\d+)\s*;")
_ENUM_VALUE_RE = re.compile(r"^\s*(\w+)\s*=\s*(\d+)\s*;")


def parse_proto(text: str):
    """Line parser good for the subset of proto3 this repo writes:
    top-level messages/enums with scalar/message fields.  Returns
    (messages, enums, line map) where line maps ``(block, name)`` to
    the source line of each field/value."""
    messages: Dict[str, Dict[str, Tuple[str, str, int]]] = {}
    enums: Dict[str, Dict[str, int]] = {}
    lines_of: Dict[Tuple[str, str], int] = {}
    current: Tuple[str, str] = ("", "")
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.split("//", 1)[0]
        block = _BLOCK_RE.match(line)
        if block is not None:
            current = (block.group(1), block.group(2))
            if block.group(1) == "message":
                messages.setdefault(block.group(2), {})
            else:
                enums.setdefault(block.group(2), {})
            lines_of[current] = lineno
            continue
        if line.strip().startswith("}"):
            current = ("", "")
            continue
        kind, name = current
        if kind == "message":
            field = _FIELD_RE.match(line)
            if field is not None:
                label, ftype, fname, number = field.groups()
                messages[name][fname] = (ftype, label or "", int(number))
                lines_of[(name, fname)] = lineno
        elif kind == "enum":
            value = _ENUM_VALUE_RE.match(line)
            if value is not None:
                enums[name][value.group(1)] = int(value.group(2))
                lines_of[(name, value.group(1))] = lineno
    return messages, enums, lines_of


@repo_checker(
    "proto-freeze",
    "schemas/downloader.proto is additive-only: frozen fields keep "
    "name/type/label/number forever; new fields only above each "
    "message's frozen high-water number.  Extend wire.FROZEN_MESSAGES "
    "(and tests/test_wire_freeze.py) in the same commit as any "
    "deliberate addition.")
def check_proto_freeze(ctx: RepoContext) -> List[Finding]:
    if not ctx.proto_text:
        return []
    out: List[Finding] = []
    path = ctx.proto_path
    messages, enums, lines_of = parse_proto(ctx.proto_text)

    def flag(anchor: Tuple[str, str], message: str):
        out.append(Finding("proto-freeze", path,
                           lines_of.get(anchor, 1), message))

    for mname, frozen_fields in FROZEN_MESSAGES.items():
        actual = messages.get(mname)
        if actual is None:
            out.append(Finding(
                "proto-freeze", path, 1,
                f"frozen message {mname} deleted from the schema"))
            continue
        high_water = max(num for _, _, num in frozen_fields.values())
        for fname, (ftype, label, number) in frozen_fields.items():
            got = actual.get(fname)
            if got is None:
                flag(("message", mname),
                     f"frozen field {mname}.{fname} (= {number}) "
                     "removed — numbers are burned, never freed")
            elif got != (ftype, label, number):
                flag((mname, fname),
                     f"frozen field {mname}.{fname} changed: "
                     f"{got[1] or 'singular'} {got[0]} = {got[2]} vs "
                     f"frozen {label or 'singular'} {ftype} = {number}")
        numbers: Dict[int, str] = {}
        for fname, (ftype, label, number) in actual.items():
            if fname in frozen_fields:
                numbers[number] = fname
                continue
            if number <= high_water:
                flag((mname, fname),
                     f"new field {mname}.{fname} reuses number "
                     f"{number} at or below the frozen high-water mark "
                     f"({high_water}) — that number belonged to "
                     "another field on deployed wires")
            if number in numbers:
                flag((mname, fname),
                     f"{mname}.{fname} duplicates field number "
                     f"{number} (also {numbers[number]})")
            numbers[number] = fname

    for ename, frozen_values in FROZEN_ENUMS.items():
        actual_values = enums.get(ename)
        if actual_values is None:
            out.append(Finding(
                "proto-freeze", path, 1,
                f"frozen enum {ename} deleted from the schema"))
            continue
        high_water = max(frozen_values.values())
        for vname, number in frozen_values.items():
            got = actual_values.get(vname)
            if got is None:
                flag(("enum", ename),
                     f"frozen enum value {ename}.{vname} (= {number}) "
                     "removed")
            elif got != number:
                flag((ename, vname),
                     f"frozen enum value {ename}.{vname} renumbered "
                     f"{number} -> {got}")
        for vname, number in actual_values.items():
            if vname not in frozen_values and number <= high_water:
                flag((ename, vname),
                     f"new enum value {ename}.{vname} reuses number "
                     f"{number} at or below the frozen high-water mark "
                     f"({high_water})")
    return out
