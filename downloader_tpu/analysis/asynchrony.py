"""Async hot-path rules: the invariants PRs 8-10 paid review rounds for.

Each rule here encodes one documented incident (CHANGES.md):

- **ack-settle-atomicity** (PR 8 review): an ``await`` between
  ``delivery.ack()``/``.nack()`` and the terminal
  ``registry.transition`` lets ack-woken observers (broker join,
  drain, ``/v1/jobs`` pollers) see a settled-but-not-terminal limbo.
- **unbounded-timeout** (PR 10 review round 2): aiohttp treats an
  explicit ``timeout=None`` as UNBOUNDED, not "session default" — a
  black-holed origin rides the watchdog instead of failing over.
- **blocking-call-in-async** (the LoopLagMonitor's raison d'être,
  PR 3/8): synchronous file/dir/sleep work on the event loop stalls
  every job on the worker; push it through ``asyncio.to_thread`` or an
  executor.
- **swallowed-cancellation**: catching ``BaseException`` (or bare
  ``except``) in async code without re-raising eats
  ``asyncio.CancelledError`` — cancel tokens, watchdogs, and shutdown
  then hang on a task that refuses to die.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .core import Finding, ModuleSource, module_checker

# -- ack-settle atomicity ----------------------------------------------

_SETTLE_ATTRS = frozenset({"ack", "nack"})


def _stmt_settle_line(stmt: ast.stmt) -> Optional[int]:
    """Line of a STATEMENT-LEVEL awaited ``.ack()``/``.nack()``
    (``await delivery.ack()`` as an expression statement or the value
    of an assignment).  Settles nested in compound statements are
    checked within their own branch's block instead — a branch that
    settles and returns must not poison the scan of the outer block
    it never flows back into."""
    value = None
    if isinstance(stmt, ast.Expr):
        value = stmt.value
    elif isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        value = stmt.value
    if (isinstance(value, ast.Await)
            and isinstance(value.value, ast.Call)
            and isinstance(value.value.func, ast.Attribute)
            and value.value.func.attr in _SETTLE_ATTRS):
        return value.lineno
    return None


def _iter_blocks(module: ModuleSource) -> Iterable[List[ast.stmt]]:
    for node in module.nodes:
        for attr in ("body", "orelse", "finalbody"):
            block = getattr(node, attr, None)
            if isinstance(block, list) and block \
                    and isinstance(block[0], ast.stmt):
                yield block


@module_checker(
    "ack-settle-atomicity",
    "No await between a delivery .ack()/.nack() and the terminal "
    "registry .transition() that follows it: the ack wakes observers "
    "(broker join, drain, /v1/jobs) who must never see a "
    "settled-but-not-terminal record (PR 8 incident).")
def check_ack_settle(module: ModuleSource) -> List[Finding]:
    if ".ack(" not in module.text and ".nack(" not in module.text:
        return []  # cheap text gate: most modules never settle deliveries
    # one children-before-parents pass computes, per node: the first
    # await line and the first .transition() call line in its subtree
    # (module.nodes is breadth-first, so reversed = children first)
    first_await: dict = {}
    first_transition: dict = {}
    for node in reversed(module.nodes):
        awaited: Optional[int] = None
        transition: Optional[int] = None
        for child in ast.iter_child_nodes(node):
            child_await = first_await[id(child)]
            if child_await is not None and (awaited is None
                                            or child_await < awaited):
                awaited = child_await
            child_transition = first_transition[id(child)]
            if child_transition is not None and (
                    transition is None or child_transition < transition):
                transition = child_transition
        if isinstance(node, ast.Await):
            awaited = min(awaited or node.lineno, node.lineno)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "transition"):
            transition = min(transition or node.lineno, node.lineno)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested definition neither awaits nor settles when the
            # enclosing block runs — its body must not leak into the
            # outer scan (its OWN blocks are still scanned directly)
            awaited = None
            transition = None
        first_await[id(node)] = awaited
        first_transition[id(node)] = transition

    def _stmt_blocks(stmt: ast.stmt):
        for attr in ("body", "orelse", "finalbody"):
            block = getattr(stmt, attr, None)
            if isinstance(block, list) and block \
                    and isinstance(block[0], ast.stmt):
                yield block
        for handler in getattr(stmt, "handlers", []) or []:
            if handler.body:
                yield handler.body

    def _await_before_transition(stmt: ast.stmt) -> Optional[int]:
        """An await that resolves before a transition WITHIN ``stmt``,
        branch-aware: each block of a compound statement is scanned
        independently, so an await in one branch never counts against
        a transition in a mutually-exclusive sibling branch."""
        blocks = list(_stmt_blocks(stmt))
        if not blocks:
            # simple statement: only awaits nested in the transition
            # call's own ARGUMENTS run first (argument evaluation
            # precedes the call regardless of line layout)
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "transition"):
                    for arg in list(node.args) + [kw.value for kw
                                                  in node.keywords]:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Await):
                                return sub.lineno
            return None
        for block in blocks:
            pending: Optional[int] = None
            for inner in block:
                if first_transition[id(inner)] is not None:
                    if pending is not None:
                        return pending
                    nested = _await_before_transition(inner)
                    if nested is not None:
                        return nested
                    break  # transition settled this block; later
                    # awaits in it are the blessed cleanup pattern
                if pending is None:
                    pending = first_await[id(inner)]
        return None

    out = []
    for block in _iter_blocks(module):
        for index, stmt in enumerate(block):
            settle_line = _stmt_settle_line(stmt)
            if settle_line is None:
                continue
            pending: Optional[int] = None
            for later in block[index + 1:]:
                if first_transition[id(later)] is not None:
                    if pending is None:
                        pending = _await_before_transition(later)
                    if pending is not None:
                        out.append(Finding(
                            "ack-settle-atomicity", module.rel_path,
                            pending,
                            "await between delivery settle (line "
                            f"{settle_line}) and the terminal "
                            "registry.transition — observers woken by "
                            "the ack see a settled-but-not-terminal "
                            "record; transition first, then await",
                        ))
                    break
                if pending is None:
                    pending = first_await[id(later)]
    return out


# -- unbounded aiohttp timeouts ----------------------------------------

_HTTP_METHOD_ATTRS = frozenset({
    "get", "post", "head", "put", "patch", "delete", "options",
    "request", "ws_connect",
})


def _callable_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


@module_checker(
    "unbounded-timeout",
    "Explicit timeout=None on an aiohttp session/request call (or "
    "ClientTimeout(total=None)) is UNBOUNDED — not 'session default' "
    "(PR 10 review round 2).  Pass a finite ClientTimeout, or omit "
    "the kwarg to inherit the session's.")
def check_unbounded_timeout(module: ModuleSource) -> List[Finding]:
    out = []
    for node in module.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = _callable_name(node.func)
        if name == "ClientTimeout":
            for kw in node.keywords:
                if (kw.arg == "total"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is None):
                    out.append(Finding(
                        "unbounded-timeout", module.rel_path, node.lineno,
                        "ClientTimeout(total=None) never fires — bound "
                        "the request or drop the kwarg"))
            continue
        if name not in _HTTP_METHOD_ATTRS and name != "ClientSession":
            continue
        for kw in node.keywords:
            if (kw.arg == "timeout"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                out.append(Finding(
                    "unbounded-timeout", module.rel_path, node.lineno,
                    f"timeout=None on {name}() is unbounded in aiohttp "
                    "(not the session default) — a black-holed peer "
                    "hangs the call forever"))
    return out


# -- blocking calls on the event loop ----------------------------------

#: module.attr calls that block the loop; shutil is wildcarded (every
#: public shutil helper is synchronous bulk I/O).
_BLOCKING_MODULE_CALLS = {
    "time": frozenset({"sleep"}),
    "os": frozenset({"walk"}),
    "json": frozenset({"load", "dump"}),
    "shutil": None,  # None = every attr
}


@module_checker(
    "blocking-call-in-async",
    "Synchronous blocking work (time.sleep, open(), os.walk, shutil.*, "
    "json.load/dump on files) called directly inside an async def stalls "
    "the event loop for every job on the worker — the reason "
    "LoopLagMonitor exists.  Route it through asyncio.to_thread / an "
    "executor, or move it to a sync helper the caller offloads.")
def check_blocking_in_async(module: ModuleSource) -> List[Finding]:
    if module.profile != "library":
        # the invariant protects the WORKER's event loop: one stalled
        # loop stalls every job on the replica.  Tests, benches, and
        # CLI tools run private, single-user loops where a blocking
        # metadata touch costs only their own wall clock.
        return []
    out = []
    for node in module.nodes:
        if not isinstance(node, ast.Call):
            continue
        blocked: Optional[str] = None
        func = node.func
        if isinstance(func, ast.Name) and func.id == "open":
            blocked = "open()"
        elif (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in _BLOCKING_MODULE_CALLS):
            allowed = _BLOCKING_MODULE_CALLS[func.value.id]
            if allowed is None or func.attr in allowed:
                blocked = f"{func.value.id}.{func.attr}()"
        if blocked is None:
            continue
        if not module.in_async_code(node):
            continue
        out.append(Finding(
            "blocking-call-in-async", module.rel_path, node.lineno,
            f"{blocked} blocks the event loop inside an async def — "
            "use asyncio.to_thread / run_in_executor"))
    return out


# -- swallowed cancellation --------------------------------------------

def _catches_base_exception(handler: ast.ExceptHandler) -> bool:
    def is_base(expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id == "BaseException"
        if isinstance(expr, ast.Attribute):
            return expr.attr == "BaseException"
        return False

    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Tuple):
        return any(is_base(elt) for elt in handler.type.elts)
    return is_base(handler.type)


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True
            if (isinstance(node.exc, ast.Name)
                    and node.exc.id == handler.name):
                return True
    return False


@module_checker(
    "swallowed-cancellation",
    "except BaseException / bare except inside async code without a "
    "re-raise eats asyncio.CancelledError — cancel tokens, watchdog "
    "task-cancels, and shutdown then hang on a task that will not die. "
    "(except Exception is safe: CancelledError derives from "
    "BaseException on 3.8+.)")
def check_swallowed_cancellation(module: ModuleSource) -> List[Finding]:
    out = []
    for node in module.nodes:
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _catches_base_exception(node):
            continue
        if not module.in_async_code(node):
            continue
        if _reraises(node):
            continue
        out.append(Finding(
            "swallowed-cancellation", module.rel_path, node.lineno,
            "BaseException caught in async code without re-raising — "
            "CancelledError must escape (re-raise, or narrow to "
            "Exception)"))
    return out
