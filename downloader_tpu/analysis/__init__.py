"""graftlint: the repo-invariant static analyzer (ISSUE 11).

Machine-checks the correctness rules PRs 5-10 learned in review
rounds: ack-settle atomicity, bounded aiohttp timeouts, no blocking
calls on the event loop, cancellation hygiene, knob/metric catalog
drift, Retrier-seam fault coverage, and the additive-only wire
schema — plus the generic eslint-parity rules folded in from the seed
lint suite.  See docs/ANALYSIS.md for the rule catalog.

Usage::

    python -m downloader_tpu.analysis            # full tree, text
    python -m downloader_tpu.analysis --json     # machine output
    make lint                                     # CLI + tier-1 gate

Importing the checker modules registers their rules; keep the imports
even though nothing references them by name.
"""

from . import asynchrony, drift, generic, staging, wire
from .core import (
    DEFAULT_TARGETS,
    AnalysisResult,
    Finding,
    ModuleSource,
    RepoContext,
    all_rules,
    analyze,
    analyze_module,
    analyze_repo,
    apply_suppressions,
    iter_source_files,
    module_checker,
    repo_checker,
)

__all__ = [
    "DEFAULT_TARGETS",
    "AnalysisResult",
    "Finding",
    "ModuleSource",
    "RepoContext",
    "all_rules",
    "analyze",
    "analyze_module",
    "analyze_repo",
    "apply_suppressions",
    "iter_source_files",
    "module_checker",
    "repo_checker",
    "asynchrony",
    "drift",
    "generic",
    "staging",
    "wire",
]
