"""Staging hot-path rules: the zero-copy ratchet's invariants (ISSUE 19).

- **second-pass-read**: the staging pipeline's contract after the
  hash-on-land work is ONE read pass per staged byte — the digest is
  computed at the landing moment (bytes hot in page cache) and carried
  on ``job.landed_digests`` / the fs store's etag memo.  Any new
  ``md5_file_hex`` / ``multipart_etag_hex`` call (or an open-and-hash
  read loop) on a stages/store module re-introduces the full-file
  second read the ratchet just retired.  The blessed sites (the
  landing-site hash itself, the memo-miss fallback, the resume probe
  that has no landed digest to trust) carry justified suppressions.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleSource, module_checker

#: the shared full-file hashing helpers (utils/hashing.py) — each call
#: is, by definition, one complete read pass over the file
_REREAD_HELPERS = frozenset({"md5_file_hex", "multipart_etag_hex"})

#: rule scope: the staging hot path — bytes land in stages/ and are
#: spilled/fetched by store/.  Other packages (control, fleet, cli,
#: tests, bench) hash small metadata where a second pass is noise.
_HOT_PREFIXES = ("downloader_tpu/stages/", "downloader_tpu/store/")


def _expr_helper(expr: ast.expr) -> str:
    """The re-read helper a Name/Attribute expression refers to, or ''."""
    if isinstance(expr, ast.Name) and expr.id in _REREAD_HELPERS:
        return expr.id
    if isinstance(expr, ast.Attribute) and expr.attr in _REREAD_HELPERS:
        return expr.attr
    return ""


def _loop_hashes_reads(loop: ast.stmt) -> bool:
    """True for a loop body that both ``.read()``s and ``.update()``s —
    the shape of a hand-rolled hash-the-whole-file pass."""
    reads = updates = False
    for node in ast.walk(loop):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            if node.func.attr == "read":
                reads = True
            elif node.func.attr == "update":
                updates = True
        if reads and updates:
            return True
    return False


@module_checker(
    "second-pass-read",
    "A full-file re-read (md5_file_hex / multipart_etag_hex, or an "
    "open-and-hash read loop) on the staging hot path (stages/, "
    "store/): the hash-on-land contract is ONE read pass per staged "
    "byte — the landing-site digest rides job.landed_digests and the "
    "fs store's etag memo, so a new full read pass is a cpu_s_per_gb "
    "regression.  Legitimately unavoidable passes (no landed digest "
    "exists) take a justified suppression.")
def check_second_pass_read(module: ModuleSource) -> List[Finding]:
    rel = module.rel_path.replace("\\", "/")
    if module.profile != "library" or not rel.startswith(_HOT_PREFIXES):
        return []
    out = []
    for node in module.nodes:
        if isinstance(node, ast.Call):
            # direct call, or the helper handed to a thread offloader
            # (asyncio.to_thread(md5_file_hex, ...) /
            # run_in_executor(pool, md5_file_hex, ...)) — the pass runs
            # either way, just on another thread
            helper = _expr_helper(node.func)
            if not helper:
                for arg in node.args:
                    helper = _expr_helper(arg)
                    if helper:
                        break
            if helper:
                out.append(Finding(
                    "second-pass-read", module.rel_path, node.lineno,
                    f"{helper}() re-reads the whole file on the staging "
                    "hot path — use the landed digest "
                    "(job.landed_digests / the store's etag memo), or "
                    "justify the pass with a suppression"))
        elif isinstance(node, (ast.While, ast.For)):
            if _loop_hashes_reads(node):
                out.append(Finding(
                    "second-pass-read", module.rel_path, node.lineno,
                    "hand-rolled read()+update() hashing loop on the "
                    "staging hot path — hash at the landing write "
                    "instead (hash-on-land), or justify the pass"))
    return out
