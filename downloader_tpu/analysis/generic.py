"""Generic eslint/ruff-parity rules, folded in from tests/test_lint.py.

These are the seed's mocha-eslint-equivalent checks (SURVEY.md §2
component 7), re-homed into the graftlint registry so the repo has ONE
checker framework: unused imports (F401), bare ``except`` (E722), tabs,
``print()`` in library code, mutable default arguments (B006),
f-strings without placeholders (F541), ``== None/True/False``
(E711/E712), ``is`` against literals (F632), ``raise NotImplemented``
(F901), same-scope redefinition (F811), and discarded ``create_task``
results (RUF006).  tests/test_lint.py now just drives this registry.

The historical ``# noqa`` escapes were migrated to graftlint
suppressions (which require a justification); ``# noqa`` is no longer
honored by any rule here.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleSource, module_checker


@module_checker(
    "tabs",
    "Tab characters in source (the tree is spaces-indented everywhere).")
def check_tabs(module: ModuleSource) -> List[Finding]:
    out = []
    for lineno, line in enumerate(module.lines, start=1):
        if "\t" in line:
            out.append(Finding("tabs", module.rel_path, lineno,
                               "tab character in source"))
    return out


class _ImportUsage(ast.NodeVisitor):
    def __init__(self):
        self.imported = {}  # name -> lineno
        self.used = set()

    def visit_Import(self, node):
        for alias in node.names:
            name = (alias.asname or alias.name).split(".")[0]
            self.imported[name] = node.lineno

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            self.imported[alias.asname or alias.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


@module_checker(
    "unused-import",
    "Imported name never referenced and not re-exported via __all__ "
    "(F401).")
def check_unused_imports(module: ModuleSource) -> List[Finding]:
    usage = _ImportUsage()
    usage.visit(module.tree)
    explicit_exports = set()
    for node in module.nodes:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == "__all__":
                    for elt in getattr(node.value, "elts", []):
                        if isinstance(elt, ast.Constant):
                            explicit_exports.add(elt.value)
    out = []
    for name, line in sorted(usage.imported.items(),
                             key=lambda item: item[1]):
        if (name in usage.used or name in explicit_exports
                or name.startswith("_")):
            continue
        out.append(Finding("unused-import", module.rel_path, line,
                           f"unused import: {name}"))
    return out


@module_checker(
    "bare-except",
    "Bare 'except:' catches SystemExit/KeyboardInterrupt and — in async "
    "code — CancelledError (E722); name the exception class.")
def check_bare_except(module: ModuleSource) -> List[Finding]:
    out = []
    for node in module.nodes:
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Finding("bare-except", module.rel_path, node.lineno,
                               "bare 'except:'"))
    return out


@module_checker(
    "print-in-library",
    "print() in library code — the pipeline logs, it doesn't print "
    "(CLIs, benches, scripts, and tests are exempt by file profile).")
def check_print(module: ModuleSource) -> List[Finding]:
    if module.profile != "library":
        return []
    out = []
    for node in module.nodes:
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            out.append(Finding("print-in-library", module.rel_path,
                               node.lineno, "print() in library code"))
    return out


@module_checker(
    "mutable-default",
    "Mutable default argument shared across calls (B006).")
def check_mutable_defaults(module: ModuleSource) -> List[Finding]:
    out = []
    for node in module.nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for default in [*node.args.defaults, *node.args.kw_defaults]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in {"list", "dict", "set"}
            ):
                out.append(Finding(
                    "mutable-default", module.rel_path, node.lineno,
                    f"mutable default argument in {node.name}()"))
    return out


@module_checker(
    "empty-fstring",
    "f-string without placeholders (F541).")
def check_empty_fstrings(module: ModuleSource) -> List[Finding]:
    # format specs (f"{x:.2f}") are themselves JoinedStr nodes with no
    # FormattedValue parts — not user-facing f-strings, don't flag them
    format_specs = {
        id(node.format_spec)
        for node in module.nodes
        if isinstance(node, ast.FormattedValue)
        and node.format_spec is not None
    }
    out = []
    for node in module.nodes:
        if (isinstance(node, ast.JoinedStr)
                and id(node) not in format_specs
                and not any(isinstance(part, ast.FormattedValue)
                            for part in node.values)):
            out.append(Finding("empty-fstring", module.rel_path,
                               node.lineno,
                               "f-string without placeholders"))
    return out


@module_checker(
    "literal-comparison",
    "Equality against None/True/False (use is/is not, E711/E712) or "
    "'is' against a str/number literal (F632).")
def check_literal_comparisons(module: ModuleSource) -> List[Finding]:
    out = []
    for node in module.nodes:
        if not isinstance(node, ast.Compare):
            continue
        for op, comparator in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                isinstance(comparator, ast.Constant)
                and (comparator.value is None
                     or comparator.value is True
                     or comparator.value is False)
            ):
                out.append(Finding(
                    "literal-comparison", module.rel_path, node.lineno,
                    "use is/is not for None/True/False"))
            if isinstance(op, (ast.Is, ast.IsNot)) and (
                isinstance(comparator, ast.Constant)
                and isinstance(comparator.value, (str, int, float, bytes))
                and not isinstance(comparator.value, bool)
            ):
                out.append(Finding(
                    "literal-comparison", module.rel_path, node.lineno,
                    "'is' comparison against a literal"))
    return out


@module_checker(
    "raise-notimplemented",
    "raise NotImplemented (the constant) instead of "
    "NotImplementedError (F901).")
def check_raise_notimplemented(module: ModuleSource) -> List[Finding]:
    out = []
    for node in module.nodes:
        if not isinstance(node, ast.Raise):
            continue
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        if isinstance(exc, ast.Name) and exc.id == "NotImplemented":
            out.append(Finding(
                "raise-notimplemented", module.rel_path, node.lineno,
                "raise NotImplementedError, not NotImplemented"))
    return out


@module_checker(
    "redefinition",
    "Function redefined in the same scope shadows the first definition "
    "(F811; decorated defs — @property setters, dispatch registrations "
    "— are legitimate).")
def check_redefinition(module: ModuleSource) -> List[Finding]:
    out = []
    for scope in module.nodes:
        if not isinstance(scope, (ast.Module, ast.ClassDef,
                                  ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        seen = {}
        for stmt in getattr(scope, "body", []):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not stmt.decorator_list and stmt.name in seen:
                    out.append(Finding(
                        "redefinition", module.rel_path, stmt.lineno,
                        f"redefinition of {stmt.name}() "
                        f"(first at line {seen[stmt.name]})"))
                seen.setdefault(stmt.name, stmt.lineno)
    return out


@module_checker(
    "discarded-task",
    "create_task() result discarded — the event loop holds only a weak "
    "reference, so the task can be garbage-collected mid-run (RUF006).")
def check_discarded_task(module: ModuleSource) -> List[Finding]:
    out = []
    for node in module.nodes:
        if (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "create_task"):
            out.append(Finding(
                "discarded-task", module.rel_path, node.lineno,
                "create_task() result discarded (task may be GC'd)"))
    return out
