"""graftlint CLI: ``python -m downloader_tpu.analysis [paths...]``.

Exit status 0 = clean (suppressed findings don't count), 1 = findings,
2 = usage error.  ``--json`` emits one machine-readable document (the
``make lint`` mode); text mode prints one ``path:line: [rule] message``
per finding plus a summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import DEFAULT_TARGETS, all_rules, analyze


def _repo_root() -> str:
    # downloader_tpu/analysis/__main__.py -> repo root two levels up
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m downloader_tpu.analysis",
        description="graftlint: repo-invariant static analysis "
                    "(docs/ANALYSIS.md)")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to analyze, relative to the "
                             f"repo root (default: {' '.join(DEFAULT_TARGETS)})")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetected)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON document instead of text")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    def emit(text: str) -> bool:
        """print() that tolerates a closed consumer (``... | head``):
        stops emitting but NEVER changes the exit status — a truncated
        listing of findings must still exit 1."""
        try:
            print(text)
            return True
        except BrokenPipeError:
            os.dup2(os.open(os.devnull, os.O_WRONLY),
                    sys.stdout.fileno())
            return False

    if args.list_rules:
        for rule in all_rules():
            if not emit(f"{rule.id} ({rule.scope})\n    {rule.doc}"):
                break
        return 0

    root = args.root or _repo_root()
    targets = tuple(args.paths) or DEFAULT_TARGETS
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        result = analyze(root, targets=targets, rules=rules)
    except ValueError as err:
        print(f"graftlint: {err}", file=sys.stderr)
        return 2
    if result.files == 0:
        # a typo'd path must not read as a clean tree
        print(f"graftlint: no Python files under {' '.join(targets)} "
              f"(root {root})", file=sys.stderr)
        return 2

    if args.json:
        emit(json.dumps(result.as_dict(), indent=2, sort_keys=True))
    else:
        for finding in result.findings:
            if not emit(finding.render()):
                break
        emit(f"graftlint: {len(result.findings)} finding(s), "
             f"{result.suppressed} suppressed, {result.files} files, "
             f"{result.duration_s:.2f}s")
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
