"""Wire schemas and enum helpers for the downloader pipeline.

Capability-equivalent to the reference's `triton-core/proto` registry usage:
``proto.load`` / ``proto.encode`` / ``proto.decode`` (/root/reference/lib/main.js:55-63,161)
and ``proto.enumToString`` / ``proto.stringToEnum``
(/root/reference/lib/download.js:243, /root/reference/lib/process.js:53).

Messages are real protobuf (see ``downloader.proto``), so the wire format is
binary protobuf just like the reference's, and the generated classes are the
single source of truth for field names and enum values.
"""

from __future__ import annotations

from typing import Type

from google.protobuf.message import Message

from .downloader_pb2 import (
    Convert,
    Download,
    JobPriority,
    Media,
    MediaType,
    SourceKind,
    SourceType,
    TelemetryProgressEvent,
    TelemetryStatus,
    TelemetryStatusEvent,
)

__all__ = [
    "Convert", "Download", "JobPriority", "Media", "MediaType",
    "SourceKind", "SourceType", "TelemetryProgressEvent",
    "TelemetryStatus", "TelemetryStatusEvent",
    "DOWNLOAD_QUEUE", "CONVERT_QUEUE", "CONVERT_EXCHANGE",
    "encode", "decode",
]

# Queue names (reference lib/main.js:164,172).
DOWNLOAD_QUEUE = "v1.download"
CONVERT_QUEUE = "v1.convert"
# fanout exchange feeding CONVERT_QUEUE (when the backend supports
# exchanges), so observers — e.g. `cli submit --wait` — can see job
# completion without stealing the converter's deliveries
CONVERT_EXCHANGE = CONVERT_QUEUE + ".fanout"

_MESSAGE_TYPES = {
    "downloader.Download": Download,
    "downloader.Convert": Convert,
    "downloader.Media": Media,
    "downloader.TelemetryStatusEvent": TelemetryStatusEvent,
    "downloader.TelemetryProgressEvent": TelemetryProgressEvent,
}


def load(name: str) -> Type[Message]:
    """Look up a message class by registry name.

    Mirrors the reference's ``proto.load('api.Download')`` surface
    (/root/reference/lib/main.js:55) with our own registry names.
    """
    try:
        return _MESSAGE_TYPES[name]
    except KeyError:
        raise KeyError(
            f"unknown message type {name!r}; known: {sorted(_MESSAGE_TYPES)}"
        ) from None


# Optional process-wide wire remap (see remap.py): reconciling our frozen
# field numbers with a real triton-core deployment is a config change
# (`wire_remap:` table), not a schema migration.
_active_remap = None


def configure_remap(tables) -> None:
    """Install (or clear, with a falsy argument) the wire remap.

    ``tables`` is the ``wire_remap`` config section: per message simple
    name, a mapping of OUR field name to the DEPLOYMENT's wire number.
    Bad tables (unknown fields, duplicate numbers) fail here, at boot,
    not on the first job.
    """
    global _active_remap
    if not tables:
        _active_remap = None
        return
    from .remap import RemapError, WireRemap

    # every table key must name a message reachable from the registry —
    # a typo ('Mdia') must not silently disable the remap for that type
    known = set()
    stack = [t.DESCRIPTOR for t in _MESSAGE_TYPES.values()]
    while stack:
        descriptor = stack.pop()
        if descriptor.name in known:
            continue
        known.add(descriptor.name)
        stack.extend(f.message_type for f in descriptor.fields
                     if f.message_type is not None)
    unknown = set(tables) - known
    if unknown:
        raise RemapError(
            f"wire_remap names unknown message type(s) {sorted(unknown)}; "
            f"known: {sorted(known)}"
        )

    remap = WireRemap(tables)
    for msg_type in _MESSAGE_TYPES.values():  # compile now -> fail fast
        remap.to_wire(msg_type.DESCRIPTOR, b"")
        remap.from_wire(msg_type.DESCRIPTOR, b"")
    _active_remap = remap


def encode(msg: Message) -> bytes:
    """Serialize a message to its binary wire format (remapped to the
    deployment's field numbers when a wire remap is configured)."""
    data = msg.SerializeToString()
    if _active_remap is not None:
        data = _active_remap.to_wire(msg.DESCRIPTOR, data)
    return data


def decode(msg_type: Type[Message], data: bytes) -> Message:
    """Parse binary wire format into a message instance (translating
    from the deployment's field numbers when a wire remap is configured)."""
    if _active_remap is not None:
        data = _active_remap.from_wire(msg_type.DESCRIPTOR, data)
    msg = msg_type()
    msg.ParseFromString(data)
    return msg


def enum_to_string(enum_type, value: int) -> str:
    """Enum numeric value -> name (reference ``proto.enumToString``,
    /root/reference/lib/download.js:243)."""
    return enum_type.Name(value)


def string_to_enum(enum_type, name: str) -> int:
    """Enum name -> numeric value (reference ``proto.stringToEnum``,
    /root/reference/lib/process.js:53)."""
    return enum_type.Value(name)
