"""Config-driven field-number remapping at the protobuf wire level.

The reference decodes ``api.Download`` / publishes ``api.Convert`` using
triton-core's schema registry (/root/reference/lib/main.js:55-56,163-164).
That package is an npm dependency that is not vendored in the reference
tree, so the field NUMBERS of the real deployment cannot be compared
offline — our schema freezes its own numbers with golden bytes
(tests/test_wire_freeze.py).  If a real deployment's numbers turn out to
differ, this module makes reconciliation a config change instead of a
schema migration: a table like

    wire_remap:
      Media:    {id: 3, creator_id: 1}
      Download: {created_at: 9}

declares, per message type, the DEPLOYMENT's wire number for each of our
field names.  Encoding rewrites our numbers to theirs; decoding rewrites
theirs back to ours.  The rewrite happens on the serialized bytes (one
pass over the tag/value tokens), so the generated classes stay the
single source of truth for field names and no code is regenerated:

- field numbers not mentioned in the table pass through unchanged, so
  unknown fields keep their unknown-field-preservation behavior;
- message-typed fields recurse with their own message's table;
- the mapping must be injective per message (checked at build time).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from google.protobuf.descriptor import Descriptor, FieldDescriptor

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5

# plan: src wire number -> (dst wire number, nested plan | None)
Plan = Dict[int, Tuple[int, Optional[dict]]]


class RemapError(ValueError):
    pass


def _read_varint(data: bytes, i: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if i >= len(data):
            raise RemapError("truncated varint")
        byte = data[i]
        i += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, i
        shift += 7
        if shift > 63:
            raise RemapError("varint too long")


def _append_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def build_plan(descriptor: Descriptor, tables: dict,
               reverse: bool = False) -> Plan:
    """Compile a remap plan for one message type from the config table.

    ``tables`` maps message simple names to ``{field_name: their_number}``.
    ``reverse=True`` builds the decode-direction plan (their -> ours).
    """
    table = dict(tables.get(descriptor.name) or {})
    plan: Plan = {}
    seen_dst: Dict[int, str] = {}
    for field in descriptor.fields:
        theirs = int(table.pop(field.name, field.number))
        if theirs in seen_dst:
            raise RemapError(
                f"{descriptor.name}: fields {seen_dst[theirs]!r} and "
                f"{field.name!r} both map to wire number {theirs}"
            )
        seen_dst[theirs] = field.name
        sub: Optional[Plan] = None
        if field.type == FieldDescriptor.TYPE_MESSAGE:
            sub = build_plan(field.message_type, tables, reverse=reverse)
        if theirs != field.number or sub:
            if reverse:
                plan[theirs] = (field.number, sub)
            else:
                plan[field.number] = (theirs, sub)
    if table:
        raise RemapError(
            f"{descriptor.name}: unknown field(s) in wire_remap: "
            f"{sorted(table)}"
        )
    return plan


def transcode(data: bytes, plan: Plan) -> bytes:
    """Rewrite field numbers in serialized protobuf bytes per ``plan``.

    Unmapped numbers pass through byte-identical (including unknown
    fields).  Groups (wire types 3/4) are legacy proto2 and rejected.
    """
    if not plan:
        return data
    # unknown (pass-through) numbers must not land on a remap target:
    # two same-numbered fields would last-wins-merge in the parser,
    # silently corrupting the mapped field AND swallowing the unknown
    taken = {dst for dst, _sub in plan.values()}
    out = bytearray()
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field, wire_type = key >> 3, key & 7
        dst, sub = plan.get(field, (field, None))
        if field not in plan and field in taken:
            raise RemapError(
                f"unmapped field number {field} collides with a remap "
                f"destination; extend the wire_remap table to cover it"
            )
        _append_varint(out, (dst << 3) | wire_type)
        if wire_type == _WT_VARINT:
            value, i = _read_varint(data, i)
            _append_varint(out, value)
        elif wire_type == _WT_I64:
            out += data[i:i + 8]
            i += 8
        elif wire_type == _WT_I32:
            out += data[i:i + 4]
            i += 4
        elif wire_type == _WT_LEN:
            length, i = _read_varint(data, i)
            if i + length > len(data):
                raise RemapError("truncated length-delimited field")
            chunk = data[i:i + length]
            i += length
            if sub:
                chunk = transcode(chunk, sub)
            _append_varint(out, len(chunk))
            out += chunk
        else:
            raise RemapError(f"unsupported wire type {wire_type}")
    return bytes(out)


class WireRemap:
    """Per-message-type encode/decode plans compiled from a config table."""

    def __init__(self, tables: dict):
        self._tables = dict(tables)
        self._plans: Dict[Tuple[str, bool], Plan] = {}

    def _plan(self, descriptor: Descriptor, reverse: bool) -> Plan:
        key = (descriptor.full_name, reverse)
        if key not in self._plans:
            self._plans[key] = build_plan(
                descriptor, self._tables, reverse=reverse)
        return self._plans[key]

    def to_wire(self, descriptor: Descriptor, data: bytes) -> bytes:
        """Ours -> deployment numbering (encode direction)."""
        return transcode(data, self._plan(descriptor, reverse=False))

    def from_wire(self, descriptor: Descriptor, data: bytes) -> bytes:
        """Deployment -> our numbering (decode direction)."""
        return transcode(data, self._plan(descriptor, reverse=True))
