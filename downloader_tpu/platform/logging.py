"""Structured JSON logging with child loggers.

Capability-equivalent to the reference's pino usage: per-file logger names
(/root/reference/index.js:12-14) and per-job child loggers carrying
``{jobId, fileId}`` bindings (/root/reference/lib/main.js:75-79,103-105).

Log lines are single-line JSON on stderr: ``{"level":..., "time":...,
"name":..., "msg":..., **bindings}`` — the same shape pino emits, so existing
log tooling keyed on that shape keeps working.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, IO, Optional

_LEVELS = {"debug": 20, "info": 30, "warn": 40, "error": 50, "fatal": 60}
_lock = threading.Lock()


def _min_level() -> int:
    return _LEVELS.get(os.environ.get("LOG_LEVEL", "info").lower(), 30)


class Logger:
    """A pino-style structured logger.

    ``child(**bindings)`` returns a logger whose every line carries the
    merged bindings — used by the orchestrator to tag all stage logs with
    the job/file ids.
    """

    def __init__(
        self,
        name: str,
        bindings: Optional[dict] = None,
        stream: Optional[IO[str]] = None,
    ):
        self.name = name
        self.bindings = dict(bindings or {})
        self._stream = stream

    def child(self, **bindings: Any) -> "Logger":
        merged = dict(self.bindings)
        merged.update(bindings)
        name = bindings.pop("name", None) or self.name
        merged.pop("name", None)
        return Logger(name, merged, self._stream)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self.name} {self.bindings}>"

    def _emit(self, level: str, msg: str, extra: dict) -> None:
        if _LEVELS[level] < _min_level():
            return
        record = {
            "level": _LEVELS[level],
            "time": int(time.time() * 1000),
            "name": self.name,
            **self.bindings,
            **extra,
            "msg": msg,
        }
        stream = self._stream or sys.stderr
        line = json.dumps(record, default=str)
        with _lock:
            stream.write(line + "\n")

    def debug(self, msg: str, **extra: Any) -> None:
        self._emit("debug", msg, extra)

    def info(self, msg: str, **extra: Any) -> None:
        self._emit("info", msg, extra)

    def warn(self, msg: str, **extra: Any) -> None:
        self._emit("warn", msg, extra)

    # alias so call sites can use stdlib-style naming
    warning = warn

    def error(self, msg: str, **extra: Any) -> None:
        self._emit("error", msg, extra)

    def fatal(self, msg: str, **extra: Any) -> None:
        self._emit("fatal", msg, extra)


def get_logger(name: str, **bindings: Any) -> Logger:
    """Create a named logger (reference: ``pino({name: basename(__filename)})``)."""
    return Logger(name, bindings)


class NullLogger(Logger):
    """A logger that drops everything — the reference tests' ``mockLogger``
    (/root/reference/test/process/filter_dirs.js:10-14)."""

    def __init__(self) -> None:
        super().__init__("null")

    def child(self, **bindings: Any) -> "Logger":
        return self

    def _emit(self, level: str, msg: str, extra: dict) -> None:
        pass
