"""YAML config loading with env overrides.

Capability-equivalent to the reference's ``triton-core/config``:
``Config('converter')`` loads the YAML config for the shared service key
(/root/reference/index.js:18), and the only key the reference consumes
in-tree is ``config.instance.download_path``
(/root/reference/lib/download.js:235,240).

Config files live in ``$CONFIG_PATH`` (default ``./config``) as
``<service>.yaml``.  Missing files fall back to built-in defaults so the
service boots hermetically.  Nested keys are exposed with attribute access
(``config.instance.download_path``) to keep call sites readable.
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Optional

import yaml

DEFAULTS: dict = {
    "instance": {
        # Relative paths are resolved against the repo root at use time,
        # matching the reference's relative-path fixup
        # (/root/reference/lib/download.js:234-240).
        "download_path": "downloading",
        # Max concurrently-processed jobs (the MQ consumer prefetch).  2
        # is the reference's qos (PARITY.md "AMQP constructor constants");
        # raise it for fan-in traffic where the content cache makes extra
        # in-flight jobs cheap.  Env: MAX_CONCURRENT_JOBS.
        "max_concurrent_jobs": 2,
        # Content-addressed staging cache (store/cache.py).  Disabled
        # unless ``cache.enabled`` is true or ``cache.path`` is set
        # (CACHE_ENABLED / CACHE_DIR).  ``cache.max_bytes`` caps the LRU
        # disk budget (CACHE_MAX_BYTES); ``cache.min_free_bytes`` is the
        # free-disk floor job admission maintains on the cache volume
        # (CACHE_MIN_FREE_BYTES).
        # "cache": {"enabled": True, "path": "...", "max_bytes": ...,
        #           "min_free_bytes": ...},
        #
        # Control plane (control/):
        # "scheduler_backlog": 0,        # extra consumer-prefetch
        #     deliveries held for priority reordering (SCHEDULER_BACKLOG;
        #     0 = FIFO parity, nothing to reorder)
        # "scheduler_aging_seconds": 60, # starvation bump: one priority
        #     class per interval waited (SCHEDULER_AGING_SECONDS)
        # "upload_rate_limit": 0,        # bytes/s egress cap to the
        #     staging store (mirror of download_rate_limit; 0=unlimited)
    },
    # Control-plane admin API (control/api.py, mounted on the health
    # port): "control": {"token": "..."} — bearer token gating the
    # mutating endpoints (env CONTROL_TOKEN); "errored_on_cancel": True
    # keeps legacy telemetry consumers on ERRORED instead of CANCELLED.
    #
    # Dependency fault tolerance (platform/errors.py):
    # "retry": {
    #   "default": {"attempts": 3, "base": 0.1, "cap": 2.0},
    #       # in-process retry budget for transient dependency failures
    #       # (total tries / backoff floor seconds / backoff ceiling);
    #       # per-dependency overrides under "store" | "publish" |
    #       # "http" | "tracker" | "disk"
    #   "redelivery": {"base": 0.25, "cap": 15.0},
    #       # park-then-nack: a transiently-failed delivery waits
    #       # base * 2^(failures-1) (capped) before its nack, so the
    #       # broker redelivers AFTER the blip; base 0 = instant nack
    # },
    # "breakers": {
    #   "enabled": True,
    #   "default": {"threshold": 5, "reset": 30.0},
    #       # consecutive transient failures that open a dependency's
    #       # circuit breaker / seconds until its half-open probe;
    #       # per-dependency overrides like "retry".  "http" (origin
    #       # fetch) is breaker-less by default — one job's dead origin
    #       # must not block the fleet — opt in via
    #       # breakers.http.enabled: true
    # },
    # "faults": {"plan": [...]}  # deterministic fault injection for
    #       # chaos drills (platform/faults.py; env FAULT_PLAN) — see
    #       # docs/OPERATIONS.md "Failure model"
    #
    # Multi-tenant overload control (control/tenancy.py +
    # control/overload.py; docs/OPERATIONS.md "Tenancy & overload"):
    # "tenants": {
    #   "<name>": {
    #     "weight": 1,             # weighted-fair share of run slots
    #         # WITHIN each priority class (stride scheduling; priority
    #         # and aging still dominate)
    #     "max_concurrent": None,  # run slots this tenant may hold at
    #         # once (None = unbounded)
    #     "download_rate_limit": 0,  # per-tenant ingress bytes/s,
    #         # stacked UNDER instance.download_rate_limit
    #     "upload_rate_limit": 0,    # per-tenant egress bytes/s
    #   },
    # },
    # # Absent/unknown Download.tenant runs as "default" (the
    # # unknown-priority -> NORMAL posture); no "tenants" section = the
    # # exact pre-tenancy behavior.
    # "overload": {
    #   "enabled": True,           # false removes the controller
    #   "interval": 1.0,           # pressure sampling cadence, seconds
    #   "sustain": 3,              # consecutive breached samples before
    #       # the worker is declared saturated (and after: one healthy
    #       # sample clears)
    #   "max_loop_lag": 1.5,       # seconds of event-loop lag; 0 off
    #   "min_headroom_bytes": 0,   # shed when disk headroom drops below
    #   "max_queue_depth": 0,      # shed when more jobs are queued
    #   "max_oldest_seconds": 0,   # shed when the oldest queued job ages
    #   "shed_backoff": 5.0,       # park before the shed nack, seconds
    # },
    # # While saturated, BULK deliveries are parked+nacked (never FAILED
    # # permanently, no poison charge); HIGH/NORMAL keep flowing.
    # # Download.ttl_seconds (deadline from receipt): expired BULK is
    # # dropped as EXPIRED, expired HIGH/NORMAL is surfaced but runs.
    #
    # Origin plane (origins/; docs/OPERATIONS.md "Origins & live
    # ingest").  Zero config needed: a job without Download.mirrors
    # and with source_kind AUTO behaves exactly as before.
    # "origins": {
    #   "max_labels": 16,      # distinct origin metric/breaker labels
    #       # per process; overflow hosts collapse to "other" (job
    #       # payloads must not mint Prometheus series — the tenant
    #       # posture)
    #   "dup_factor": 1.25,    # an idle origin duplicates a straggler
    #       # tail only when its EWMA beats the owner's by this factor
    #   "min_dup_bytes": 1048576,  # tails smaller than this are waited
    #       # out, not duplicated
    #   "stall_takeover": 10.0,    # an in-flight range that lands
    #       # nothing for this long is treated as black-holed: idle
    #       # origins may duplicate/take it over regardless of the
    #       # EWMA and min-tail gates
    #   "hedge_delay": 1.0,    # manifest segment fetch: seconds to wait
    #       # for an origin's FIRST byte before hedging to the next
    #   "manifest": {
    #     "min_poll": 0.25,    # playlist refresh clamp (refresh runs at
    #     "max_poll": 6.0,     # target_duration/2 between these bounds)
    #     "stall_timeout": 240.0,  # live playlist unchanged this long
    #         # => ERRDLSTALL (ack + drop, the dead-stream policy)
    #     "live_window": 0,    # join a live playlist at most this many
    #         # segments behind the live edge (0 = from the beginning)
    #   },
    # },
    # # Per-origin fault seams inherit family config:
    # # retry.origin.{attempts,base,cap} and
    # # breakers.origin.{threshold,reset,enabled} cover every
    # # origin:<host> dependency (breakers default ON per origin — a
    # # dead mirror must open ITS breaker without parking the fleet;
    # # admission still keys only on store/publish).
    #
    # Fleet coordination plane (fleet/): disabled by default — a lone
    # worker pays nothing.  See docs/ARCHITECTURE.md "Fleet plane".
    # "fleet": {
    #   "enabled": False,            # FLEET_ENABLED; join the fleet
    #   "backend": "bucket",         # coordination store: staging-bucket
    #       # objects under .fleet/ (default) | "memory" (hermetic,
    #       # single-process tests/benches)
    #   "worker_id": None,           # WORKER_ID; default host-pid-nonce
    #   "heartbeat_interval": 5.0,   # registry re-beat cadence, seconds
    #   "liveness_ttl": 15.0,        # heartbeat age at which a worker
    #       # is considered dead (must exceed heartbeat_interval)
    #   "lease_ttl": 20.0,           # content-lease expiry; a crashed
    #       # leader's work is taken over after this long
    #   "poll_interval": 0.25,       # lease-waiter poll cadence
    #   "max_wait": 600.0,           # waiter livelock bound before an
    #       # uncoordinated fallback fetch
    #   "shared_tier": True,         # spill cache fills to the staging
    #       # bucket (.fleet-cache/<key>/) for peers to materialize
    #   "gc_interval": 300.0,        # shared-tier + tombstone GC sweep
    #       # cadence (0 disables); bounds .fleet-cache/ and .fleet/
    #       # growth on the bucket backend
    #   "shared_max_age": 86400.0,   # evict shared-tier entries older
    #       # than this (manifest age), seconds
    #   "shared_max_bytes": 0,       # shared-tier size budget (oldest
    #       # evicted first; 0 = age bound only)
    # },
    #
    # In-process SLO accounting (control/slo.py; docs/OPERATIONS.md
    # "SLOs, burn rates & the fleet overview").  On by default — the
    # tracker is a deque append per settle.
    # "slo": {
    #   "enabled": True,         # false removes the tracker entirely
    #   "objectives": {          # per-priority-class targets; a key
    #     "HIGH": {              # matching a configured tenant name
    #       "p99_ms": 30000.0,   # creates a tenant-scoped objective
    #       "availability": 0.999,
    #     },
    #   },
    #   "fast_window": 300.0,    # burn-rate fast window, seconds
    #   "slow_window": 3600.0,   # burn-rate slow window, seconds
    #   "budget_window": 86400.0,  # error budget accounting window
    #   "max_events": 8192,      # bounded per-objective event ring
    # },
    "minio": {
        "endpoint": os.environ.get("MINIO_ENDPOINT", "localhost:9000"),
        "access_key": os.environ.get("MINIO_ACCESS_KEY", ""),
        "secret_key": os.environ.get("MINIO_SECRET_KEY", ""),
        "ssl": False,
    },
    "rabbitmq": {
        # "memory" boots hermetically; "amqp" connects to dyn('rabbitmq')
        "backend": "memory",
    },
    "services": {
        # service-discovery name -> address map consumed by dyn()
        "rabbitmq": os.environ.get("RABBITMQ", "amqp://localhost"),
        "minio": os.environ.get("MINIO", "http://localhost:9000"),
    },
}


class ConfigNode(Mapping):
    """Read-only mapping with attribute access over a nested dict."""

    def __init__(self, data: dict):
        self._data = data

    def __getattr__(self, key: str) -> Any:
        try:
            value = self._data[key]
        except KeyError:
            raise AttributeError(key) from None
        return ConfigNode(value) if isinstance(value, dict) else value

    def __getitem__(self, key: str) -> Any:
        value = self._data[key]
        return ConfigNode(value) if isinstance(value, dict) else value

    def __iter__(self):
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def get(self, key: str, default: Any = None) -> Any:
        value = self._data.get(key, default)
        return ConfigNode(value) if isinstance(value, dict) else value

    def to_dict(self) -> dict:
        return dict(self._data)

    def __repr__(self) -> str:
        return f"ConfigNode({self._data!r})"


def _deep_merge(base: dict, override: dict) -> dict:
    out = dict(base)
    for key, value in override.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = value
    return out


def load_config(service: str = "converter", path: Optional[str] = None) -> ConfigNode:
    """Load ``<service>.yaml`` from the config dir, merged over defaults.

    Mirrors ``Config('converter')`` (/root/reference/index.js:18): the
    downloader shares the converter service's config file.
    """
    config_dir = path or os.environ.get("CONFIG_PATH", "config")
    config_file = os.path.join(config_dir, f"{service}.yaml")
    data: dict = {}
    if os.path.exists(config_file):
        with open(config_file, "r", encoding="utf-8") as fh:
            loaded = yaml.safe_load(fh) or {}
            if not isinstance(loaded, dict):
                raise ValueError(f"config file {config_file} must contain a mapping")
            data = loaded
    return ConfigNode(_deep_merge(DEFAULTS, data))


def cfg_get(config, path: str, default: Any = None) -> Any:
    """Safe nested lookup: ``cfg_get(config, "health.sane", False)``.

    Tolerates a None/dict-less config, missing intermediate sections, and
    explicit None values (which fall back to ``default``).  The one place
    config-gated features resolve their keys, instead of each hand-rolling
    the try/except ladder.
    """
    node = config
    for key in path.split("."):
        if node is None or not hasattr(node, "get"):
            return default
        node = node.get(key)
    return default if node is None else node


def dyn(name: str, config: Optional[ConfigNode] = None) -> str:
    """Service-discovery: resolve a service name to an address.

    Capability-equivalent to ``triton-core/dynamics``' ``dyn('rabbitmq')``
    (/root/reference/lib/main.js:46,49).  Resolution order: env var
    ``<NAME>`` (uppercased), then the config ``services`` map, then
    ``localhost``.
    """
    env = os.environ.get(name.upper())
    if env:
        return env
    if config is not None:
        services = config.get("services")
        if services is not None and name in services:
            return services[name]
    defaults = DEFAULTS["services"]
    return defaults.get(name, "localhost")
