"""Deterministic fault injection at the dependency seams.

The fault-tolerance layer (platform/errors.py) is only as trustworthy
as the failures it was proven against, so the same seams the taxonomy
covers — store/S3 ops, convert publish, HTTP origin fetch, tracker
announce, disk preflight — carry injection hooks driven by a declarative
**fault plan**:

.. code-block:: yaml

    faults:
      plan:
        - seam: "store.put"     # fnmatch pattern over the seam name
          match: "job-7"        # optional substring filter on the call key
          kind: error           # error | delay | partial | hang
          count: 5              # how many matching calls to affect
          after: 0              # matching calls to let through first
          fault: transient      # taxonomy class carried by the error
          delay_s: 0.05         # delay/partial sleep length

(env ``FAULT_PLAN`` takes the same list as JSON).  Kinds:

- ``error``   — raise an :class:`InjectedFault` carrying ``fault``
- ``delay``   — sleep ``delay_s`` then let the call through
- ``partial`` — sleep ``delay_s`` (simulated partial progress) then
  raise, modelling a mid-transfer connection drop
- ``hang``    — block until cancelled (exercises cancel tokens and
  watchdogs against a black-holed dependency)
- ``crash``   — SIGKILL this very process at the seam: a deterministic
  crash point for the kill-based chaos harness (tests/test_crash.py,
  ``make crash``).  A real, uncatchable kill — no atexit, no finally,
  no flush — exactly the torn state an OOM-kill leaves, so restart
  reconciliation (control/journal.py) is proven against the worst
  case, not a polite simulation.  ``after``/``count`` place the kill
  precisely (e.g. ``seam: store.put, after: 1`` dies between the first
  staged file and the done marker); the restarted process starts with
  fresh rule counters, so the same plan does not re-kill unless its
  ``after`` is reached again

**Windowed network-degradation kinds** (the degraded-world chaos plane,
``make degraded``).  Per-call counts cannot express "the store is slow
for ten seconds" or "the coordination store flaps" — the failure modes
that defeat count-based breakers in production — so three kinds are
scoped by *wall-clock window* instead: active while ``start_s <=
(now - install time) < start_s + window_s`` (``window_s: 0`` = open-
ended).  ``after``/``count`` still apply to matching calls inside the
window:

- ``brownout``  — add latency to every matching call, then let it
  through: ``latency_ms`` base plus a deterministic ``jitter_ms``
  spread (a fixed sample sequence, no RNG — reruns see identical
  latency trains).  The call SUCCEEDS slowly: exactly the
  "slow is the new down" shape failure-count breakers never see and
  the slow-call policy (platform/errors.py) exists for.
- ``partition`` — refuse the whole seam family for the window: raise
  an :class:`InjectedFault` per call, or black-hole it
  (``blackhole: true`` — block until cancelled).  ``mode``
  (``all`` | ``writes`` | ``reads``) makes it asymmetric: the classic
  degraded coord store that answers reads while conditional puts
  time out is ``mode: writes``.
- ``flap``      — a periodic partition: partitioned for the first
  ``duty`` fraction of every ``period_s`` cycle, healthy for the
  rest.  Same ``mode``/``blackhole`` knobs.  The waiter-livelock
  regression (fleet.max_wait aging) drills with exactly this kind.

**Disk faults** (the storage fault plane, ``make bench-disk``).  The
zero-copy staging path (io_uring landing, sendfile/mmap uploads, the
hardlinked peer tier) has failure modes no network kind can model —
ENOSPC mid-part, EIO on a completion, a short write the caller must
resume, a torn tail across a crash — so a ``disk`` kind injects them
through the VFS shim (platform/vfs.py) every landing/staging write
routes through.  ``disk_mode`` selects the failure shape:

- ``enospc``  — raise :class:`DiskFault` carrying ``errno.ENOSPC``
  (classified ``fault`` — PERMANENT by default for space exhaustion
  drills, or transient when the window models an operator freeing
  space)
- ``eio``     — raise :class:`DiskFault` carrying ``errno.EIO``
- ``short``   — the shim truncates one write syscall (the kernel
  accepted fewer bytes than asked): the caller's resume loop must
  carry on at the right offset, no error raised
- ``latency`` — a slow device: sleep ``latency_ms`` (+ deterministic
  ``jitter_ms``) around the write.  Only enacted where the write
  already runs off the event loop (the io_pool landing thread); on-loop
  writes skip the sleep rather than stall every job
- ``torn``    — crash-consistency: at promote time, rename WITHOUT the
  fsync, corrupt the tail of the renamed file, then SIGKILL — the
  exact page-cache-loss state a power cut leaves, which boot recovery
  must demote back to resumable instead of serving

``disk`` rules are windowed like the network kinds (``start_s`` /
``window_s`` against install time; both 0 = always on), so a drill can
say "the disk is full for ten seconds" — and
``analysis/drift.py``'s windowed-coverage lint enforces that the
family stays drillable (its exemption list is empty).

Count-scoped kinds stay fully deterministic — activation is by *call
count* per rule, no randomness — so a chaos test
(tests/test_faults.py, ``make chaos``) asserts exact retry/breaker
sequences; windowed kinds are deterministic *given the clock* (phase
helpers :meth:`FaultRule.window_active` / :meth:`FaultRule.flap_on`
are pure functions of elapsed time, unit-testable without sleeping).
When no plan is installed the seams pay one module-level ``None``
check (:func:`enabled`), nothing else.

The injector is process-global (:func:`install` / :func:`uninstall`):
the seams live in stages, stores, and the tracker, and threading a
handle through every call path would put a test-harness concern in
every production signature.  The orchestrator installs from config at
construction and uninstalls at shutdown; tests use
``install(...)``/``uninstall()`` in fixtures.
"""

from __future__ import annotations

import asyncio
import fnmatch
import json
import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

from .config import cfg_get
from .errors import FAULT_CLASSES, TRANSIENT

_ENV_PLAN = "FAULT_PLAN"

KINDS = ("error", "delay", "partial", "hang", "crash",
         "brownout", "partition", "flap", "disk")
#: kinds scoped by wall-clock window (anchored at injector install)
WINDOWED_KINDS = frozenset({"brownout", "partition", "flap"})
#: partition/flap asymmetry: which side of the dependency is degraded
MODES = ("all", "writes", "reads")
#: ``disk`` failure shapes (see module docstring)
DISK_MODES = ("enospc", "eio", "short", "latency", "torn")

#: seam ops (the last dotted component) that mutate shared state —
#: what an asymmetric ``mode: writes`` partition refuses while reads
#: pass.  ``bucket`` creates, ``announce`` mutates tracker state.
_WRITE_OPS = frozenset({"put", "delete", "remove", "bucket", "write",
                        "publish", "spill", "announce", "ack", "nack"})

#: the declarative surface of a rule — exactly the keys from_dict
#: accepts and to_dict emits.  The incident plane's bundle/compile
#: round-trip (downloader_tpu/incident) leans on this: a serialized
#: rule must re-load through from_dict on any later version.
RULE_FIELDS = ("seam", "kind", "match", "count", "after", "fault",
               "delay_s", "start_s", "window_s", "latency_ms",
               "jitter_ms", "mode", "blackhole", "period_s", "duty",
               "disk_mode")

#: brownout jitter: a fixed sample sequence standing in for a latency
#: distribution — deterministic across reruns (indexed by per-rule
#: fire count), spread roughly uniform over [0, 1)
_JITTER_SEQ = (0.00, 0.63, 0.21, 0.87, 0.44, 0.95, 0.10, 0.71,
               0.33, 0.52, 0.79, 0.05)


def seam_is_write(seam: str) -> bool:
    """``coord.put`` -> True, ``coord.get`` -> False: the asymmetric-
    partition classification (reads-ok/writes-failing is the classic
    degraded object store)."""
    return seam.rsplit(".", 1)[-1] in _WRITE_OPS


def _crash_now(seam: str) -> None:
    """SIGKILL this process — the deterministic crash point.

    ``signal.SIGKILL`` (not ``os._exit``): the process must die the way
    an OOM-kill kills it — no interpreter teardown, no buffered-file
    flush — so the journal/workdir state the restart reconciles is the
    real torn state, not a softened one.  The raw stderr write is a
    best-effort breadcrumb for the harness log (fd 2, unbuffered — it
    survives the kill).
    """
    import signal

    try:
        os.write(2, f"FAULT CRASH at seam {seam}\n".encode())
    except OSError:
        pass
    os.kill(os.getpid(), signal.SIGKILL)


class InjectedFault(RuntimeError):
    """A failure manufactured by the fault plan (classified per rule)."""

    def __init__(self, seam: str, kind: str, fault_class: str):
        self.fault_seam = seam
        self.kind = kind
        self.fault_class = fault_class
        super().__init__(f"injected {fault_class} fault at {seam} ({kind})")


class DiskFault(OSError):
    """An injected storage failure.  An :class:`OSError` subclass with a
    REAL ``errno`` (ENOSPC/EIO) so every ``err.errno`` check on the
    write path — the multipart abort classifier, the uring fallback,
    the headroom breaker — handles a drill exactly like the kernel's
    own error, while ``fault_class`` keeps the retrier taxonomy
    deterministic per rule."""

    def __init__(self, seam: str, disk_mode: str, err_no: int,
                 fault_class: str):
        self.fault_seam = seam
        self.kind = "disk"
        self.disk_mode = disk_mode
        self.fault_class = fault_class
        super().__init__(
            err_no, f"injected disk fault at {seam} ({disk_mode})")


@dataclass
class FaultRule:
    """One line of the fault plan (see module docstring)."""

    seam: str
    kind: str = "error"
    match: str = ""
    count: Optional[int] = None   # None = every matching call
    after: int = 0
    fault: str = TRANSIENT
    delay_s: float = 0.05
    # -- windowed kinds (brownout | partition | flap) only --------------
    start_s: float = 0.0      # window opens this long after install
    window_s: float = 0.0     # window length (0 = open-ended)
    latency_ms: float = 250.0  # brownout base added latency
    jitter_ms: float = 0.0     # brownout deterministic latency spread
    mode: str = "all"          # partition/flap asymmetry (all|writes|reads)
    blackhole: bool = False    # partition/flap: hang instead of raising
    period_s: float = 2.0      # flap cycle length
    duty: float = 0.5          # flap: partitioned fraction of each cycle
    # -- disk kind only -------------------------------------------------
    disk_mode: str = "enospc"  # enospc|eio|short|latency|torn
    # runtime counters (not config)
    calls: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"fault rule kind must be one of {KINDS}, got {self.kind!r}"
            )
        if self.fault not in FAULT_CLASSES:
            raise ValueError(
                f"fault rule fault must be one of {FAULT_CLASSES}, "
                f"got {self.fault!r}"
            )
        if self.after < 0 or (self.count is not None and self.count < 0):
            raise ValueError("fault rule after/count must be >= 0")
        if self.mode not in MODES:
            raise ValueError(
                f"fault rule mode must be one of {MODES}, got {self.mode!r}"
            )
        if self.start_s < 0 or self.window_s < 0:
            raise ValueError("fault rule start_s/window_s must be >= 0")
        if self.latency_ms < 0 or self.jitter_ms < 0:
            raise ValueError(
                "fault rule latency_ms/jitter_ms must be >= 0")
        if self.kind == "flap" and (
                self.period_s <= 0 or not 0.0 < self.duty <= 1.0):
            raise ValueError(
                "flap rule needs period_s > 0 and 0 < duty <= 1")
        if self.disk_mode not in DISK_MODES:
            raise ValueError(
                f"fault rule disk_mode must be one of {DISK_MODES}, "
                f"got {self.disk_mode!r}")

    @classmethod
    def from_dict(cls, raw: dict) -> "FaultRule":
        unknown = set(raw) - set(RULE_FIELDS)
        if unknown:
            raise ValueError(f"unknown fault rule keys: {sorted(unknown)}")
        if "seam" not in raw:
            raise ValueError("fault rule needs a 'seam'")
        return cls(**raw)

    def to_dict(self) -> dict:
        """The rule's declarative config (RULE_FIELDS only — runtime
        counters excluded), round-trippable through :meth:`from_dict`.
        This is what an incident bundle ships as the fault plan in
        force, so a compiled replay re-arms the exact same rules."""
        return {name: getattr(self, name) for name in RULE_FIELDS}

    # -- windowed phase helpers (pure functions of elapsed time) --------
    def window_active(self, elapsed: float) -> bool:
        """Is the wall-clock window open ``elapsed`` seconds after
        install?  (``window_s: 0`` = open-ended once ``start_s`` passes.)"""
        if elapsed < self.start_s:
            return False
        if self.window_s <= 0:
            return True
        return elapsed < self.start_s + self.window_s

    def flap_on(self, elapsed: float) -> bool:
        """Is a ``flap`` rule in its partitioned phase at ``elapsed``?
        Each ``period_s`` cycle starts partitioned for ``duty`` of it."""
        phase = (elapsed - self.start_s) % self.period_s
        return phase < self.period_s * self.duty

    def mode_covers(self, seam: str) -> bool:
        """Does this rule's asymmetry (``mode``) include ``seam``?"""
        if self.mode == "all":
            return True
        is_write = seam_is_write(seam)
        return is_write if self.mode == "writes" else not is_write

    def brownout_delay_s(self) -> float:
        """The next deterministic brownout latency sample (seconds):
        ``latency_ms`` plus the fire-count-indexed jitter sample."""
        jitter = self.jitter_ms * _JITTER_SEQ[self.fired % len(_JITTER_SEQ)]
        return (self.latency_ms + jitter) / 1000.0

    def applies(self, seam: str, key: str,
                elapsed: Optional[float] = None) -> bool:
        """Match + window + count bookkeeping; True when this call is
        affected.  ``elapsed`` (seconds since injector install) gates
        the windowed kinds; calls outside the window are not counted
        against ``after``/``count``."""
        if not fnmatch.fnmatch(seam, self.seam):
            return False
        if self.match and self.match not in key:
            return False
        if self.kind in WINDOWED_KINDS:
            if not self.mode_covers(seam):
                return False
            if elapsed is None or not self.window_active(elapsed):
                return False
            if self.kind == "flap" and not self.flap_on(elapsed):
                return False
        elif self.kind == "disk":
            # windowed like the network kinds; the 0/0 defaults make an
            # unwindowed rule always-on, so count-scoped disk drills
            # (``after``/``count``) still work unchanged
            if elapsed is None or not self.window_active(elapsed):
                return False
        n = self.calls
        self.calls += 1
        if n < self.after:
            return False
        if self.count is not None and n >= self.after + self.count:
            return False
        return True


class FaultInjector:
    """Executes a fault plan at the seams; tracks firing for tests/bench."""

    def __init__(self, rules: List[FaultRule], logger=None):
        self.rules = rules
        self.logger = logger
        self.fired_total = 0
        # monotonic time of the LAST injected failure: the recovery-time
        # bench measures "dependency healthy -> first completed job" from
        # this moment
        self.last_fired_mono: Optional[float] = None
        # the windowed kinds' wall-clock anchor; install() re-stamps it
        # so a plan built early and installed late still means "window
        # opens start_s after the drill began"
        self.installed_mono = time.monotonic()

    @classmethod
    def from_config(cls, config, logger=None) -> "Optional[FaultInjector]":
        """Build from env ``FAULT_PLAN`` (JSON list) or ``faults.plan``;
        None when no plan is configured."""
        raw_env = os.environ.get(_ENV_PLAN)
        if raw_env:
            try:
                plan = json.loads(raw_env)
            except ValueError as err:
                raise ValueError(f"{_ENV_PLAN} is not valid JSON: {err}")
        else:
            plan = cfg_get(config, "faults.plan", None)
        if not plan:
            return None
        if not isinstance(plan, (list, tuple)):
            raise ValueError("faults.plan must be a list of rules")
        rules = [FaultRule.from_dict(dict(rule)) for rule in plan]
        return cls(rules, logger=logger)

    def disk_action(self, seam: str, key: str = "",
                    thread_ok: bool = False) -> Optional[str]:
        """Consult ``disk`` rules for one write syscall (the VFS shim's
        hook — platform/vfs.py).  Raising modes raise a
        :class:`DiskFault` here; ``latency`` sleeps (only when
        ``thread_ok`` — the caller attests it is off the event loop);
        ``short``/``torn`` return their mode string for the shim to
        enact, since only the shim knows the buffer/rename at hand.
        Returns None when no rule fires."""
        import errno as _errno

        elapsed = time.monotonic() - self.installed_mono
        for rule in self.rules:
            if rule.kind != "disk" or not rule.applies(seam, key, elapsed):
                continue
            self._note_fired(rule)
            mode = rule.disk_mode
            if mode == "latency":
                if thread_ok:
                    time.sleep(rule.brownout_delay_s())
                continue  # the write proceeds (slowly); later rules apply
            self.last_fired_mono = time.monotonic()
            if mode == "enospc":
                raise DiskFault(seam, mode, _errno.ENOSPC, rule.fault)
            if mode == "eio":
                raise DiskFault(seam, mode, _errno.EIO, rule.fault)
            return mode  # "short" | "torn": enacted by the shim
        return None

    def _note_fired(self, rule: FaultRule) -> None:
        rule.fired += 1
        self.fired_total += 1
        if self.logger is not None:
            self.logger.warn("fault injected", seam=rule.seam,
                             kind=rule.kind, fault=rule.fault,
                             fired=rule.fired)

    async def fire(self, seam: str, key: str = "") -> None:
        """Apply the plan to one seam call (raise / delay / hang)."""
        elapsed = time.monotonic() - self.installed_mono
        for rule in self.rules:
            if not rule.applies(seam, key, elapsed):
                continue
            self._note_fired(rule)
            if rule.kind == "crash":
                _crash_now(seam)
            if rule.kind == "disk":
                # async seams (e.g. ``disk.land``) honor the raising and
                # latency modes; short/torn are write-shim mechanics the
                # VFS layer enacts, meaningless at an async hook
                import errno as _errno

                if rule.disk_mode == "latency":
                    await asyncio.sleep(rule.brownout_delay_s())
                    continue
                if rule.disk_mode == "enospc":
                    self.last_fired_mono = time.monotonic()
                    raise DiskFault(seam, "enospc", _errno.ENOSPC,
                                    rule.fault)
                if rule.disk_mode == "eio":
                    self.last_fired_mono = time.monotonic()
                    raise DiskFault(seam, "eio", _errno.EIO, rule.fault)
                continue
            if rule.kind == "brownout":
                # the call SUCCEEDS, slowly: sample the deterministic
                # latency train, sleep, let it through (later rules —
                # e.g. a stacked error — still apply)
                await asyncio.sleep(rule.brownout_delay_s())
                continue
            if rule.kind in ("partition", "flap") and rule.blackhole:
                await asyncio.Event().wait()  # until cancelled
            if rule.kind == "delay":
                await asyncio.sleep(rule.delay_s)
                continue  # delayed, not failed: later rules still apply
            if rule.kind == "hang":
                await asyncio.Event().wait()  # until cancelled
            if rule.kind == "partial":
                # partial progress then a mid-transfer failure
                await asyncio.sleep(rule.delay_s)
            self.last_fired_mono = time.monotonic()
            raise InjectedFault(seam, rule.kind, rule.fault)

    def fire_sync(self, seam: str, key: str = "") -> None:
        """Synchronous seams (disk preflight) support ``error``,
        ``crash``, the refusing (non-blackhole) side of
        ``partition``/``flap``, and the raising ``disk`` modes
        (ENOSPC/EIO) — a blocking sleep would stall the event loop, so
        latency kinds never inject here (disk latency rides the VFS
        shim's off-loop writes instead)."""
        import errno as _errno

        elapsed = time.monotonic() - self.installed_mono
        for rule in self.rules:
            if not rule.applies(seam, key, elapsed):
                continue
            if rule.kind == "crash":
                self._note_fired(rule)
                _crash_now(seam)
            if rule.kind == "disk":
                if rule.disk_mode in ("enospc", "eio"):
                    self._note_fired(rule)
                    self.last_fired_mono = time.monotonic()
                    raise DiskFault(
                        seam, rule.disk_mode,
                        _errno.ENOSPC if rule.disk_mode == "enospc"
                        else _errno.EIO,
                        rule.fault)
                continue
            if rule.kind in ("partition", "flap") and not rule.blackhole:
                self._note_fired(rule)
                self.last_fired_mono = time.monotonic()
                raise InjectedFault(seam, rule.kind, rule.fault)
            if rule.kind != "error":
                continue
            self._note_fired(rule)
            self.last_fired_mono = time.monotonic()
            raise InjectedFault(seam, rule.kind, rule.fault)


# -- process-global installation ---------------------------------------

_ACTIVE: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _ACTIVE
    # anchor the windowed kinds at install time: "start_s after the
    # drill began", not after the plan object happened to be built
    injector.installed_mono = time.monotonic()
    _ACTIVE = injector
    return injector


def uninstall(injector: Optional[FaultInjector] = None) -> None:
    """Remove the active injector.  Pass the instance you installed to
    make uninstall idempotent across owners (the orchestrator only
    removes its own, never a test's)."""
    global _ACTIVE
    if injector is None or _ACTIVE is injector:
        _ACTIVE = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def enabled() -> bool:
    """The zero-overhead guard seams check before awaiting :func:`fire`."""
    return _ACTIVE is not None


async def fire(seam: str, key: str = "") -> None:
    if _ACTIVE is not None:
        await _ACTIVE.fire(seam, key)


def fire_sync(seam: str, key: str = "") -> None:
    if _ACTIVE is not None:
        _ACTIVE.fire_sync(seam, key)


def disk_action(seam: str, key: str = "",
                thread_ok: bool = False) -> Optional[str]:
    """The VFS shim's per-syscall hook (see
    :meth:`FaultInjector.disk_action`); None when no injector or no
    matching ``disk`` rule."""
    if _ACTIVE is not None:
        return _ACTIVE.disk_action(seam, key, thread_ok=thread_ok)
    return None
