"""Job telemetry: status + progress events published to the queue.

Capability-equivalent to ``triton-core/telemetry``: ``emitStatus(jobId, 2)``
(/root/reference/lib/main.js:68) and
``emitProgress(id, DOWNLOADING, percent)``
(/root/reference/lib/download.js:85,255,272, lib/upload.js:51), delivered
over RabbitMQ (lib/main.js:49-50).

Events are protobuf (``TelemetryStatusEvent`` / ``TelemetryProgressEvent``)
on the ``v1.telemetry.status`` / ``v1.telemetry.progress`` queues.  The
reference stores its telemetry client in ``global.telem`` (lib/main.js:52,
self-annotated ``// BAD``); here the client is passed explicitly to every
stage (SURVEY.md §7 step 6 lists that global as a bug to fix).
"""

from __future__ import annotations

from .. import schemas
from ..mq.base import MessageQueue

STATUS_QUEUE = "v1.telemetry.status"
PROGRESS_QUEUE = "v1.telemetry.progress"
# fanout exchanges feeding the canonical queues, so observers (cli watch)
# can bind their own tap queues without stealing the work-queue deliveries
STATUS_EXCHANGE = STATUS_QUEUE + ".fanout"
PROGRESS_EXCHANGE = PROGRESS_QUEUE + ".fanout"


class Telemetry:
    """Publishes job status/progress events.

    ``metrics`` is optional, mirroring how the reference passes its prom
    handle into Telemetry for internal counters (lib/main.js:49).

    Events go through fanout exchanges bound to the canonical queues when
    the backend supports exchanges (AMQP, memory broker): downstream
    consumers read the same queue names as before, and any number of
    observers can tap the stream with their own bound queues.  Backends
    without exchange support fall back to direct queue publishes.
    """

    def __init__(self, mq: MessageQueue, metrics=None):
        self._mq = mq
        self._metrics = metrics
        self._fanout = False

    async def connect(self) -> None:
        """(reference lib/main.js:50)"""
        await self._mq.connect()
        try:
            await self._mq.bind_queue(STATUS_QUEUE, STATUS_EXCHANGE)
            await self._mq.bind_queue(PROGRESS_QUEUE, PROGRESS_EXCHANGE)
            self._fanout = True
        except NotImplementedError:
            self._fanout = False

    async def close(self) -> None:
        """Tear down the telemetry connection (graceful shutdown)."""
        await self._mq.close()

    async def _publish(self, queue: str, exchange: str, body: bytes) -> None:
        if self._fanout:
            await self._mq.publish_exchange(exchange, body)
        else:
            await self._mq.publish(queue, body)
        if self._metrics is not None:
            self._metrics.messages_published.labels(queue=queue).inc()

    async def emit_status(self, media_id: str, status: int) -> None:
        event = schemas.TelemetryStatusEvent(media_id=media_id, status=status)
        await self._publish(STATUS_QUEUE, STATUS_EXCHANGE,
                            schemas.encode(event))

    async def emit_progress(self, media_id: str, status: int, percent: int) -> None:
        event = schemas.TelemetryProgressEvent(
            media_id=media_id, status=status, percent=int(percent)
        )
        await self._publish(PROGRESS_QUEUE, PROGRESS_EXCHANGE,
                            schemas.encode(event))


class NullTelemetry(Telemetry):
    """Telemetry sink that drops everything (hermetic stage tests)."""

    def __init__(self) -> None:  # noqa: D401
        super().__init__(mq=None)  # type: ignore[arg-type]

    async def close(self) -> None:
        pass

    async def connect(self) -> None:
        pass

    async def emit_status(self, media_id: str, status: int) -> None:
        pass

    async def emit_progress(self, media_id: str, status: int, percent: int) -> None:
        pass
