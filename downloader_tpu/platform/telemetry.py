"""Job telemetry: status + progress events published to the queue.

Capability-equivalent to ``triton-core/telemetry``: ``emitStatus(jobId, 2)``
(/root/reference/lib/main.js:68) and
``emitProgress(id, DOWNLOADING, percent)``
(/root/reference/lib/download.js:85,255,272, lib/upload.js:51), delivered
over RabbitMQ (lib/main.js:49-50).

Events are protobuf (``TelemetryStatusEvent`` / ``TelemetryProgressEvent``)
on the ``v1.telemetry.status`` / ``v1.telemetry.progress`` queues.  The
reference stores its telemetry client in ``global.telem`` (lib/main.js:52,
self-annotated ``// BAD``); here the client is passed explicitly to every
stage (SURVEY.md §7 step 6 lists that global as a bug to fix).
"""

from __future__ import annotations

from .. import schemas
from ..mq.base import MessageQueue

STATUS_QUEUE = "v1.telemetry.status"
PROGRESS_QUEUE = "v1.telemetry.progress"


class Telemetry:
    """Publishes job status/progress events.

    ``metrics`` is optional, mirroring how the reference passes its prom
    handle into Telemetry for internal counters (lib/main.js:49).
    """

    def __init__(self, mq: MessageQueue, metrics=None):
        self._mq = mq
        self._metrics = metrics

    async def connect(self) -> None:
        """(reference lib/main.js:50)"""
        await self._mq.connect()

    async def close(self) -> None:
        """Tear down the telemetry connection (graceful shutdown)."""
        await self._mq.close()

    async def emit_status(self, media_id: str, status: int) -> None:
        event = schemas.TelemetryStatusEvent(media_id=media_id, status=status)
        await self._mq.publish(STATUS_QUEUE, schemas.encode(event))
        if self._metrics is not None:
            self._metrics.messages_published.labels(queue=STATUS_QUEUE).inc()

    async def emit_progress(self, media_id: str, status: int, percent: int) -> None:
        event = schemas.TelemetryProgressEvent(
            media_id=media_id, status=status, percent=int(percent)
        )
        await self._mq.publish(PROGRESS_QUEUE, schemas.encode(event))
        if self._metrics is not None:
            self._metrics.messages_published.labels(queue=PROGRESS_QUEUE).inc()


class NullTelemetry(Telemetry):
    """Telemetry sink that drops everything (hermetic stage tests)."""

    def __init__(self) -> None:  # noqa: D401
        super().__init__(mq=None)  # type: ignore[arg-type]

    async def close(self) -> None:
        pass

    async def connect(self) -> None:
        pass

    async def emit_status(self, media_id: str, status: int) -> None:
        pass

    async def emit_progress(self, media_id: str, status: int, percent: int) -> None:
        pass
