"""Platform layer: cross-cutting services.

Capability-equivalent to the reference's external ``triton-core`` npm package
(config, logging, tracing, metrics, telemetry, service discovery — SURVEY.md
§1 "Platform layer"), rebuilt in-tree so the framework is self-contained.
"""
