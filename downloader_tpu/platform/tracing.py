"""Distributed tracing.

The reference plumbs a Jaeger tracer (/root/reference/index.js:10,15) and
imports the opentracing symbols (/root/reference/lib/main.js:20) but never
creates a span — SURVEY.md §5 flags tracing as "plumbed-but-unused" and the
build plan (§7 step 7) says to wire it for real.  This module is a small
OpenTracing-style tracer: nested spans with tags and timings, kept in an
in-memory buffer, optionally exported as JSON lines for offline analysis,
and — the production path — shipped to any OpenTelemetry collector over
OTLP/HTTP JSON (:class:`OtlpExporter`; Jaeger ingests OTLP natively since
1.35, so this supersedes the reference's jaeger-thrift wire).

Configuration: ``tracing.otlp_endpoint`` in the service YAML or
``$OTLP_ENDPOINT`` (e.g. ``http://localhost:4318``).  Spans are batched in
a background thread; a down collector never blocks or fails the pipeline.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import queue
import re
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Any, Dict, List, Optional

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "current_span", default=None
)


class RemoteSpanContext:
    """A parent carried over the wire (W3C traceparent) rather than the
    contextvar: just the two ids a child span needs.  The reference
    imports serialize/unserialize for exactly this cross-service carry
    (/root/reference/lib/main.js:20) and never uses them — here the
    context actually rides queue message headers."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


def format_traceparent(span: Optional["Span"] = None) -> Optional[str]:
    """W3C trace-context header for ``span`` (default: the current one);
    None when there is nothing to propagate."""
    span = span or _current_span.get()
    if span is None:
        return None
    return f"00-{span.trace_id}-{span.span_id}-01"


_HEX32 = re.compile(r"[0-9a-f]{32}")
_HEX16 = re.compile(r"[0-9a-f]{16}")
_HEX2 = re.compile(r"[0-9a-f]{2}")


def parse_traceparent(value: Any) -> Optional[RemoteSpanContext]:
    """Parse a W3C traceparent header; None for anything malformed
    (wire headers are untrusted — never raise)."""
    if isinstance(value, bytes):
        try:
            value = value.decode("ascii")
        except UnicodeDecodeError:
            return None
    if not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    # strict lowercase hex (int(x, 16) would admit signs/underscores/
    # uppercase, and a malformed id poisons the whole OTLP batch it is
    # exported with — review r5)
    if version != "00" or not _HEX32.fullmatch(trace_id) \
            or not _HEX16.fullmatch(span_id) or not _HEX2.fullmatch(flags):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # the spec's all-zero ids mean "no trace"
    return RemoteSpanContext(trace_id, span_id)


class Span:
    __slots__ = (
        "tracer", "name", "trace_id", "span_id", "parent_id",
        "start", "end", "tags", "error", "_mono",
    )

    def __init__(self, tracer: "Tracer", name: str,
                 parent: "Optional[Span | RemoteSpanContext]" = None,
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 **tags: Any):
        self.tracer = tracer
        self.name = name
        # W3C/OTLP sizes: 16-byte trace id, 8-byte span id (hex).
        # Explicit ids win (the orchestrator pre-allocates a job's ids so
        # its child logger and flight recorder carry them from receipt,
        # before the span opens); otherwise inherit/generate as before.
        self.trace_id = trace_id or (parent.trace_id if parent
                                     else uuid.uuid4().hex)
        self.span_id = span_id or uuid.uuid4().hex[:16]
        self.parent_id = parent.span_id if parent else None
        # wall-clock anchors the OTLP start/end nanos; the duration is
        # measured on the monotonic clock (an NTP step mid-span would
        # otherwise skew — or negate — every timing derived from it)
        self.start = time.time()
        self._mono = time.monotonic()
        self.end: Optional[float] = None
        self.tags: Dict[str, Any] = dict(tags)
        self.error: Optional[str] = None

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def finish(self, error: Optional[BaseException] = None) -> None:
        if self.end is not None:
            return
        # end = wall start + monotonic elapsed: OTLP nanos stay
        # wall-anchored while the span's duration is NTP-step-immune
        self.end = self.start + (time.monotonic() - self._mono)
        if error is not None:
            self.error = f"{type(error).__name__}: {error}"
        self.tracer._record(self)

    @property
    def duration(self) -> float:
        if self.end is None:
            return time.monotonic() - self._mono
        return self.end - self.start

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "startTime": self.start,
            "duration": self.duration,
            "tags": self.tags,
            "error": self.error,
        }


def _otlp_attr(key: str, value: Any) -> dict:
    """One OTLP KeyValue; non-primitive values stringify."""
    if isinstance(value, bool):
        body: dict = {"boolValue": value}
    elif isinstance(value, int):
        body = {"intValue": str(value)}
    elif isinstance(value, float):
        body = {"doubleValue": value}
    else:
        body = {"stringValue": str(value)}
    return {"key": key, "value": body}


def span_to_otlp(span: "Span") -> dict:
    """One finished span in OTLP/JSON (opentelemetry-proto mapping)."""
    out = {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(span.start * 1e9)),
        "endTimeUnixNano": str(int((span.end or span.start) * 1e9)),
        "attributes": [_otlp_attr(k, v) for k, v in span.tags.items()],
    }
    if span.parent_id:
        out["parentSpanId"] = span.parent_id
    if span.error:
        out["status"] = {"code": 2, "message": span.error}  # STATUS_CODE_ERROR
    return out


class OtlpExporter:
    """Ships finished spans to an OTLP/HTTP collector in the background.

    Batches up to ``max_batch`` spans every ``interval`` seconds and POSTs
    them to ``<endpoint>/v1/traces`` as OTLP JSON.  Export failures are
    counted and dropped — tracing must never block or fail the pipeline.
    """

    def __init__(self, endpoint: str, service: str,
                 interval: float = 2.0, max_batch: int = 512,
                 max_queue: int = 8192, timeout: float = 5.0):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service = service
        self.interval = interval
        self.max_batch = max_batch
        self.timeout = timeout
        self.dropped = 0
        self.exported = 0
        self.errors = 0
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue(max_queue)
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True
        )
        self._thread.start()

    def enqueue(self, span: "Span") -> None:
        try:
            self._queue.put_nowait(span_to_otlp(span))
        except queue.Full:
            self.dropped += 1

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while not self._closed.is_set() or not self._queue.empty():
            batch: List[dict] = []
            deadline = time.monotonic() + self.interval
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if self._closed.is_set():
                    remaining = 0.0
                try:
                    item = self._queue.get(timeout=max(remaining, 0.01))
                except queue.Empty:
                    break
                if item is None:
                    break
                batch.append(item)
            if batch:
                self._post(batch)

    def _post(self, batch: List[dict]) -> None:
        payload = json.dumps({
            "resourceSpans": [{
                "resource": {
                    "attributes": [_otlp_attr("service.name", self.service)],
                },
                "scopeSpans": [{
                    "scope": {"name": "downloader_tpu"},
                    "spans": batch,
                }],
            }]
        }).encode("utf-8")
        request = urllib.request.Request(
            self.url, data=payload,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(request, timeout=self.timeout):
                self.exported += len(batch)
        except (urllib.error.URLError, OSError, ValueError):
            self.errors += 1
            self.dropped += len(batch)

    def close(self, timeout: float = 10.0) -> None:
        """Flush remaining spans and stop the exporter thread."""
        self._closed.set()
        self._queue.put(None)  # wake the worker
        self._thread.join(timeout)


class Tracer:
    """Span factory + buffer.  ``export_path`` (or ``$TRACE_EXPORT``) appends
    each finished span as one JSON line; ``exporter`` (an
    :class:`OtlpExporter`) ships spans to a collector."""

    def __init__(self, service: str, export_path: Optional[str] = None,
                 max_buffer: int = 10_000,
                 exporter: Optional[OtlpExporter] = None):
        self.service = service
        self.export_path = export_path or os.environ.get("TRACE_EXPORT")
        self.exporter = exporter
        # optional structured logger (init_tracer attaches it): used to
        # report exporter health once at the shutdown flush
        self.logger = None
        self.finished: List[Span] = []
        self._max_buffer = max_buffer
        self._lock = threading.Lock()

    def buffer_depth(self) -> int:
        """Finished spans currently held in the in-process buffer."""
        with self._lock:
            return len(self.finished)

    @contextlib.contextmanager
    def span(self, name: str, remote_parent: Optional[RemoteSpanContext] = None,
             trace_id: Optional[str] = None, span_id: Optional[str] = None,
             **tags: Any):
        parent = remote_parent or _current_span.get()
        span = Span(self, name, parent, trace_id=trace_id, span_id=span_id,
                    **tags)
        token = _current_span.set(span)
        try:
            yield span
        except BaseException as exc:
            span.finish(error=exc)
            raise
        finally:
            _current_span.reset(token)
            span.finish()

    def _record(self, span: Span) -> None:
        with self._lock:
            self.finished.append(span)
            if len(self.finished) > self._max_buffer:
                del self.finished[: len(self.finished) - self._max_buffer]
        if self.exporter is not None:
            self.exporter.enqueue(span)
        if self.export_path:
            line = json.dumps({"service": self.service, **span.to_dict()})
            with self._lock, open(self.export_path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")

    def close(self) -> None:
        """Flush the OTLP exporter, if any, and report its health.

        Export failures are deliberately silent in-flight (a down
        collector must never fail the pipeline), so the shutdown flush
        is where their tally surfaces: one log line with
        exported/dropped/errors — the operator's signal that traces
        were (or were not) actually leaving the process.
        """
        if self.exporter is not None:
            self.exporter.close()
            if self.logger is not None:
                self.logger.info(
                    "otlp exporter flushed",
                    exported=self.exporter.exported,
                    dropped=self.exporter.dropped,
                    errors=self.exporter.errors,
                    queued=self.exporter._queue.qsize(),
                )

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            return [s for s in self.finished if name is None or s.name == name]


class NullTracer(Tracer):
    """Tracer that records nothing (for perf-sensitive or minimal runs)."""

    def __init__(self) -> None:
        super().__init__("null")

    def _record(self, span: Span) -> None:
        pass


def init_tracer(service: str, logger=None, config=None) -> Tracer:
    """(reference ``Tracer('downloader', logger)``, index.js:15)

    Resolution for the OTLP endpoint: ``$OTLP_ENDPOINT`` env, then the
    ``tracing.otlp_endpoint`` config key.  Absent both, spans stay in the
    in-process buffer (and the optional JSONL file) only.
    """
    from .config import cfg_get

    endpoint = os.environ.get("OTLP_ENDPOINT") or cfg_get(
        config, "tracing.otlp_endpoint"
    )
    exporter = OtlpExporter(endpoint, service) if endpoint else None
    tracer = Tracer(service, exporter=exporter)
    tracer.logger = logger
    if logger is not None:
        logger.debug(
            "tracer initialized", service=service,
            otlp=endpoint or "disabled",
        )
    return tracer
