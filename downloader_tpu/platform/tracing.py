"""Distributed tracing.

The reference plumbs a Jaeger tracer (/root/reference/index.js:10,15) and
imports the opentracing symbols (/root/reference/lib/main.js:20) but never
creates a span — SURVEY.md §5 flags tracing as "plumbed-but-unused" and the
build plan (§7 step 7) says to wire it for real.  This module is a small
OpenTracing-style tracer: nested spans with tags and timings, kept in an
in-memory buffer and optionally exported as JSON lines for offline analysis
(no Jaeger agent required).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "current_span", default=None
)


class Span:
    __slots__ = (
        "tracer", "name", "trace_id", "span_id", "parent_id",
        "start", "end", "tags", "error",
    )

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional["Span"] = None, **tags: Any):
        self.tracer = tracer
        self.name = name
        self.trace_id = parent.trace_id if parent else uuid.uuid4().hex[:16]
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent.span_id if parent else None
        self.start = time.time()
        self.end: Optional[float] = None
        self.tags: Dict[str, Any] = dict(tags)
        self.error: Optional[str] = None

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def finish(self, error: Optional[BaseException] = None) -> None:
        if self.end is not None:
            return
        self.end = time.time()
        if error is not None:
            self.error = f"{type(error).__name__}: {error}"
        self.tracer._record(self)

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "startTime": self.start,
            "duration": self.duration,
            "tags": self.tags,
            "error": self.error,
        }


class Tracer:
    """Span factory + buffer.  ``export_path`` (or ``$TRACE_EXPORT``) appends
    each finished span as one JSON line."""

    def __init__(self, service: str, export_path: Optional[str] = None,
                 max_buffer: int = 10_000):
        self.service = service
        self.export_path = export_path or os.environ.get("TRACE_EXPORT")
        self.finished: List[Span] = []
        self._max_buffer = max_buffer
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, **tags: Any):
        parent = _current_span.get()
        span = Span(self, name, parent, **tags)
        token = _current_span.set(span)
        try:
            yield span
        except BaseException as exc:
            span.finish(error=exc)
            raise
        finally:
            _current_span.reset(token)
            span.finish()

    def _record(self, span: Span) -> None:
        with self._lock:
            self.finished.append(span)
            if len(self.finished) > self._max_buffer:
                del self.finished[: len(self.finished) - self._max_buffer]
        if self.export_path:
            line = json.dumps({"service": self.service, **span.to_dict()})
            with self._lock, open(self.export_path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            return [s for s in self.finished if name is None or s.name == name]


class NullTracer(Tracer):
    """Tracer that records nothing (for perf-sensitive or minimal runs)."""

    def __init__(self) -> None:
        super().__init__("null")

    def _record(self, span: Span) -> None:
        pass


def init_tracer(service: str, logger=None) -> Tracer:
    """(reference ``Tracer('downloader', logger)``, index.js:15)"""
    tracer = Tracer(service)
    if logger is not None:
        logger.debug("tracer initialized", service=service)
    return tracer
