"""Dependency fault tolerance: error taxonomy, retries, circuit breakers.

The reference service's only failure policy is "nack and hope"
(/root/reference/lib/main.js:148-150): every stage error triggers an
*instant* broker redelivery, and the poison guard counts attempts with
no notion of *why* they failed — a 30-second S3 or tracker blip can burn
the whole poison budget in milliseconds and permanently drop healthy
jobs.  At production scale transient dependency failures are the steady
state, not the exception; this module gives every dependency seam a
shared vocabulary and machinery to ride them out:

- **Taxonomy** — :func:`classify` buckets any exception into
  :data:`TRANSIENT` (dependency blip: timeouts, resets, 5xx, disk
  pressure — retry with backoff), :data:`PERMANENT` (will never succeed:
  4xx, bad protocol, missing file — fail fast, never burn retries), or
  :data:`POISON` (the *content* is bad: no media files — drop, don't
  redeliver).  Exceptions may pre-classify themselves by carrying a
  ``fault_class`` attribute; the injected faults (platform/faults.py)
  and the S3 driver's status-code errors do.
- **Retry** — :class:`Retrier` runs a dependency call under a
  per-dependency :class:`RetryPolicy` (config ``retry.<dependency>``,
  falling back to ``retry.default``): bounded attempts, exponential
  backoff with decorrelated jitter (AWS architecture-blog style:
  ``sleep = min(cap, uniform(base, prev * 3))``), cancel token honored
  *during* the backoff sleeps, every retry visible as a flight-recorder
  event and a ``dependency_retries_total{seam}`` metric.
- **Circuit breakers** — :class:`CircuitBreaker` per dependency
  (closed → open after ``threshold`` consecutive transient failures →
  half-open probe after ``reset`` seconds → closed on probe success),
  aggregated in a :class:`BreakerBoard` the orchestrator consults at
  admission: when the staging store or convert publish breaker is open,
  intake parks jobs instead of failing them, ``/readyz`` answers 503
  with the breaker states, and the half-open probe restores service
  without operator action.  State rides ``breaker_state{dependency}``
  (0=closed, 1=open, 2=half-open) and
  ``breaker_transitions_total{dependency,to_state}``.
- **Slow-call policy** ("slow is the new down") — a browned-out
  dependency that answers every call successfully but slowly never
  trips a failure-count breaker, and by the time timeouts fire the
  whole pipeline is wedged behind it.  With
  ``breakers.<dep>.slow_threshold_ms`` set, every *answered* attempt
  (success or transient failure) is classified fast/slow into a
  bounded ring of the last ``slow_window`` calls; once at least
  ``slow_min_calls`` are in the ring and the slow fraction reaches
  ``slow_ratio``, the breaker opens with ``open_reason = "slow"`` —
  the same park-not-fail shedding as a failure-opened breaker, before
  the timeout cascade.  A half-open probe that answers slowly re-opens
  (the dependency is back, but not usable).  Slow calls count on
  ``dependency_slow_total{dependency}``; every open is attributed on
  ``breaker_opened_total{dependency,reason=failure|slow}`` and the
  reason rides ``/readyz``.

Seams are dotted names (``store.put``, ``http.fetch``,
``tracker.announce``); the dependency — the retry-policy and breaker
key — is the first component (``store``, ``publish``, ``http``,
``tracker``, ``disk``).
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

from .config import cfg_get

# -- the taxonomy -------------------------------------------------------
TRANSIENT = "transient"   # dependency blip: retry with backoff
PERMANENT = "permanent"   # will never succeed: fail fast, no retries
POISON = "poison"         # the content is bad: drop, never redeliver

FAULT_CLASSES = (TRANSIENT, PERMANENT, POISON)

# exception codes the retry machinery must always pass through untouched:
# cooperative cancellation settles the job, and a stall has its own
# orchestrator policy (ack + drop, reference lib/main.js:144-146)
_PASSTHROUGH_CODES = frozenset({"ERRCANCELLED", "ERRDLSTALL"})

# type names classified without importing their modules (stages/store
# import this package; importing them back would cycle)
_POISON_TYPE_NAMES = frozenset({"NoMediaFilesError"})
_PERMANENT_TYPE_NAMES = frozenset({"ObjectNotFound"})

# HTTP statuses that are retryable despite being client errors
_TRANSIENT_HTTP_STATUSES = frozenset({408, 429})


def _passthrough_code(err: BaseException) -> bool:
    """True for the cancel/stall marker codes.  Reads ``code`` off the
    CLASS (our marker exceptions define it there) — instance getattr
    would trip aiohttp's deprecated ``ClientResponseError.code``
    property."""
    code = getattr(type(err), "code", None)
    return isinstance(code, str) and code in _PASSTHROUGH_CODES


def seam_dependency(seam: str) -> str:
    """``store.put`` -> ``store``: the retry-policy / breaker key."""
    return seam.split(".", 1)[0]


def dependency_family(dependency: str) -> Optional[str]:
    """``origin:mirror-a:8080`` -> ``origin``: the config family a
    *labeled* dependency inherits knobs from.  Per-origin breakers and
    retry budgets key on ``origin:<label>`` so each origin trips
    independently, but nobody configures per-host thresholds — the
    ``retry.origin`` / ``breakers.origin`` sections cover the family.
    None for plain (unlabeled) dependencies."""
    if ":" not in dependency:
        return None
    return dependency.split(":", 1)[0]


def classify(err: BaseException) -> str:
    """Bucket ``err`` into TRANSIENT / PERMANENT / POISON.

    An explicit ``fault_class`` attribute wins (injected faults, the S3
    driver's status-coded errors, and anything a seam pre-classified).
    Unknown errors default to TRANSIENT: at-least-once delivery already
    assumes redelivery is safe, and misclassifying a transient blip as
    permanent drops real work while the reverse merely wastes a bounded
    retry budget.
    """
    explicit = getattr(err, "fault_class", None)
    if explicit in FAULT_CLASSES:
        return explicit
    name = type(err).__name__
    if name in _POISON_TYPE_NAMES:
        return POISON
    if name in _PERMANENT_TYPE_NAMES:
        return PERMANENT
    if _passthrough_code(err):
        # never reached via the Retrier (it passes these through before
        # classifying); callers classifying directly must not retry them
        return PERMANENT
    # aiohttp response errors carry the origin's verdict
    status = getattr(err, "status", None)
    if isinstance(status, int) and status >= 400:
        return (TRANSIENT if status >= 500
                or status in _TRANSIENT_HTTP_STATUSES else PERMANENT)
    if isinstance(err, (PermissionError, FileNotFoundError,
                        NotADirectoryError, IsADirectoryError)):
        return PERMANENT
    if isinstance(err, (ValueError, TypeError, KeyError, LookupError,
                        NotImplementedError, AttributeError)):
        # contract/config errors ("Protocol not supported.", bad stage
        # payloads): retrying re-runs the same deterministic code path
        return PERMANENT
    if isinstance(err, (ConnectionError, TimeoutError, OSError,
                        asyncio.TimeoutError)):
        return TRANSIENT
    return TRANSIENT


def tag_fault(err: BaseException, fault_class: Optional[str] = None,
              seam: Optional[str] = None) -> BaseException:
    """Best-effort annotation of ``err`` with its classification/seam
    (slotted exceptions simply stay untagged)."""
    try:
        if fault_class is not None:
            err.fault_class = fault_class
        if seam is not None:
            err.fault_seam = seam
    except (AttributeError, TypeError):
        pass
    return err


class BreakerOpen(RuntimeError):
    """A dependency's circuit breaker rejected the call without trying.

    TRANSIENT by class (the dependency is expected back), but it must
    NOT advance the poison counter — the job never got to fail; the
    orchestrator parks and redelivers it without charging the budget.
    """

    fault_class = TRANSIENT
    counts_toward_poison = False

    def __init__(self, dependency: str, retry_after: float):
        self.dependency = dependency
        self.fault_seam = dependency
        self.retry_after = retry_after
        super().__init__(
            f"{dependency} circuit breaker is open "
            f"(probe in ~{retry_after:.1f}s)"
        )


# -- retry policy -------------------------------------------------------

@dataclass(frozen=True)
class RetryPolicy:
    """Per-dependency in-process retry budget.

    ``attempts`` counts total tries (1 = no retries).  ``base``/``cap``
    bound the decorrelated-jitter backoff.  Defaults are deliberately
    small — a media pipeline's in-process retries ride *inside* the
    broker's at-least-once redelivery, which handles the long outages
    (see ``retry.redelivery``); production deployments raise them per
    dependency (docs/OPERATIONS.md "Failure model").
    """

    attempts: int = 3
    base: float = 0.1
    cap: float = 2.0

    @classmethod
    def from_config(cls, config, dependency: str) -> "RetryPolicy":
        family = dependency_family(dependency)

        def knob(name: str, fallback):
            fallback = cfg_get(config, f"retry.default.{name}", fallback)
            if family is not None:
                fallback = cfg_get(config, f"retry.{family}.{name}",
                                   fallback)
            return cfg_get(config, f"retry.{dependency}.{name}", fallback)

        attempts = int(knob("attempts", cls.attempts))
        base = float(knob("base", cls.base))
        cap = float(knob("cap", cls.cap))
        if attempts < 1:
            raise ValueError(
                f"retry.{dependency}.attempts must be >= 1, got {attempts}"
            )
        if base < 0 or cap < base:
            raise ValueError(
                f"retry.{dependency}: need 0 <= base <= cap, "
                f"got base={base} cap={cap}"
            )
        return cls(attempts=attempts, base=base, cap=cap)


# -- circuit breaker ----------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

DEFAULT_BREAKER_THRESHOLD = 5
DEFAULT_BREAKER_RESET = 30.0
# slow-call policy defaults (slow_threshold 0 = policy off)
DEFAULT_SLOW_RATIO = 0.5
DEFAULT_SLOW_WINDOW = 16
DEFAULT_SLOW_MIN_CALLS = 8

# breaker open reasons (``breaker_opened_total{reason}`` / readyz)
OPEN_FAILURE = "failure"
OPEN_SLOW = "slow"
OPEN_DISK = "disk"


class CircuitBreaker:
    """Closed/open/half-open breaker for one dependency.

    Counts *consecutive* transient failures; at ``threshold`` it opens
    and :meth:`allow` rejects calls until ``reset`` seconds pass, then
    admits exactly one half-open probe.  Probe success closes the
    breaker; probe failure re-opens it (fresh reset window).  Only
    transient failures should be recorded — a 404 is not an outage.

    With ``slow_threshold`` > 0 the breaker also watches latency: each
    answered attempt lands fast/slow in a bounded ring, and a sustained
    slow fraction (>= ``slow_ratio`` over >= ``slow_min_calls`` of the
    last ``slow_window`` answers) opens the breaker with
    ``open_reason = "slow"`` even though every call succeeded — the
    brownout shape failure counting is blind to.
    """

    __slots__ = ("dependency", "threshold", "reset", "metrics", "logger",
                 "state", "failures", "_opened_mono", "_probe_inflight",
                 "transitions", "slow_threshold", "slow_ratio",
                 "slow_window", "slow_min_calls", "_slow_ring",
                 "open_reason")

    def __init__(self, dependency: str,
                 threshold: int = DEFAULT_BREAKER_THRESHOLD,
                 reset: float = DEFAULT_BREAKER_RESET,
                 slow_threshold: float = 0.0,
                 slow_ratio: float = DEFAULT_SLOW_RATIO,
                 slow_window: int = DEFAULT_SLOW_WINDOW,
                 slow_min_calls: int = DEFAULT_SLOW_MIN_CALLS,
                 metrics=None, logger=None):
        if threshold < 1:
            raise ValueError(
                f"breakers.{dependency}.threshold must be >= 1, "
                f"got {threshold}"
            )
        if reset <= 0:
            raise ValueError(
                f"breakers.{dependency}.reset must be > 0, got {reset}"
            )
        if slow_threshold < 0:
            raise ValueError(
                f"breakers.{dependency}.slow_threshold_ms must be >= 0"
            )
        if not 0.0 < slow_ratio <= 1.0:
            raise ValueError(
                f"breakers.{dependency}.slow_ratio must be in (0, 1], "
                f"got {slow_ratio}"
            )
        if slow_window < 1 or slow_min_calls < 1:
            raise ValueError(
                f"breakers.{dependency}.slow_window/slow_min_calls "
                "must be >= 1"
            )
        self.dependency = dependency
        self.threshold = threshold
        self.reset = reset
        self.slow_threshold = float(slow_threshold)
        self.slow_ratio = float(slow_ratio)
        self.slow_window = int(slow_window)
        self.slow_min_calls = min(int(slow_min_calls), int(slow_window))
        self.metrics = metrics
        self.logger = logger
        self.state = CLOSED
        self.failures = 0          # consecutive transient failures
        self._opened_mono = 0.0
        self._probe_inflight = False
        self.transitions = 0
        # fast/slow verdicts of the last slow_window ANSWERED attempts
        self._slow_ring: "deque[bool]" = deque(maxlen=self.slow_window)
        # why the breaker last opened ("failure" | "slow"); None while
        # it has never opened or has closed again
        self.open_reason: Optional[str] = None
        if metrics is not None:
            metrics.breaker_state.labels(dependency=dependency).set(0)

    def _move(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        self.transitions += 1
        if self.metrics is not None:
            self.metrics.breaker_state.labels(
                dependency=self.dependency
            ).set(_STATE_GAUGE[state])
            self.metrics.breaker_transitions.labels(
                dependency=self.dependency, to_state=state
            ).inc()
        if self.logger is not None:
            self.logger.warn("circuit breaker transition",
                             dependency=self.dependency, state=state,
                             failures=self.failures,
                             reason=self.open_reason)

    def _open(self, reason: str) -> None:
        """Open with attribution: the triage path for a slow-opened
        breaker (shed + wait out the brownout) differs from a
        failure-opened one (check the dependency is up at all)."""
        self.open_reason = reason
        self._opened_mono = time.monotonic()
        if self.metrics is not None:
            self.metrics.breaker_opened.labels(
                dependency=self.dependency, reason=reason
            ).inc()
        self._move(OPEN)

    def force_open(self, reason: str) -> None:
        """Open now on an out-of-band verdict the call counters never
        see — the disk-headroom gate (``reason="disk"``): the volume
        filling up fails no store call until the ENOSPC cascade is
        already underway.  Re-forcing while open refreshes the reset
        window; recovery is the normal half-open probe (the first
        successful call after ``reset`` closes it)."""
        self._open(reason)

    def note_latency(self, elapsed: Optional[float]) -> bool:
        """Land one answered attempt's latency in the slow ring;
        returns whether it was slow.  No-op when the policy is off."""
        if self.slow_threshold <= 0 or elapsed is None:
            return False
        slow = elapsed >= self.slow_threshold
        self._slow_ring.append(slow)
        if slow and self.metrics is not None:
            self.metrics.dependency_slow.labels(
                dependency=self.dependency
            ).inc()
        return slow

    def _slow_trip_due(self) -> bool:
        ring = self._slow_ring
        return (len(ring) >= self.slow_min_calls
                and sum(ring) / len(ring) >= self.slow_ratio)

    def retry_after(self) -> float:
        """Seconds until the next half-open probe window (0 = now)."""
        if self.state != OPEN:
            return 0.0
        return max(0.0, self._opened_mono + self.reset - time.monotonic())

    @property
    def blocking(self) -> bool:
        """True while calls would be rejected (open, window not elapsed)."""
        return self.state == OPEN and self.retry_after() > 0

    def allow(self) -> bool:
        """May a call proceed right now?  Handles the open -> half-open
        transition; in half-open only one in-flight probe is admitted."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self.retry_after() > 0:
                return False
            self._move(HALF_OPEN)
            self._probe_inflight = False
        # half-open: exactly one probe at a time
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        return True

    def release_probe(self) -> None:
        """A half-open probe ended without a dependency verdict (the job
        was cancelled, the transfer stalled): free the slot so the next
        caller can probe — otherwise the breaker wedges half-open."""
        self._probe_inflight = False

    def record_success(self, elapsed: Optional[float] = None) -> None:
        slow = self.note_latency(elapsed)
        self._probe_inflight = False
        if slow and self.state != CLOSED:
            # a slow answer while not closed: the half-open probe came
            # back without the dependency being usable (re-open, fresh
            # reset window), or an in-flight slow success landed after
            # the open — either way it must not close the breaker
            self._slow_ring.clear()
            if self.state == HALF_OPEN:
                self._open(OPEN_SLOW)
            return
        self.failures = 0
        if self.state != CLOSED:
            self.open_reason = None
            self._slow_ring.clear()
            self._move(CLOSED)
            return
        if self._slow_trip_due():
            # every call "succeeds" and the failure counter never moves,
            # yet the dependency is browned out: open on the slow ratio
            # (ring cleared so the post-reset probe is judged fresh)
            self._slow_ring.clear()
            self._open(OPEN_SLOW)

    def record_failure(self, elapsed: Optional[float] = None) -> None:
        self.note_latency(elapsed)
        self._probe_inflight = False
        if self.state == HALF_OPEN:
            # failed probe: back to open, fresh reset window — and
            # RE-attributed: a probe that ERRORED means the dependency
            # is down now, even if the original open was slow-call (a
            # brownout hardening into an outage must steer operators to
            # the failure runbook, not "wait it out")
            self._open(OPEN_FAILURE)
            return
        self.failures += 1
        if self.state == CLOSED:
            if self.failures >= self.threshold:
                self._open(OPEN_FAILURE)
            elif self._slow_trip_due():
                # slow transient failures count toward the brownout
                # verdict too (a timing-out store answers *eventually*)
                self._slow_ring.clear()
                self._open(OPEN_SLOW)


# dependencies that are per-JOB concerns, not shared infrastructure: a
# breaker would let ONE job's dead origin block every other job's
# downloads, so no breaker is kept for them unless config opts in
# (``breakers.<dep>.enabled: true``) — retries still apply
_PER_JOB_DEPENDENCIES = frozenset({"http"})


class BreakerBoard:
    """Per-dependency breakers, built lazily from config.

    Config: ``breakers.<dependency>.{threshold,reset,enabled}`` over
    ``breakers.default``.  ``breakers.enabled: false`` disables the
    whole board (every call allowed, nothing recorded).  The ``http``
    dependency defaults to breaker-less: an origin is one job's
    problem, not the fleet's (see :data:`_PER_JOB_DEPENDENCIES`).
    """

    def __init__(self, config=None, metrics=None, logger=None):
        self.config = config
        self.metrics = metrics
        self.logger = logger
        self.enabled = bool(cfg_get(config, "breakers.enabled", True))
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, dependency: str) -> Optional[CircuitBreaker]:
        family = dependency_family(dependency)
        enabled_fallback = dependency not in _PER_JOB_DEPENDENCIES
        if family is not None:
            enabled_fallback = bool(cfg_get(
                self.config, f"breakers.{family}.enabled",
                enabled_fallback,
            ))
        if not bool(cfg_get(
            self.config, f"breakers.{dependency}.enabled",
            enabled_fallback,
        )):
            return None
        breaker = self._breakers.get(dependency)
        if breaker is None:
            def knob(name: str, fallback):
                fallback = cfg_get(self.config,
                                   f"breakers.default.{name}", fallback)
                if family is not None:
                    fallback = cfg_get(
                        self.config, f"breakers.{family}.{name}", fallback
                    )
                return cfg_get(
                    self.config, f"breakers.{dependency}.{name}", fallback
                )

            breaker = CircuitBreaker(
                dependency,
                threshold=int(knob("threshold",
                                   DEFAULT_BREAKER_THRESHOLD)),
                reset=float(knob("reset", DEFAULT_BREAKER_RESET)),
                # slow-call policy (ms in config, seconds inside): 0
                # keeps the exact failure-count-only behavior
                slow_threshold=float(
                    knob("slow_threshold_ms", 0.0)) / 1000.0,
                slow_ratio=float(knob("slow_ratio", DEFAULT_SLOW_RATIO)),
                slow_window=int(knob("slow_window", DEFAULT_SLOW_WINDOW)),
                slow_min_calls=int(knob("slow_min_calls",
                                        DEFAULT_SLOW_MIN_CALLS)),
                metrics=self.metrics, logger=self.logger,
            )
            self._breakers[dependency] = breaker
        return breaker

    def states(self) -> Dict[str, str]:
        """dependency -> state, for ``/readyz`` and the admin API."""
        return {dep: b.state for dep, b in sorted(self._breakers.items())}

    def open_reasons(self) -> Dict[str, str]:
        """dependency -> why its breaker last opened (``failure`` |
        ``slow``), for every breaker not currently closed — the triage
        attribution ``/readyz`` carries beside the states."""
        return {dep: b.open_reason
                for dep, b in sorted(self._breakers.items())
                if b.state != CLOSED and b.open_reason}

    def blocking_dependencies(
        self, dependencies: Optional[Iterable[str]] = None
    ) -> List[str]:
        """Dependencies whose breaker would reject a call right now."""
        deps = (self._breakers.keys() if dependencies is None
                else dependencies)
        out = []
        for dep in deps:
            breaker = self._breakers.get(dep)
            if breaker is not None and breaker.blocking:
                out.append(dep)
        return out

    async def wait_ready(self, dependencies: Iterable[str],
                         poll: float = 0.05) -> None:
        """Park until none of ``dependencies`` is hard-open.

        Returns as soon as every breaker is closed or due a half-open
        probe — released jobs then race for the single probe slot; the
        losers get :class:`BreakerOpen` from their seams and are parked
        for redelivery without advancing the poison counter.
        """
        deps = list(dependencies)
        while True:
            blocked = self.blocking_dependencies(deps)
            if not blocked:
                return
            retry_after = min(
                self._breakers[dep].retry_after() for dep in blocked
            )
            await asyncio.sleep(min(max(retry_after, poll), 1.0))


# -- the retry executor -------------------------------------------------

class Retrier:
    """Runs dependency calls under per-dependency retry + breaker policy.

    One instance per service (the orchestrator shares its own through
    ``ctx.resources``); standalone stage use builds one from config via
    :meth:`shared`.
    """

    def __init__(self, config=None, breakers: Optional[BreakerBoard] = None,
                 metrics=None, logger=None,
                 rng: Optional[random.Random] = None):
        self.config = config
        self.breakers = breakers
        self.metrics = metrics
        self.logger = logger
        self._rng = rng or random.Random()
        self._policies: Dict[str, RetryPolicy] = {}

    @classmethod
    def shared(cls, resources: dict, config, metrics=None,
               logger=None) -> "Retrier":
        """Per-service retrier memoized in the cross-job ``resources``
        dict (same idiom as the rate-limit buckets): the orchestrator
        pre-installs its instance so the stages share its breaker board;
        standalone stage use lazily builds one from config."""
        retrier = resources.get("retrier")
        if retrier is None:
            retrier = cls(
                config=config,
                breakers=BreakerBoard(config, metrics=metrics,
                                      logger=logger),
                metrics=metrics, logger=logger,
            )
            resources["retrier"] = retrier
        return retrier

    def policy(self, dependency: str) -> RetryPolicy:
        policy = self._policies.get(dependency)
        if policy is None:
            policy = RetryPolicy.from_config(self.config, dependency)
            self._policies[dependency] = policy
        return policy

    def _observe(self, dependency: str, seam: str, outcome: str,
                 elapsed: float) -> None:
        """One RED sample per dependency *attempt* — the latency
        distribution behind every seam (``dependency_request_seconds``),
        labeled with how the dependency answered.  Breaker rejections
        are NOT observed: no request was made, and a wall of sub-ms
        "failures" would bury the real latency signal."""
        if self.metrics is not None:
            self.metrics.dependency_request_seconds.labels(
                dependency=dependency, op=seam, outcome=outcome
            ).observe(elapsed)

    async def run(self, seam: str, factory: Callable[[], Any], *,
                  cancel=None, record=None, logger=None) -> Any:
        """Await ``factory()`` with bounded transient retries.

        ``factory`` is a zero-arg callable returning a fresh awaitable
        per attempt.  TRANSIENT failures back off (decorrelated jitter,
        cancel-aware sleeps) and feed the dependency's breaker;
        PERMANENT/POISON failures, cancellation, and stalls re-raise
        immediately.  The final error is tagged with ``fault_class`` and
        ``fault_seam`` so the orchestrator's redelivery policy can key
        on them.
        """
        dependency = seam_dependency(seam)
        policy = self.policy(dependency)
        breaker = (self.breakers.get(dependency)
                   if self.breakers is not None and self.breakers.enabled
                   else None)
        log = logger or self.logger
        prev_delay = policy.base
        for attempt in range(1, policy.attempts + 1):
            if breaker is not None and not breaker.allow():
                raise BreakerOpen(dependency, breaker.retry_after())
            attempt_started = time.monotonic()
            try:
                result = await factory()
            except Exception as err:
                elapsed = time.monotonic() - attempt_started
                if _passthrough_code(err):
                    # cancellation / stall: never retried, never tagged —
                    # and no breaker verdict (the dependency didn't get
                    # to answer), but a held half-open probe slot must
                    # be freed or the breaker wedges
                    if breaker is not None:
                        breaker.release_probe()
                    self._observe(dependency, seam, "cancelled", elapsed)
                    raise
                fault = classify(err)
                self._observe(dependency, seam, fault, elapsed)
                if fault != TRANSIENT:
                    # the dependency ANSWERED (404, 403, bad request) —
                    # not an outage, so no failure is recorded; but not
                    # a success either: a store failing only its WRITE
                    # path must not have interleaved healthy 404 probes
                    # (e.g. the idempotency marker check) resetting the
                    # consecutive-failure count.  Free any held probe
                    # slot and let a real success close the breaker.
                    if breaker is not None:
                        breaker.release_probe()
                    raise tag_fault(err, fault, seam)
                if breaker is not None:
                    breaker.record_failure(elapsed)
                if attempt >= policy.attempts:
                    raise tag_fault(err, TRANSIENT, seam)
                delay = min(policy.cap,
                            self._rng.uniform(policy.base,
                                              max(prev_delay * 3,
                                                  policy.base)))
                prev_delay = delay
                if self.metrics is not None:
                    self.metrics.dependency_retries.labels(seam=seam).inc()
                if record is not None:
                    record.event("retry", seam=seam, attempt=attempt,
                                 of=policy.attempts,
                                 delay_s=round(delay, 3),
                                 type=type(err).__name__,
                                 error=str(err)[:160])
                    record.retry = {
                        "seam": seam, "attempt": attempt,
                        "of": policy.attempts,
                        "nextDelayS": round(delay, 3),
                    }
                if log is not None:
                    log.warn("transient dependency failure, retrying",
                             seam=seam, attempt=attempt,
                             of=policy.attempts, delay_s=round(delay, 3),
                             error=str(err)[:200])
                if cancel is not None:
                    await cancel.guard(asyncio.sleep(delay))
                else:
                    await asyncio.sleep(delay)
            else:
                elapsed = time.monotonic() - attempt_started
                self._observe(dependency, seam, "ok", elapsed)
                if breaker is not None:
                    # elapsed feeds the slow-call ring: a browned-out
                    # dependency's all-successes-but-slow train opens
                    # the breaker with reason "slow"
                    breaker.record_success(elapsed)
                if record is not None:
                    record.retry = None
                return result
        raise AssertionError("unreachable: retry loop exits via return/raise")
