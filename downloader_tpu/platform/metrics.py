"""Prometheus metrics.

Capability-equivalent to ``triton-core/prom``: a named registry
(``Prom.new('downloader')``) and an exposed ``/metrics`` endpoint
(``Prom.expose()``) at /root/reference/lib/main.js:43-44, plus the counters
the platform lib kept for AMQP/telemetry internals (the prom handle is
passed into both at lib/main.js:46,49).

Unlike the reference (whose in-tree code records nothing itself), the
pipeline here records job/stage outcomes, durations, and byte counts.
"""

from __future__ import annotations

import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)


class Metrics:
    """The downloader's metric set, bound to its own registry."""

    def __init__(self, service: str = "downloader",
                 registry: Optional[CollectorRegistry] = None):
        self.service = service
        self.registry = registry or CollectorRegistry()
        ns = service.replace("-", "_")
        self.jobs_consumed = Counter(
            f"{ns}_jobs_consumed_total",
            "Download jobs consumed from the queue",
            registry=self.registry,
        )
        self.jobs_completed = Counter(
            f"{ns}_jobs_completed_total",
            "Jobs fully staged and acked",
            registry=self.registry,
        )
        self.jobs_failed = Counter(
            f"{ns}_jobs_failed_total",
            "Jobs that errored (nacked or dropped)",
            ["reason"],
            registry=self.registry,
        )
        self.jobs_skipped = Counter(
            f"{ns}_jobs_skipped_total",
            "Jobs skipped via the staging-bucket idempotency marker",
            registry=self.registry,
        )
        self.jobs_active = Gauge(
            f"{ns}_jobs_active",
            "Jobs currently being processed",
            registry=self.registry,
        )
        self.jobs_cancelled = Counter(
            f"{ns}_jobs_cancelled_total",
            "Jobs cancelled via the control plane (acked, not requeued)",
            registry=self.registry,
        )
        self.jobs_by_state = Gauge(
            f"{ns}_jobs_by_state",
            "Jobs known to the control-plane registry, by lifecycle state "
            "(live + the bounded terminal ring)",
            ["state"],
            registry=self.registry,
        )
        self.job_state_transitions = Counter(
            f"{ns}_job_state_transitions_total",
            "Control-plane registry lifecycle transitions",
            ["from_state", "to_state"],
            registry=self.registry,
        )
        self.jobs_parked = Counter(
            f"{ns}_jobs_parked_total",
            "Jobs parked by the fault-tolerance layer instead of failed "
            "(breaker open at admission/mid-job, or a delayed-redelivery "
            "backoff before a nack)",
            ["reason"],
            registry=self.registry,
        )
        self.dependency_retries = Counter(
            f"{ns}_dependency_retries_total",
            "In-process retries of transient dependency failures, by seam "
            "(store.put, http.fetch, publish, ...)",
            ["seam"],
            registry=self.registry,
        )
        self.dependency_request_seconds = Histogram(
            f"{ns}_dependency_request_seconds",
            "Latency of every dependency call made through the Retrier "
            "seams (store put/stat/bucket, publish, http origin, tracker, "
            "coord ops), per attempt: dependency = breaker/policy key, "
            "op = the exact seam, outcome = ok|transient|permanent|"
            "poison|cancelled.  The R.E.D. signal breaker thresholds and "
            "retry budgets are tuned against",
            ["dependency", "op", "outcome"],
            registry=self.registry,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
        )
        self.breaker_state = Gauge(
            f"{ns}_breaker_state",
            "Per-dependency circuit-breaker state: 0=closed, 1=open, "
            "2=half-open",
            ["dependency"],
            registry=self.registry,
        )
        self.breaker_transitions = Counter(
            f"{ns}_breaker_transitions_total",
            "Circuit-breaker state transitions, by dependency and "
            "destination state",
            ["dependency", "to_state"],
            registry=self.registry,
        )
        self.breaker_opened = Counter(
            f"{ns}_breaker_opened_total",
            "Circuit-breaker opens with attribution: reason=failure "
            "(consecutive transient failures hit the threshold) vs "
            "reason=slow (the slow-call policy tripped on a sustained "
            "latency brownout — triage differently: the dependency is "
            "up, just unusable)",
            ["dependency", "reason"],
            registry=self.registry,
        )
        self.dependency_slow = Counter(
            f"{ns}_dependency_slow_total",
            "Answered dependency attempts that exceeded the breaker's "
            "slow_threshold_ms — the brownout signal behind a "
            "reason=slow breaker open",
            ["dependency"],
            registry=self.registry,
        )
        self.stage_seconds = Histogram(
            f"{ns}_stage_seconds",
            "Wall-clock seconds per pipeline stage",
            ["stage"],
            registry=self.registry,
        )
        # -- per-job hop ledger (platform/obs.py HopLedger) ------------
        self.hop_seconds_per_gb = Histogram(
            f"{ns}_hop_seconds_per_gb",
            "Seconds spent per gigabyte moved through each transfer hop "
            "(socket_read/splice/disk_write/hash/filter/upload/"
            "bucket_fetch/cache/h2d/compute/d2h), observed once per job "
            "at settle — the "
            "attribution the zero-copy staging work (ROADMAP item 3) "
            "ratchets against",
            ["hop"],
            registry=self.registry,
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0,
                     32.0, 64.0),
        )
        self.hop_bytes = Counter(
            f"{ns}_hop_bytes_total",
            "Bytes moved through each transfer hop (the weight behind "
            "hop_seconds_per_gb)",
            ["hop"],
            registry=self.registry,
        )
        self.hop_seconds = Counter(
            f"{ns}_hop_seconds_total",
            "Seconds spent in each transfer hop (with hop_bytes_total: "
            "fleet-wide where-does-a-gigabyte's-time-go attribution)",
            ["hop"],
            registry=self.registry,
        )
        self.staging_cpu_s_per_gb = Gauge(
            f"{ns}_staging_cpu_s_per_gb",
            "Copy-hop seconds per staged gigabyte for the most recently "
            "settled job (summed COPY_HOPS seconds over the widest "
            "hop's bytes) — the zero-copy staging ratchet's live "
            "headline number",
            registry=self.registry,
        )
        self.staging_hop_s_per_gb = Gauge(
            f"{ns}_staging_hop_s_per_gb",
            "Per-copy-hop seconds per gigabyte from the most recent "
            "settled job that exercised the hop — max() over the hop "
            "label is the current top offender the ratchet should "
            "attack next",
            ["hop"],
            registry=self.registry,
        )
        self.queue_wait_seconds = Histogram(
            f"{ns}_queue_wait_seconds",
            "Seconds from delivery receipt (RECEIVED) to admission "
            "(ADMITTED) — the disk-headroom gate's wait",
            registry=self.registry,
        )
        self.scheduler_wait_seconds = Histogram(
            f"{ns}_scheduler_wait_seconds",
            "Seconds from ADMITTED to acquiring a priority-scheduler "
            "run slot",
            registry=self.registry,
        )
        self.event_loop_lag = Gauge(
            f"{ns}_event_loop_lag_seconds",
            "Most recent event-loop scheduling lag sample (how late the "
            "loop woke the lag monitor's timer)",
            registry=self.registry,
        )
        self.event_loop_lag_hist = Histogram(
            f"{ns}_event_loop_lag",
            "Event-loop scheduling lag distribution, seconds",
            registry=self.registry,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0),
        )
        self.tracer_buffer_spans = Gauge(
            f"{ns}_tracer_buffer_spans",
            "Finished spans held in the tracer's in-process buffer",
            registry=self.registry,
        )
        self.otlp_spans_exported = Gauge(
            f"{ns}_otlp_spans_exported",
            "Spans successfully shipped to the OTLP collector "
            "(monotonic; gauge because it is read from the exporter)",
            registry=self.registry,
        )
        self.otlp_spans_dropped = Gauge(
            f"{ns}_otlp_spans_dropped",
            "Spans dropped by the OTLP exporter (full queue or failed "
            "batches) — nonzero means traces are silently missing",
            registry=self.registry,
        )
        self.otlp_export_errors = Gauge(
            f"{ns}_otlp_export_errors",
            "Failed OTLP batch POSTs (collector down/unreachable)",
            registry=self.registry,
        )
        self.otlp_queue_depth = Gauge(
            f"{ns}_otlp_queue_depth",
            "Spans waiting in the OTLP exporter's send queue",
            registry=self.registry,
        )
        self.bytes_downloaded = Counter(
            f"{ns}_bytes_downloaded_total",
            "Bytes fetched by the download stage",
            ["protocol"],
            registry=self.registry,
        )
        self.bytes_uploaded = Counter(
            f"{ns}_bytes_uploaded_total",
            "Bytes staged by the upload stage",
            registry=self.registry,
        )
        self.messages_published = Counter(
            f"{ns}_messages_published_total",
            "Queue messages published",
            ["queue"],
            registry=self.registry,
        )
        self.frames_upscaled = Counter(
            f"{ns}_frames_upscaled_total",
            "Video frames run through the upscale stage's TPU model",
            registry=self.registry,
        )
        self.transcode_bytes_in = Counter(
            f"{ns}_transcode_bytes_in_total",
            "Source bytes (container or raw y4m) consumed by the "
            "upscale stage's transcode",
            registry=self.registry,
        )
        self.transcode_bytes_out = Counter(
            f"{ns}_transcode_bytes_out_total",
            "Output bytes (container or raw y4m) written by the upscale "
            "stage's transcode — out/in quantifies the staging size "
            "effect of the encode back-end",
            registry=self.registry,
        )
        self.cache_hits = Counter(
            f"{ns}_cache_hits_total",
            "Download jobs served from the content-addressed staging cache",
            registry=self.registry,
        )
        self.cache_misses = Counter(
            f"{ns}_cache_misses_total",
            "Cacheable downloads that had to fetch from the network",
            registry=self.registry,
        )
        self.cache_coalesced = Counter(
            f"{ns}_cache_coalesced_waiters_total",
            "Jobs that awaited another job's in-flight fetch of the same "
            "content (singleflight fan-in)",
            registry=self.registry,
        )
        self.cache_bytes_saved = Counter(
            f"{ns}_cache_bytes_saved_total",
            "Bytes served from cache or coalesced fetches instead of "
            "re-downloaded over the network",
            registry=self.registry,
        )
        self.cache_evicted_bytes = Counter(
            f"{ns}_cache_evicted_bytes_total",
            "Bytes LRU-evicted from the staging cache",
            registry=self.registry,
        )
        self.scrub_objects = Counter(
            f"{ns}_scrub_objects_total",
            "Integrity-scrubber verdicts per object scanned (clean = "
            "digest matched; repaired = re-copied from a healthy "
            "replica into a fresh inode; quarantined = no healthy "
            "source, moved aside — never served)",
            ["outcome"],
            registry=self.registry,
        )
        # -- fleet coordination plane (fleet/) ------------------------
        self.fleet_workers_live = Gauge(
            f"{ns}_fleet_workers_live",
            "Workers with a live heartbeat in the fleet registry "
            "(sampled at this worker's own heartbeat)",
            registry=self.registry,
        )
        self.fleet_leases_acquired = Counter(
            f"{ns}_fleet_leases_acquired_total",
            "Cross-worker content leases this worker won, by mode "
            "(fresh, or takeover of a dead leader's expired lease)",
            ["mode"],
            registry=self.registry,
        )
        self.fleet_lease_waits = Counter(
            f"{ns}_fleet_lease_waits_total",
            "Jobs that parked waiting out a peer worker's content lease "
            "instead of duplicating its download",
            registry=self.registry,
        )
        self.fleet_shared_hits = Counter(
            f"{ns}_fleet_shared_tier_hits_total",
            "Cache entries materialized from the fleet shared tier "
            "instead of an origin",
            registry=self.registry,
        )
        self.fleet_shared_fills = Counter(
            f"{ns}_fleet_shared_tier_fills_total",
            "Local cache entries spilled to the fleet shared tier",
            registry=self.registry,
        )
        self.fleet_shared_bytes = Counter(
            f"{ns}_fleet_shared_tier_bytes_total",
            "Bytes moved through the fleet shared tier, by direction "
            "(out = spilled by this worker, in = materialized from peers)",
            ["direction"],
            registry=self.registry,
        )
        self.fleet_coord_errors = Counter(
            f"{ns}_fleet_coord_errors_total",
            "Coordination-store failures, by operation — each one is a "
            "moment this worker degraded toward uncoordinated fetching",
            ["op"],
            registry=self.registry,
        )
        self.fleet_gc_removed = Counter(
            f"{ns}_fleet_gc_removed_total",
            "Objects reclaimed by the fleet GC sweep, by kind "
            "(shared_entry = an evicted .fleet-cache/ entry, tombstone = "
            "a compacted .fleet/ coordination tombstone, telemetry = an "
            "aged .fleet/telemetry/ per-job trace digest)",
            ["kind"],
            registry=self.registry,
        )
        self.fleet_telemetry = Counter(
            f"{ns}_fleet_telemetry_digests_total",
            "Per-job trace-digest traffic through the coordination store, "
            "by op (published = digest written at settle, fetched = "
            "digests read during cross-worker trace assembly, error = "
            "either direction degraded)",
            ["op"],
            registry=self.registry,
        )
        self.fleet_gc_bytes = Counter(
            f"{ns}_fleet_gc_reclaimed_bytes_total",
            "Bytes reclaimed from the fleet shared cache tier by the GC "
            "sweep",
            registry=self.registry,
        )
        self.fleet_fenced_writes = Counter(
            f"{ns}_fleet_fenced_writes_total",
            "Cross-worker writes REJECTED by fencing-token enforcement, "
            "by op (shared_manifest = a stale leader's shared-tier "
            "publish, done_marker = a stale seal of the staging set, "
            "telemetry = a stale trace digest).  Each count is a "
            "split-brain write that did NOT land — nonzero during a "
            "partition/stall incident is the fence doing its job",
            ["op"],
            registry=self.registry,
        )
        self.fleet_watch_wakeups = Counter(
            f"{ns}_fleet_watch_wakeups_total",
            "Watch-plane wake-ups, by mode (event = the watch delivered "
            "changes, timeout = a bounded long-poll lapsed quiet, poll = "
            "degraded to sleep-poll because the watch was unavailable or "
            "broke).  A healthy fleet is event/timeout-dominated; a "
            "poll-dominated worker is running the degraded path",
            ["mode"],
            registry=self.registry,
        )
        self.fleet_origin_health = Counter(
            f"{ns}_fleet_origin_health_total",
            "Fleet-shared origin-health table traffic, by op (published "
            "= this worker CAS-merged its per-origin EWMAs, seeded = a "
            "boot imported fresh fleet rows into its local OriginHealth)",
            ["op"],
            registry=self.registry,
        )
        self.fleet_router_decisions = Counter(
            f"{ns}_fleet_router_decisions_total",
            "Content-router admission decisions, by outcome (run = no "
            "routing concern, defer = handed to the current lease "
            "holder via park+nack, fairness_defer = BULK deferred for "
            "fleet-wide tenant fairness, shed = BULK shed on the "
            "controller's plan, local = routing skipped because the "
            "holder is this worker)",
            ["outcome"],
            registry=self.registry,
        )
        self.fleet_controller_decisions = Counter(
            f"{ns}_fleet_controller_decisions_total",
            "Placement/autoscale controller decisions published on the "
            "fleet plan, by kind (shed_bulk = burn-rate-driven BULK "
            "admission shed, drain = a browning-out worker steered away "
            "from new leases, scale_up/scale_down = queue-depth scale "
            "signal edges, plan = a plan document published)",
            ["kind"],
            registry=self.registry,
        )
        self.fleet_plan_age = Gauge(
            f"{ns}_fleet_plan_age_seconds",
            "Age of the placement-controller plan document this worker "
            "last read (steady state: under 2x fleet.heartbeat_interval;"
            " climbing = the elected controller stopped planning).  -1 "
            "until a plan has been seen",
            registry=self.registry,
        )
        self.fleet_desired_workers = Gauge(
            f"{ns}_fleet_desired_workers",
            "Worker count the placement controller's plan currently "
            "asks for (the queue-depth autoscale signal, exported for "
            "external autoscalers; -1 until a plan has been seen)",
            registry=self.registry,
        )
        # -- SLO plane (control/slo.py) --------------------------------
        # "class" is bounded by the priority-class enum plus the
        # config-bounded tenant-objective names; "window" is the
        # fast|slow literal pair
        self.slo_burn_rate = Gauge(
            f"{ns}_slo_burn_rate",
            "Error-budget burn rate per SLO objective and window "
            "(fast ~5 m / slow ~1 h): bad_fraction / (1 - availability)."
            "  1.0 spends the budget exactly at the allowed rate; "
            "sustained > 1 on BOTH windows is the page condition",
            ["class", "window"],
            registry=self.registry,
        )
        self.slo_budget_remaining = Gauge(
            f"{ns}_slo_error_budget_remaining",
            "Fraction of the error budget left per SLO objective over "
            "slo.budget_window (1 = untouched, 0 = exhausted; clamped "
            "at 0)",
            ["class"],
            registry=self.registry,
        )
        # -- incident plane (downloader_tpu/incident) ------------------
        # "trigger" is bounded by the two code literals breach|manual
        # (incident/bundle.py TRIGGER_BREACH / TRIGGER_MANUAL)
        self.incident_bundles = Counter(
            f"{ns}_incident_bundles_total",
            "Incident bundles exported into the bounded ring, by "
            "trigger (breach = auto-export at a budget-burning settle; "
            "manual = admin API / CLI).  A breach-trigger rate above "
            "the slo_burn_rate page condition means the ring "
            "(incident.max_bundles) is evicting forensics — raise it "
            "or pull bundles off the worker faster",
            ["trigger"],
            registry=self.registry,
        )
        self.incident_replay_signature_match = Gauge(
            f"{ns}_incident_replay_signature_match",
            "1 when the latest incident replay reproduced the original "
            "breach signature (same objective classes, open-breaker "
            "dependency+reason, guilty hop, fencing verdict), 0 when "
            "it diverged; -1 until a replay has run.  Set by the bench "
            "--incident arm and `cli incident replay`",
            registry=self.registry,
        )
        self.incident_replay_signature_match.set(-1.0)
        self.fleet_overview_age = Gauge(
            f"{ns}_fleet_overview_age_seconds",
            "Age of the fleet-overview document this worker last "
            "published or read (steady state: under 2x "
            "fleet.heartbeat_interval; climbing = the elected "
            "aggregator stopped folding, or the coordination store is "
            "unreachable).  -1 until an overview has been seen",
            registry=self.registry,
        )
        # -- multi-tenant overload control (control/tenancy+overload) --
        self.jobs_shed = Counter(
            f"{ns}_jobs_shed_total",
            "Deliveries shed by the overload layer, by reason (loop_lag/"
            "disk_headroom/queue_depth/queue_age = saturation park+nack; "
            "deadline = TTL-expired BULK dropped as EXPIRED) and tenant",
            ["reason", "tenant"],
            registry=self.registry,
        )
        self.tenant_jobs = Counter(
            f"{ns}_tenant_jobs_total",
            "Settled deliveries per tenant, by terminal lifecycle state "
            "(the per-tenant slice of the job outcome counters)",
            ["tenant", "outcome"],
            registry=self.registry,
        )
        self.tenant_queue_depth = Gauge(
            f"{ns}_tenant_queue_depth",
            "Jobs accepted but not yet running, per tenant (the "
            "per-tenant breakdown of queue_depth; label set bounded by "
            "the configured tenants)",
            ["tenant"],
            registry=self.registry,
        )
        self.overload_saturated = Gauge(
            f"{ns}_overload_saturated",
            "1 while the overload controller considers this worker "
            "saturated (BULK work is being shed), else 0",
            registry=self.registry,
        )
        # -- autoscale signal trio (ROADMAP item 5's fleet contract) --
        self.queue_depth = Gauge(
            f"{ns}_queue_depth",
            "Jobs accepted but not yet running (RECEIVED/PARKED/"
            "ADMITTED) — the primary scale-out signal",
            registry=self.registry,
        )
        self.oldest_queued_seconds = Gauge(
            f"{ns}_oldest_queued_job_seconds",
            "Age of the oldest not-yet-running job — queue depth alone "
            "cannot distinguish a burst from a stall",
            registry=self.registry,
        )
        self.cache_headroom_bytes = Gauge(
            f"{ns}_cache_disk_headroom_bytes",
            "Free bytes on the cache (or download) volume — the "
            "scale-DOWN guard: a worker without disk headroom is not "
            "spare capacity",
            registry=self.registry,
        )
        # -- crash-safe durability (control/journal.py) ----------------
        self.jobs_recovered = Counter(
            f"{ns}_jobs_recovered_total",
            "Startup-reconciliation outcomes after a crash, by kind "
            "(replayed = journal job restored as a PARKED placeholder, "
            "resumable = workdir kept for its expected redelivery, "
            "demoted = torn landed output deleted for re-fetch, "
            "swept = orphan workdir deleted, adopted = redelivery took "
            "over its placeholder, cancelled = placeholder cancelled "
            "during the replay window, expired = placeholder or cancel "
            "tombstone retired past journal.tombstone_ttl — its "
            "redelivery never came, staged_elsewhere = placeholder "
            "retired DONE because a fleet peer's done marker proves the "
            "content already staged)",
            ["outcome"],
            registry=self.registry,
        )
        # -- bounded-growth gauges (the soak harness's SLO inputs) -----
        self.journal_bytes = Gauge(
            f"{ns}_journal_bytes",
            "Size of the job journal file on disk — compaction "
            "(journal.max_bytes) must hold this bounded by live-job "
            "count, not process age; a sustained climb means "
            "compaction is stalled or the live set itself is growing",
            registry=self.registry,
        )
        self.journal_lines = Gauge(
            f"{ns}_journal_lines",
            "Lines in the job journal file (one per lifecycle event "
            "since the last compaction snapshot) — the replay cost a "
            "restart would pay right now",
            registry=self.registry,
        )
        self.coord_docs = Gauge(
            f"{ns}_fleet_coord_docs_total",
            "Documents in the fleet coordination store per key prefix "
            "(workers / leases / telemetry), censused by the elected "
            "GC sweeper each fleet.gc_interval — growth here is a GC "
            "stall: telemetry digests and tombstones otherwise accrete "
            "one per job forever",
            ["prefix"],
            registry=self.registry,
        )
        self.recorder_ring_evictions = Counter(
            f"{ns}_recorder_ring_evictions_total",
            "Flight-recorder events evicted from per-job rings "
            "(obs.recorder_events), counted when each job settles — a "
            "high rate means long/chatty jobs are losing their early "
            "timeline and debug bundles show only the tail",
            registry=self.registry,
        )
        self.manifest_mismatches = Counter(
            f"{ns}_staged_manifest_mismatches_total",
            "Jobs whose staged objects failed the pre-done-marker "
            "content-manifest verification (short, missing, or "
            "hash-divergent staging set) — each one is a torn publish "
            "that was caught before the converter could trust it",
            registry=self.registry,
        )
        self.tenant_staging_bytes = Gauge(
            f"{ns}_tenant_staging_bytes",
            "Live staging footprint per tenant: bytes on disk under "
            "non-terminal jobs' workdirs (the disk half of per-tenant "
            "accounting; quotas cover transfer rate only)",
            ["tenant"],
            registry=self.registry,
        )
        # -- origin plane (downloader_tpu/origins/) --------------------
        # label cardinality is bounded by origins.max_labels (overflow
        # collapses to "other"), the tenant-table posture: job payloads
        # must not mint Prometheus series
        self.origin_bytes = Counter(
            f"{ns}_origin_bytes_total",
            "Bytes landed from each origin by the racing fetcher / "
            "manifest ingest (who actually served the fleet's bytes)",
            ["origin"],
            registry=self.registry,
        )
        self.origin_active_ranges = Gauge(
            f"{ns}_origin_active_ranges",
            "Byte ranges currently being fetched from each origin by "
            "the racing scheduler (owners + straggler duplicates)",
            ["origin"],
            registry=self.registry,
        )
        self.origin_race_wins = Counter(
            f"{ns}_origin_race_win_total",
            "Ranges an origin completed, by how it got them: fastest = "
            "work-stealing pull, failover = re-assigned after another "
            "origin died mid-range, straggler_dup = duplicate tail "
            "fetch that beat the original owner (first-byte-wins)",
            ["origin", "reason"],
            registry=self.registry,
        )
        self.torrent_hash_failures = Counter(
            f"{ns}_torrent_piece_hash_failures_total",
            "Torrent pieces that failed SHA-1 verification",
            registry=self.registry,
        )
        self.torrent_bytes_served = Counter(
            f"{ns}_torrent_bytes_served_total",
            "Bytes served back to the swarm while leeching/seeding",
            registry=self.registry,
        )

    def bind_tracer(self, tracer) -> None:
        """Surface tracer/OTLP-exporter internals on ``/metrics``.

        The exporter deliberately swallows failures in-flight (tracing
        must never fail the pipeline), which made them invisible; these
        gauges read its counters at scrape time, so a down collector
        shows up as climbing ``otlp_export_errors``/``otlp_spans_dropped``
        instead of silently missing traces.
        """
        self.tracer_buffer_spans.set_function(
            lambda: float(tracer.buffer_depth())
        )
        exporter = getattr(tracer, "exporter", None)
        if exporter is None:
            return
        self.otlp_spans_exported.set_function(
            lambda: float(exporter.exported))
        self.otlp_spans_dropped.set_function(
            lambda: float(exporter.dropped))
        self.otlp_export_errors.set_function(
            lambda: float(exporter.errors))
        self.otlp_queue_depth.set_function(
            lambda: float(exporter._queue.qsize()))

    def bind_journal(self, journal) -> None:
        """Wire the journal growth gauges to a live
        :class:`~..control.journal.JobJournal`.

        ``journal_bytes`` stats the file at scrape time (one syscall);
        ``journal_lines`` reads the in-memory census the journal
        maintains across appends and compactions.  Together they are
        the bounded-growth signal the soak harness guards on: the file
        must stay O(live jobs) no matter how many jobs have settled.
        """
        self.journal_bytes.set_function(
            lambda: float(journal.size_bytes))
        self.journal_lines.set_function(
            lambda: float(journal.lines))

    def bind_slo(self, tracker) -> None:
        """Wire the SLO gauges to a live
        :class:`~..control.slo.SloTracker`.

        The label set is fixed at bind time (priority classes + the
        config-bounded tenant objectives); every gauge reads the
        tracker's memoized snapshot, so one scrape pays one bounded
        ring scan however many objective/window series exist.
        """
        def entry(name: str) -> dict:
            return tracker.snapshot()["objectives"].get(name) or {}

        for name in tracker.objective_names():
            self.slo_burn_rate.labels(
                **{"class": name, "window": "fast"}).set_function(
                lambda n=name: float(entry(n).get("burnFast", 0.0)))
            self.slo_burn_rate.labels(
                **{"class": name, "window": "slow"}).set_function(
                lambda n=name: float(entry(n).get("burnSlow", 0.0)))
            self.slo_budget_remaining.labels(
                **{"class": name}).set_function(
                lambda n=name: float(
                    entry(n).get("budgetRemaining", 1.0)))

    def bind_overview_age(self, age_fn) -> None:
        """Wire ``fleet_overview_age_seconds`` to the fleet plane's
        last-seen overview stamp (``FleetPlane.overview_age``; None
        until any overview doc has been published or read -> -1)."""
        def _age() -> float:
            age = age_fn()
            return float(age) if age is not None else -1.0

        self.fleet_overview_age.set_function(_age)

    def bind_autoscale(self, signals_fn) -> None:
        """Wire the autoscale trio to a live snapshot callable.

        ``signals_fn`` returns ``{"queue_depth": int,
        "oldest_queued_seconds": float, "cache_headroom_bytes": int}``
        (the orchestrator's :meth:`autoscale_signals`); the gauges read
        it at scrape time, so /metrics and the fleet heartbeat payload
        report the SAME numbers by construction.  One snapshot is
        shared by all three gauges (a sub-second memo): a scrape pays
        one registry scan and one statvfs, not three of each.
        """
        memo = {"at": 0.0, "snap": None}

        def _snapshot() -> dict:
            now = time.monotonic()
            if memo["snap"] is None or now - memo["at"] > 0.5:
                memo["snap"] = signals_fn()
                memo["at"] = now
            return memo["snap"]

        self.queue_depth.set_function(
            lambda: float(_snapshot()["queue_depth"]))
        self.oldest_queued_seconds.set_function(
            lambda: float(_snapshot()["oldest_queued_seconds"]))
        self.cache_headroom_bytes.set_function(
            lambda: float(_snapshot()["cache_headroom_bytes"]))

    def bind_tenants(self, names, depths_fn) -> None:
        """Wire the per-tenant queue-depth gauges to a live snapshot.

        ``names`` is the config-bounded tenant set (so the label
        cardinality is fixed at bind time); ``depths_fn`` returns
        ``{tenant: queued_depth}`` (``JobRegistry.tenant_queue_depths``).
        One memoized snapshot serves every label per scrape, mirroring
        :meth:`bind_autoscale`.
        """
        memo = {"at": 0.0, "snap": None}

        def _snapshot() -> dict:
            now = time.monotonic()
            if memo["snap"] is None or now - memo["at"] > 0.5:
                memo["snap"] = depths_fn()
                memo["at"] = now
            return memo["snap"]

        for name in names:
            self.tenant_queue_depth.labels(tenant=name).set_function(
                lambda n=name: float(_snapshot().get(n, 0))
            )

    def bind_tenant_staging(self, names, footprint_fn) -> None:
        """Wire the per-tenant staging-footprint gauges to a live walk.

        ``footprint_fn`` returns ``{tenant: bytes_on_disk}``
        (``Orchestrator.tenant_staging_bytes`` — itself memoized for a
        few seconds, since the walk stats real workdirs); the label set
        is the config-bounded tenant list, like :meth:`bind_tenants`.
        """
        memo = {"at": 0.0, "snap": None}

        def _snapshot() -> dict:
            now = time.monotonic()
            if memo["snap"] is None or now - memo["at"] > 0.5:
                memo["snap"] = footprint_fn()
                memo["at"] = now
            return memo["snap"]

        for name in names:
            self.tenant_staging_bytes.labels(tenant=name).set_function(
                lambda n=name: float(_snapshot().get(n, 0))
            )

    def render(self) -> bytes:
        """Prometheus text exposition of the registry."""
        return generate_latest(self.registry)


def new(service: str = "downloader") -> Metrics:
    """(reference ``Prom.new('downloader')``, lib/main.js:43)"""
    return Metrics(service)
