"""Runtime observability: per-job flight recorder + event-loop introspection.

The reference plumbed a Jaeger tracer and never opened a span
(/root/reference/index.js:15; SURVEY.md §5 "plumbed-but-unused").  Our
rebuild fixed that at span/metric/log grain, but the four signals were
silos: a failing job's spans, log lines, Prometheus counters, and its
``GET /v1/jobs/{id}`` record could not be joined, and the asyncio
runtime itself (loop lag, stalled transfers, stuck tasks) was a black
box.  This module is the glue:

- :class:`FlightRecorder` — a bounded ring of structured events carried
  by every :class:`~..control.registry.JobRecord`: state transitions,
  queue/scheduler waits, throughput samples, cache decisions, retries,
  cancellation, settlement, and span references.  Retrievable live via
  ``GET /v1/jobs/{id}/events`` and dumped as a debug bundle when a job
  dies (FAILED / DROPPED_POISON).
- :class:`LoopLagMonitor` — samples event-loop scheduling lag into a
  gauge + histogram on ``/metrics`` (a blocked loop is the one failure
  every async service shares and none surface).
- :class:`TransferProfiler` — periodically samples each RUNNING job's
  live transfer counters into ``throughput`` flight-recorder events and
  flags flat-lined transfers (``stall_suspect``) long before the 240 s
  stall watchdog fires.
- :func:`dump_tasks` / :func:`dump_stacks` — live asyncio-task and
  thread-stack snapshots behind ``GET /debug/tasks`` / ``/debug/stacks``
  and the SIGUSR1 dump (app.py), so "what is the worker doing right
  now" never requires attaching a debugger.

Event schema: each event is one flat JSON object
``{"t": <epoch seconds>, "kind": <str>, ...fields}``.  ``t`` is
wall-clock so operators can join events against log timestamps; the
job's ``trace_id``/``span_id`` (also bound into its child logger and
its OTLP span) make the log/span/timeline join exact.
"""

from __future__ import annotations

import asyncio
import collections
import sys
import time
import traceback
from typing import Any, Dict, List, Optional

# default per-job event ring (``obs.recorder_events``): deep enough for a
# full lifecycle plus minutes of throughput samples, bounded so a
# retry-looping or hours-long job can never grow memory
DEFAULT_EVENT_LIMIT = 256

# default sampling cadences (``obs.loop_lag_interval`` /
# ``obs.profile_interval``)
DEFAULT_LAG_INTERVAL = 0.25
DEFAULT_PROFILE_INTERVAL = 5.0
# consecutive flat profiler samples before a RUNNING transfer is flagged
DEFAULT_STALL_SAMPLES = 3

# the streaming dispatch's combined RUNNING-stage attribution
# (stages/streaming.py runs download ∥ process ∥ upload as one stage).
# A string literal here, not an import — this module must not import the
# stages package (stages -> control -> this module would cycle).
PIPELINE_STAGE = "pipeline"


# The hops that are byte-COPY work on the staging path — each staged
# gigabyte pays each of these at most once, so their summed seconds
# over the widest single hop's bytes is the job's staging copy cost
# (``cpu_s_per_gb``), the number the zero-copy ratchet drives down.
# Excluded on purpose: wait hops (``origin_wait`` — stalled, not
# copying) and accelerator hops (``h2d``/``compute``/``d2h`` scale with
# pixels, not staged bytes).
COPY_HOPS = frozenset({
    "socket_read", "splice", "disk_write", "hash", "filter",
    "upload", "bucket_fetch", "shared_fetch", "cache",
})


class HopLedger:
    """Monotonic per-hop byte + time attribution for one job's transfer
    path (socket/splice read, disk write, hashing, filter, upload).

    Each ``note`` is two dict lookups and two adds — cheap enough for
    per-chunk calls on the hot transfer loops (the ``hop_ledger_overhead_ms``
    bench guard keeps it under 1 ms/job).  The summary is read once per
    job: the ``hopLedger`` block on ``GET /v1/jobs/{id}``, a
    ``hop_ledger`` flight-recorder event at settle, and the
    ``hop_seconds_per_gb{hop}`` observations — the attribution data
    ROADMAP item 3's zero-copy work ratchets against.
    """

    __slots__ = ("_hops",)

    # per-GB observations below this weight are noise (a 4 KiB marker
    # write "per GB" says nothing about the copy floor)
    MIN_OBSERVE_BYTES = 1 << 20

    def __init__(self) -> None:
        # hop -> [bytes, seconds], both monotonically accumulated
        self._hops: Dict[str, list] = {}

    def note(self, hop: str, nbytes: int, seconds: float) -> None:
        entry = self._hops.get(hop)
        if entry is None:
            self._hops[hop] = [int(nbytes), float(seconds)]
        else:
            entry[0] += int(nbytes)
            entry[1] += seconds

    def __bool__(self) -> bool:
        return bool(self._hops)

    def iter_hops(self):
        """``(hop, bytes, seconds)`` triples — the public read the SLO
        tracker's hop accumulation rides (the internal ``[bytes,
        seconds]`` list layout is not a contract; named away from the
        mapping protocol's ``items`` because these are triples, not
        key/value pairs)."""
        for hop, (nbytes, seconds) in self._hops.items():
            yield hop, nbytes, seconds

    def total_seconds(self) -> float:
        return sum(entry[1] for entry in self._hops.values())

    def summary(self) -> Dict[str, dict]:
        """``hop -> {bytes, seconds, secondsPerGb}`` (secondsPerGb only
        for hops that moved enough bytes to make the rate meaningful)."""
        out: Dict[str, dict] = {}
        for hop, (nbytes, seconds) in sorted(self._hops.items()):
            entry = {"bytes": nbytes, "seconds": round(seconds, 6)}
            if nbytes >= self.MIN_OBSERVE_BYTES:
                entry["secondsPerGb"] = round(seconds / (nbytes / 1e9), 3)
            out[hop] = entry
        return out

    def copy_seconds_per_gb(self) -> "tuple[float, str] | tuple[None, None]":
        """``(seconds_per_gb, top_hop)`` across the staging COPY_HOPS,
        or ``(None, None)`` when too few bytes moved to mean anything.

        Denominator: the WIDEST copy hop's bytes — the staged payload
        crosses each hop once, so the widest hop is the payload size;
        summing bytes across hops would count the same gigabyte at
        every hop it crossed.  ``top_hop`` is the per-rate worst
        offender among hops past the observation floor.
        """
        seconds = 0.0
        weight = 0
        top_hop, top_rate = None, -1.0
        for hop, (nbytes, secs) in self._hops.items():
            if hop not in COPY_HOPS:
                continue
            seconds += secs
            weight = max(weight, nbytes)
            if nbytes >= self.MIN_OBSERVE_BYTES:
                rate = secs / (nbytes / 1e9)
                if rate > top_rate:
                    top_hop, top_rate = hop, rate
        if weight < self.MIN_OBSERVE_BYTES:
            return None, None
        return seconds / (weight / 1e9), top_hop

    def observe(self, metrics) -> None:
        """Feed the job's totals into the fleet-wide hop metrics."""
        for hop, (nbytes, seconds) in self._hops.items():
            if nbytes:
                metrics.hop_bytes.labels(hop=hop).inc(nbytes)
            if seconds:
                metrics.hop_seconds.labels(hop=hop).inc(seconds)
            if nbytes >= self.MIN_OBSERVE_BYTES:
                metrics.hop_seconds_per_gb.labels(hop=hop).observe(
                    seconds / (nbytes / 1e9)
                )
            # per-hop copy-rate gauge (zero-copy ratchet): last settled
            # job's s/GB per copy hop — max() over the ``hop`` label is
            # the fleet's current top offender.  getattr-guarded so a
            # caller wiring a pre-ratchet metrics object keeps working.
            if (hop in COPY_HOPS and nbytes >= self.MIN_OBSERVE_BYTES
                    and getattr(metrics, "staging_hop_s_per_gb", None)
                    is not None):
                metrics.staging_hop_s_per_gb.labels(hop=hop).set(
                    seconds / (nbytes / 1e9)
                )
        per_gb, _top = self.copy_seconds_per_gb()
        if (per_gb is not None
                and getattr(metrics, "staging_cpu_s_per_gb", None)
                is not None):
            metrics.staging_cpu_s_per_gb.set(per_gb)


class FlightRecorder:
    """Bounded ring of structured events for one job.

    Append is O(1) and allocation-light (one small dict per event) — the
    bench guard (``recorder_overhead_ms`` < 1 ms/job, bench.py v10)
    keeps it honest.  The ring drops the *oldest* events and counts the
    drops, so a long job's tail — where failures live — is always kept.
    """

    __slots__ = ("_events", "dropped", "context")

    def __init__(self, limit: int = DEFAULT_EVENT_LIMIT,
                 context: Optional[Dict[str, Any]] = None):
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=max(int(limit), 1)
        )
        self.dropped = 0
        # bindings stamped into EVERY event (e.g. the fleet worker id,
        # so cross-worker traces join on (trace_id, worker_id) without
        # each event site threading identity through)
        self.context: Dict[str, Any] = dict(context or {})

    def record(self, kind: str, **fields: Any) -> None:
        if len(self._events) == self._events.maxlen:
            self.dropped += 1
        event = {"t": round(time.time(), 3), "kind": kind}
        if self.context:
            event.update(self.context)
        event.update(fields)
        self._events.append(event)

    def events(self) -> List[dict]:
        """Snapshot, oldest first (each event copied: callers may serve
        it over HTTP while the job keeps appending)."""
        return [dict(event) for event in self._events]

    def tail(self, count: int) -> List[dict]:
        return self.events()[-max(int(count), 0):]

    def __len__(self) -> int:
        return len(self._events)


class LoopLagMonitor:
    """Event-loop scheduling-lag sampler.

    Sleeps ``interval`` and measures how much later than requested the
    loop woke it — the classic lag probe.  Feeds the
    ``event_loop_lag_seconds`` gauge (last sample) and the
    ``event_loop_lag`` histogram on ``/metrics``, warns past
    ``warn_threshold``, and keeps ``last_lag``/``max_lag`` for
    ``GET /debug/tasks``.
    """

    def __init__(self, metrics=None, interval: float = DEFAULT_LAG_INTERVAL,
                 logger=None, warn_threshold: float = 0.5):
        self.metrics = metrics
        self.interval = max(float(interval), 0.01)
        self.logger = logger
        self.warn_threshold = warn_threshold
        self.last_lag = 0.0
        self.max_lag = 0.0
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            started = loop.time()
            await asyncio.sleep(self.interval)
            lag = max(0.0, loop.time() - started - self.interval)
            self.last_lag = lag
            if lag > self.max_lag:
                self.max_lag = lag
            if self.metrics is not None:
                self.metrics.event_loop_lag.set(lag)
                self.metrics.event_loop_lag_hist.observe(lag)
            if lag >= self.warn_threshold and self.logger is not None:
                self.logger.warn("event loop lag", lag_s=round(lag, 3))


class TransferProfiler:
    """Samples per-stage transfer progress into each job's recorder.

    Every ``interval`` seconds, each RUNNING record's live counters
    (``JobRecord.transferred``, fed by the stages' chunk loops, plus the
    telemetry progress percent) are diffed against the previous sample:
    movement becomes a ``throughput`` event (stage, bytes, bytes/s);
    ``stall_samples`` consecutive flat samples become one
    ``stall_suspect`` event + a warn log — minutes before the 240 s
    watchdog would kill the transfer, and visible per job via
    ``GET /v1/jobs/{id}/events``.
    """

    def __init__(self, registry, interval: float = DEFAULT_PROFILE_INTERVAL,
                 stall_samples: int = DEFAULT_STALL_SAMPLES, logger=None):
        self.registry = registry
        self.interval = max(float(interval), 0.01)
        self.stall_samples = max(int(stall_samples), 1)
        self.logger = logger
        # uid -> [monotonic, total_bytes, percent, consecutive_flat]
        self._last: Dict[int, list] = {}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.sample()

    def sample(self) -> None:
        """One sampling pass (sync: also drivable from tests)."""
        now = time.monotonic()
        seen = set()
        for record in list(self.registry._active.values()):
            # string compare, not an import: control.registry imports
            # this module for FlightRecorder (cycle otherwise)
            if record.state != "RUNNING":
                continue
            seen.add(record.uid)
            total = sum(record.transferred.values())
            percent = record.percent
            prev = self._last.get(record.uid)
            if prev is None:
                self._last[record.uid] = [now, total, percent, 0]
                continue
            t_prev, b_prev, p_prev, flat = prev
            elapsed = max(now - t_prev, 1e-9)
            delta = total - b_prev
            if delta > 0 or percent != p_prev:
                record.event(
                    "throughput", stage=record.stage, bytes=delta,
                    bps=round(delta / elapsed, 1), total=total,
                    percent=percent,
                )
                flat = 0
            else:
                flat += 1
                # only flag stages whose live counter was actually
                # flowing (a "download"/"upload" key exists for THIS
                # stage): compute stages (upscale/process) feed no
                # counters and must never read as stalled transfers.
                # The streaming dispatch's combined "pipeline" stage is
                # flagged on any LIVE counter — the runner retires both
                # counters once ingress completes (moving uploads
                # reinstall theirs), so its CPU-only reconciliation
                # phases carry no counters and stay exempt, matching
                # the barrier stages' behavior.
                if (flat == self.stall_samples
                        and (record.stage in record.transferred
                             or (record.stage == PIPELINE_STAGE
                                 and record.transferred))):
                    record.event(
                        "stall_suspect", stage=record.stage, total=total,
                        flat_s=round(self.interval * flat, 2),
                    )
                    if self.logger is not None:
                        # traceId explicitly: this logger is the service
                        # root, not the job's child, and the stall line
                        # must join the job's trace like every other
                        self.logger.warn(
                            "transfer flat-lined", jobId=record.job_id,
                            traceId=record.trace_id,
                            stage=record.stage, total_bytes=total,
                            flat_s=round(self.interval * flat, 2),
                        )
            self._last[record.uid] = [now, total, percent, flat]
        for uid in [u for u in self._last if u not in seen]:
            del self._last[uid]


# ---------------------------------------------------------------------------
# Live task / stack introspection (GET /debug/tasks, /debug/stacks, SIGUSR1)
# ---------------------------------------------------------------------------

def _frame_lines(frames, limit: int = 12) -> List[str]:
    out = []
    for frame in frames[-limit:]:
        code = frame.f_code
        out.append(f"{code.co_filename}:{frame.f_lineno} in {code.co_name}")
    return out


def dump_tasks(limit: int = 512) -> List[dict]:
    """Snapshot of live asyncio tasks: name, coroutine, top stack frames.

    Answers "what is every task blocked on" without a debugger.  Must be
    called from the loop thread (the aiohttp handlers and the SIGUSR1
    handler both are).
    """
    try:
        tasks = asyncio.all_tasks()
    except RuntimeError:
        return []
    out = []
    for task in list(tasks)[: max(int(limit), 1)]:
        coro = task.get_coro()
        qualname = getattr(coro, "__qualname__", None) or repr(coro)[:160]
        out.append({
            "name": task.get_name(),
            "done": task.done(),
            "coro": qualname,
            "stack": _frame_lines(task.get_stack(limit=12)),
        })
    out.sort(key=lambda t: t["name"])
    return out


def dump_stacks() -> dict:
    """Every thread's (and task's) current stack, formatted.

    The SIGUSR1 / ``GET /debug/stacks`` payload: the moral equivalent of
    ``kill -QUIT`` on a JVM — one shot that shows where a wedged worker
    is stuck, including the splice/upload worker threads the event loop
    cannot see.
    """
    import threading

    names = {t.ident: t.name for t in threading.enumerate()}
    threads = []
    for thread_id, frame in sys._current_frames().items():
        threads.append({
            "threadId": thread_id,
            "name": names.get(thread_id, "?"),
            "stack": [
                line.rstrip()
                for line in traceback.format_stack(frame)[-16:]
            ],
        })
    return {"threads": threads, "tasks": dump_tasks()}
