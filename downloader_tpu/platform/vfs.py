"""The write-syscall shim the storage fault plane rides.

Every landing/staging write in the service — the HTTP landing loop
(stages/download.py), the io_uring fallback (utils/uring.py), the fs
store's atomic writers and spill paths (store/fs.py) — routes its
write syscalls through this module instead of calling ``os.write`` /
``os.pwrite`` / ``os.replace`` directly.  In production the shim is a
pass-through (one module-level ``None`` check per call, the same cost
as the fault seams); under a fault plan with ``kind: disk`` rules
(platform/faults.py) it enacts the storage failure shapes a kernel
write path really has:

- ``enospc`` / ``eio`` — :class:`~.faults.DiskFault` raised from
  inside the write call, carrying the real errno
- ``short``   — ONE syscall accepts fewer bytes than asked; the
  caller's resume loop must carry on at the right offset
- ``latency`` — the write stalls (only enacted where the caller
  attests it is off the event loop: ``thread_ok=True``)
- ``torn``    — at :func:`promote`: rename WITHOUT the fsync, zero the
  tail of the renamed file, SIGKILL — the exact page-cache-loss state
  a power cut leaves behind a rename-before-data-durable bug.  The
  file's SIZE still matches (the torn pages are zeroed, not missing),
  so only digest-based boot recovery can catch it — which is the
  point.

Seam names fan the family out so one drill can target one layer:
``disk.write`` (landing/stream writes), ``disk.promote`` (the
fsync-before-rename publish), ``disk.fsync`` (durability barriers),
``disk.spill`` (fs-store atomic writers: cache inserts, shared-tier
spill, staged publish).  All share the ``disk`` dependency family, so
``seam: "disk.*"`` drills the whole plane.

:func:`promote` is also where the crash-consistency discipline lives:
fsync the data file, rename, fsync the parent directory — so a
promoted name never points at bytes the disk does not have.  Callers
that promote multi-GB landings run it off the loop
(``asyncio.to_thread``)."""

from __future__ import annotations

import os

from . import faults

#: bytes zeroed at the end of a torn-promoted file (one page's worth
#: rounded up — enough to defeat any size-only validity check)
TORN_TAIL_BYTES = 4096


def _action(seam: str, key: str, thread_ok: bool):
    if faults.enabled():
        return faults.disk_action(seam, key, thread_ok=thread_ok)
    return None


def _short(view: memoryview) -> memoryview:
    """The truncated prefix a short write accepts (always >= 1 byte, so
    forward progress is preserved and the drill can't livelock a
    write-all loop)."""
    if len(view) <= 1:
        return view
    return view[: max(1, len(view) // 2)]


def write(fd: int, data, *, seam: str = "disk.write", key: str = "",
          thread_ok: bool = False) -> int:
    """``os.write`` with the disk fault plan applied (may be short)."""
    view = memoryview(data)
    if _action(seam, key, thread_ok) == "short":
        view = _short(view)
    return os.write(fd, view)


def pwrite(fd: int, data, offset: int, *, seam: str = "disk.write",
           key: str = "", thread_ok: bool = True) -> int:
    """``os.pwrite`` with the disk fault plan applied (may be short)."""
    view = memoryview(data)
    if _action(seam, key, thread_ok) == "short":
        view = _short(view)
    return os.pwrite(fd, view, offset)


def write_all(fd: int, view, pos: "int | None", *,
              seam: str = "disk.write", key: str = "",
              thread_ok: bool = False) -> None:
    """Write a full buffer at ``pos`` (None = the fd's own offset),
    resuming short writes at the right offset — the landing loops'
    one write primitive."""
    view = memoryview(view)
    while view:
        if pos is None:
            n = write(fd, view, seam=seam, key=key, thread_ok=thread_ok)
        else:
            n = pwrite(fd, view, pos, seam=seam, key=key,
                       thread_ok=thread_ok)
            pos += n
        view = view[n:]


def fh_write_all(fh, data, *, seam: str = "disk.write", key: str = "",
                 thread_ok: bool = False) -> int:
    """Write a full buffer to a raw/binary file object, resuming short
    writes (a ``buffering=0`` stream's write is one syscall and may
    legally accept fewer bytes).  Returns bytes written."""
    view = memoryview(data)
    total = len(view)
    while view:
        sub = view
        if _action(seam, key, thread_ok) == "short":
            sub = _short(view)
        n = fh.write(sub)
        if n is None:  # non-blocking raw stream contract; not expected
            n = len(sub)
        view = view[n:]
    return total


def fsync(fd: int, *, seam: str = "disk.fsync", key: str = "") -> None:
    """``os.fsync`` with the disk fault plan applied (EIO drills)."""
    _action(seam, key, True)
    os.fsync(fd)


def fsync_path(path: str, *, seam: str = "disk.fsync",
               key: str = "") -> None:
    """Open-fsync-close one path — the promote barrier."""
    _action(seam, key or path, True)
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync (making a rename durable).  Swallows
    OSError: some filesystems refuse directory fsync, and a promote
    must not fail on the barrier a lesser filesystem cannot provide."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _torn_promote(src: str, dst: str, seam: str) -> None:
    """Enact the ``torn`` drill: rename without the data fsync, zero
    the file's tail (the pages the cache never wrote back), then die
    the way a power cut dies.  Never returns."""
    os.replace(src, dst)
    try:
        size = os.path.getsize(dst)
        tail = min(size, TORN_TAIL_BYTES)
        if tail:
            with open(dst, "r+b") as fh:
                fh.seek(size - tail)
                fh.write(b"\0" * tail)
                fh.flush()
                os.fsync(fh.fileno())
    except OSError:
        pass
    faults._crash_now(seam)


def promote(src: str, dst: str, *, seam: str = "disk.promote",
            key: str = "", durable: bool = True) -> None:
    """Crash-consistent rename-into-place: fsync the data file BEFORE
    the rename and the parent directory after, so the published name
    never points at bytes the disk does not hold.  ``durable=False``
    skips the barriers for small metadata sidecars whose loss is
    harmless (they are re-derivable).  ENOSPC/EIO disk rules raise
    here; a ``torn`` rule enacts the page-loss crash instead."""
    action = _action(seam, key or dst, True)
    if action == "torn":
        _torn_promote(src, dst, seam)
    if durable:
        fd = os.open(src, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    os.replace(src, dst)
    if durable:
        fsync_dir(os.path.dirname(dst))
