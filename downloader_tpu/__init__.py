"""downloader_tpu — a from-scratch rebuild of tritonmedia/downloader.

A message-driven media staging pipeline: consume ``Download`` jobs from a
queue, fetch media (torrent / http / file / bucket), filter for convertible
media files, stage them into an object store under ``<id>/original/`` with a
``done`` idempotency marker, emit telemetry + metrics, and publish ``Convert``
jobs for a downstream converter.

Layer map (mirrors SURVEY.md §1):

- ``app``            — entrypoint & lifecycle (reference index.js)
- ``orchestrator``   — job runtime: consume, decode, idempotency, stage loop,
                       ack/nack, publish (reference lib/main.js)
- ``stages``         — download / process / upload plugins (reference lib/*.js)
- ``platform``       — config, logging, tracing, metrics, telemetry, service
                       discovery (reference's external triton-core package)
- ``mq`` / ``store`` — queue + object-store abstractions with hermetic
                       in-memory implementations (the reference's RabbitMQ +
                       MinIO surface)
- ``torrent``        — pure-asyncio BitTorrent client (reference's webtorrent)
- ``compute``        — optional JAX/TPU demo of the downstream converter stage
                       the pipeline feeds (the reference itself has no tensor
                       compute; see SURVEY.md §7)
"""

__version__ = "0.1.0"

# backport asyncio pieces the codebase relies on when the runtime is
# older than the 3.11 target (no-op otherwise) — see utils/compat.py
from .utils.compat import install as _install_compat

_install_compat()
del _install_compat
