"""Hermetic in-process broker with at-least-once delivery semantics.

Stands in for RabbitMQ so the orchestrator and stages are testable without a
network broker (SURVEY.md §4 calls this out as the reference's biggest gap).
Semantics model the slice of AMQP the pipeline relies on:

- named FIFO queues, created on first use
- consumer prefetch (bounded unsettled deliveries per consumer)
- ``nack(requeue=True)`` redelivers with ``redelivered=True``
- unsettled deliveries from a crashed handler are redelivered
"""

from __future__ import annotations

import asyncio
import collections
from typing import Deque, Dict, List, Optional, Set, Tuple

from .base import Delivery, Handler, MessageQueue


class _Message:
    __slots__ = ("body", "redelivered", "deliveries", "headers")

    def __init__(self, body: bytes, headers: Optional[dict] = None):
        self.body = body
        self.redelivered = False
        self.deliveries = 0
        # copy: fanout shares the caller's dict across messages, and a
        # consumer mutating its delivery's headers must not bleed into
        # siblings/redeliveries (the AMQP backend isolates via the wire
        # codec; match it — review r5)
        self.headers = dict(headers) if headers else {}


class _MemoryDelivery(Delivery):
    __slots__ = ("_msg", "_broker", "_queue", "_settled", "_sem", "_headers")

    def __init__(self, msg: _Message, broker: "InMemoryBroker", queue: str,
                 sem: asyncio.Semaphore):
        self._msg = msg
        self._broker = broker
        self._queue = queue
        self._settled = False
        self._sem = sem
        # per-DELIVERY copy: the AMQP backend re-decodes headers from the
        # wire for every delivery, so a handler mutating its delivery's
        # headers must see a fresh dict again on redelivery (advisor r5)
        self._headers = dict(msg.headers)

    @property
    def body(self) -> bytes:
        return self._msg.body

    @property
    def redelivered(self) -> bool:
        return self._msg.redelivered

    @property
    def headers(self) -> dict:
        return self._headers

    def _settle(self) -> bool:
        if self._settled:
            return False
        self._settled = True
        self._sem.release()
        return True

    async def ack(self) -> None:
        if self._settle():
            self._broker._settled(self._queue)

    async def nack(self, requeue: bool = True) -> None:
        if self._settle():
            if requeue:
                self._msg.redelivered = True
                self._broker._requeue(self._queue, self._msg)
            self._broker._settled(self._queue)


class InMemoryBroker:
    """Shared broker state; one per test/process.

    ``max_redeliveries`` (optional) caps redelivery of a single message so a
    poison message cannot spin a test forever; ``None`` means redeliver
    forever, like a RabbitMQ queue without a dead-letter policy.
    """

    def __init__(self, max_redeliveries: Optional[int] = None):
        self._queues: Dict[str, Deque[_Message]] = collections.defaultdict(collections.deque)
        self._events: Dict[str, asyncio.Event] = {}
        self._published: Dict[str, List[bytes]] = collections.defaultdict(list)
        self._unsettled: Dict[str, int] = collections.defaultdict(int)
        self.max_redeliveries = max_redeliveries
        self.dropped: List[Tuple[str, bytes]] = []
        # fanout exchanges: name -> bound queue names (ordered, deduped)
        self._exchanges: Dict[str, Dict[str, None]] = collections.defaultdict(dict)

    # -- introspection helpers for tests --------------------------------
    def published(self, queue: str) -> List[bytes]:
        """All bodies ever published to ``queue`` (including consumed ones)."""
        return list(self._published[queue])

    def depth(self, queue: str) -> int:
        """Messages currently waiting in ``queue``."""
        return len(self._queues[queue])

    def idle(self, queue: str) -> bool:
        """True when ``queue`` has no waiting or unsettled messages."""
        return not self._queues[queue] and self._unsettled[queue] == 0

    async def join(self, queue: str, timeout: float = 10.0) -> None:
        """Wait until ``queue`` is fully drained and settled."""
        async with asyncio.timeout(timeout):
            while not self.idle(queue):
                await asyncio.sleep(0.005)

    # -- broker internals ----------------------------------------------
    def _event(self, queue: str) -> asyncio.Event:
        if queue not in self._events:
            self._events[queue] = asyncio.Event()
        return self._events[queue]

    def _push(self, queue: str, msg: _Message, front: bool = False) -> None:
        if front:
            self._queues[queue].appendleft(msg)
        else:
            self._queues[queue].append(msg)
        self._event(queue).set()

    def _requeue(self, queue: str, msg: _Message) -> None:
        if self.max_redeliveries is not None and msg.deliveries > self.max_redeliveries:
            self.dropped.append((queue, msg.body))
            return
        self._push(queue, msg, front=True)

    def _settled(self, queue: str) -> None:
        self._unsettled[queue] -= 1

    def publish(self, queue: str, body: bytes,
                headers: Optional[dict] = None) -> None:
        self._published[queue].append(body)
        self._push(queue, _Message(body, headers))

    def bind(self, queue: str, exchange: str) -> None:
        self._exchanges[exchange][queue] = None

    def publish_exchange(self, exchange: str, body: bytes,
                         headers: Optional[dict] = None) -> None:
        """Fanout: every bound queue gets its own copy."""
        for queue in self._exchanges[exchange]:
            self.publish(queue, body, headers)

    async def pop(self, queue: str) -> _Message:
        q = self._queues[queue]
        event = self._event(queue)
        while not q:
            event.clear()
            await event.wait()
        msg = q.popleft()
        msg.deliveries += 1
        self._unsettled[queue] += 1
        return msg


class MemoryQueue(MessageQueue):
    """A connection to an :class:`InMemoryBroker`."""

    def __init__(self, broker: InMemoryBroker):
        self._broker = broker
        self._consume_loops: Set[asyncio.Task] = set()
        self._handlers: Set[asyncio.Task] = set()
        # subscriptions survive stop_consuming so resume_consuming can
        # re-spawn them (control-plane intake pause/resume); the shared
        # semaphore keeps unsettled deliveries counted across the pause
        self._subscriptions: list = []
        self._connected = False

    async def connect(self) -> None:
        self._connected = True

    async def stop_consuming(self) -> None:
        for task in self._consume_loops:
            task.cancel()
        for task in list(self._consume_loops):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._consume_loops.clear()

    async def resume_consuming(self) -> None:
        if not self._connected:
            raise RuntimeError("resume on closed queue connection")
        if self._consume_loops:
            return  # already consuming
        for sub in self._subscriptions:
            self._spawn_consumer(*sub)

    async def close(self) -> None:
        self._connected = False
        await self.stop_consuming()
        for task in self._handlers:
            task.cancel()
        for task in list(self._handlers):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._handlers.clear()

    async def publish(self, queue: str, body: bytes,
                      headers: Optional[dict] = None) -> None:
        if not self._connected:
            raise RuntimeError("publish on closed queue connection")
        self._broker.publish(queue, body, headers)

    async def publish_exchange(self, exchange: str, body: bytes,
                               headers: Optional[dict] = None) -> None:
        if not self._connected:
            raise RuntimeError("publish on closed queue connection")
        self._broker.publish_exchange(exchange, body, headers)

    async def bind_queue(self, queue: str, exchange: str,
                         exclusive: bool = False) -> None:
        if not self._connected:
            raise RuntimeError("bind on closed queue connection")
        self._broker.bind(queue, exchange)

    async def listen(self, queue: str, handler: Handler, prefetch: int = 1) -> None:
        if not self._connected:
            raise RuntimeError("listen on closed queue connection")
        sem = asyncio.Semaphore(prefetch)
        self._subscriptions.append((queue, handler, sem))
        self._spawn_consumer(queue, handler, sem)

    def _spawn_consumer(self, queue: str, handler: Handler,
                        sem: asyncio.Semaphore) -> None:
        async def _consume() -> None:
            while True:
                await sem.acquire()
                try:
                    msg = await self._broker.pop(queue)
                except asyncio.CancelledError:
                    # stop_consuming cancelled us while parked on an empty
                    # queue: give the permit back or every pause/resume
                    # cycle would shrink the effective prefetch by one
                    sem.release()
                    raise
                delivery = _MemoryDelivery(msg, self._broker, queue, sem)

                async def _run(d: _MemoryDelivery = delivery) -> None:
                    try:
                        await handler(d)
                    except asyncio.CancelledError:
                        # cancelled mid-handler (connection close): requeue so
                        # the at-least-once contract holds
                        await d.nack(requeue=True)
                        raise
                    except Exception:
                        # crashed handler: redeliver, like an AMQP channel
                        # close would
                        await d.nack(requeue=True)

                task = asyncio.create_task(_run())
                self._handlers.add(task)
                task.add_done_callback(self._handlers.discard)

        self._consume_loops.add(asyncio.create_task(_consume()))
