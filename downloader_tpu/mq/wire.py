"""AMQP 0-9-1 wire codec: frames, field values, methods, content.

The reference speaks AMQP 0-9-1 to RabbitMQ through ``triton-core/amqp``
(amqplib, /root/reference/yarn.lock:3574-3575; connected at
/root/reference/lib/main.js:46-47).  This module implements the subset of
the protocol the pipeline exercises — connection/channel handshake, queue
declare, qos, publish with content, consume/deliver, ack/nack, heartbeat —
from the public AMQP 0-9-1 specification.  It is shared by the asyncio
client (:mod:`downloader_tpu.mq.amqp`) and the hermetic test broker
(``tests/miniamqp.py``), so both ends of every test exchange real protocol
bytes.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"
FRAME_END = 0xCE

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8

# class ids
CLASS_CONNECTION = 10
CLASS_CHANNEL = 20
CLASS_QUEUE = 50
CLASS_BASIC = 60

# (class, method) ids for the methods this framework uses
CONNECTION_START = (10, 10)
CONNECTION_START_OK = (10, 11)
CONNECTION_TUNE = (10, 30)
CONNECTION_TUNE_OK = (10, 31)
CONNECTION_OPEN = (10, 40)
CONNECTION_OPEN_OK = (10, 41)
CONNECTION_CLOSE = (10, 50)
CONNECTION_CLOSE_OK = (10, 51)

CHANNEL_OPEN = (20, 10)
CHANNEL_OPEN_OK = (20, 11)
CHANNEL_CLOSE = (20, 40)
CHANNEL_CLOSE_OK = (20, 41)

EXCHANGE_DECLARE = (40, 10)
EXCHANGE_DECLARE_OK = (40, 11)

QUEUE_DECLARE = (50, 10)
QUEUE_DECLARE_OK = (50, 11)
QUEUE_BIND = (50, 20)
QUEUE_BIND_OK = (50, 21)

BASIC_QOS = (60, 10)
BASIC_QOS_OK = (60, 11)
BASIC_CONSUME = (60, 20)
BASIC_CONSUME_OK = (60, 21)
BASIC_CANCEL = (60, 30)
BASIC_CANCEL_OK = (60, 31)
BASIC_PUBLISH = (60, 40)
BASIC_DELIVER = (60, 60)
BASIC_ACK = (60, 80)
BASIC_NACK = (60, 120)

CONFIRM_SELECT = (85, 10)
CONFIRM_SELECT_OK = (85, 11)

# Per-method argument layouts.  Codes: 'o' octet, 'h' short, 'l' long,
# 'q' long-long, 's' shortstr, 'S' longstr, 'F' field table, 'b' bit.
# Consecutive bits pack into shared octets, per the spec.
METHOD_ARGS: Dict[Tuple[int, int], str] = {
    CONNECTION_START: "ooFSS",
    CONNECTION_START_OK: "FsSs",
    CONNECTION_TUNE: "hlh",
    CONNECTION_TUNE_OK: "hlh",
    CONNECTION_OPEN: "ssb",
    CONNECTION_OPEN_OK: "s",
    CONNECTION_CLOSE: "hshh",
    CONNECTION_CLOSE_OK: "",
    CHANNEL_OPEN: "s",
    CHANNEL_OPEN_OK: "S",
    CHANNEL_CLOSE: "hshh",
    CHANNEL_CLOSE_OK: "",
    EXCHANGE_DECLARE: "hssbbbbbF",
    EXCHANGE_DECLARE_OK: "",
    QUEUE_DECLARE: "hsbbbbbF",
    QUEUE_DECLARE_OK: "sll",
    QUEUE_BIND: "hsssbF",
    QUEUE_BIND_OK: "",
    BASIC_QOS: "lhb",
    BASIC_QOS_OK: "",
    BASIC_CONSUME: "hssbbbbF",
    BASIC_CONSUME_OK: "s",
    BASIC_CANCEL: "sb",
    BASIC_CANCEL_OK: "s",
    BASIC_PUBLISH: "hssbb",
    BASIC_DELIVER: "sqbss",
    BASIC_ACK: "qb",
    BASIC_NACK: "qbb",
    CONFIRM_SELECT: "b",
    CONFIRM_SELECT_OK: "",
}

# Basic content properties, in property-flag order (bit 15 downward).
BASIC_PROPERTIES: List[Tuple[str, str]] = [
    ("content_type", "s"),
    ("content_encoding", "s"),
    ("headers", "F"),
    ("delivery_mode", "o"),
    ("priority", "o"),
    ("correlation_id", "s"),
    ("reply_to", "s"),
    ("expiration", "s"),
    ("message_id", "s"),
    ("timestamp", "q"),
    ("type", "s"),
    ("user_id", "s"),
    ("app_id", "s"),
    ("cluster_id", "s"),
]


class ProtocolError(Exception):
    """Malformed or unexpected AMQP bytes."""


# ---------------------------------------------------------------------------
# primitive value codec
# ---------------------------------------------------------------------------


class Writer:
    """Append-only buffer with AMQP primitive encoders."""

    def __init__(self) -> None:
        self._parts: List[bytes] = []
        # pending bit-pack state: consecutive 'b' args share octets
        self._bits: List[bool] = []

    def _flush_bits(self) -> None:
        while self._bits:
            chunk, self._bits = self._bits[:8], self._bits[8:]
            octet = 0
            for i, bit in enumerate(chunk):
                if bit:
                    octet |= 1 << i
            self._parts.append(bytes([octet]))

    def octet(self, v: int) -> None:
        self._flush_bits()
        self._parts.append(struct.pack(">B", v))

    def short(self, v: int) -> None:
        self._flush_bits()
        self._parts.append(struct.pack(">H", v))

    def long(self, v: int) -> None:
        self._flush_bits()
        self._parts.append(struct.pack(">I", v))

    def longlong(self, v: int) -> None:
        self._flush_bits()
        self._parts.append(struct.pack(">Q", v))

    def bit(self, v: bool) -> None:
        self._bits.append(bool(v))

    def shortstr(self, v: str) -> None:
        self._flush_bits()
        raw = v.encode("utf-8")
        if len(raw) > 255:
            raise ProtocolError("shortstr too long")
        self._parts.append(struct.pack(">B", len(raw)) + raw)

    def longstr(self, v) -> None:
        self._flush_bits()
        raw = v if isinstance(v, (bytes, bytearray)) else str(v).encode("utf-8")
        self._parts.append(struct.pack(">I", len(raw)) + bytes(raw))

    def table(self, v: Optional[Dict[str, Any]]) -> None:
        self._flush_bits()
        body = _encode_table(v or {})
        self._parts.append(struct.pack(">I", len(body)) + body)

    def getvalue(self) -> bytes:
        self._flush_bits()
        return b"".join(self._parts)


class Reader:
    """Cursor over received AMQP bytes with primitive decoders."""

    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0
        # bit-unpack state mirrors Writer._bits
        self._bit_octet = 0
        self._bits_left = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise ProtocolError("truncated frame payload")
        out = self._data[self._pos:self._pos + n]
        self._pos += n
        return out

    def _reset_bits(self) -> None:
        self._bits_left = 0

    def octet(self) -> int:
        self._reset_bits()
        return self._take(1)[0]

    def short(self) -> int:
        self._reset_bits()
        return struct.unpack(">H", self._take(2))[0]

    def long(self) -> int:
        self._reset_bits()
        return struct.unpack(">I", self._take(4))[0]

    def longlong(self) -> int:
        self._reset_bits()
        return struct.unpack(">Q", self._take(8))[0]

    def bit(self) -> bool:
        if self._bits_left == 0:
            self._bit_octet = self._take(1)[0]
            self._bits_left = 8
        v = bool(self._bit_octet & 1)
        self._bit_octet >>= 1
        self._bits_left -= 1
        return v

    def shortstr(self) -> str:
        self._reset_bits()
        n = self._take(1)[0]
        return self._take(n).decode("utf-8")

    def longstr(self) -> bytes:
        self._reset_bits()
        n = struct.unpack(">I", self._take(4))[0]
        return self._take(n)

    def table(self) -> Dict[str, Any]:
        self._reset_bits()
        n = struct.unpack(">I", self._take(4))[0]
        return _decode_table(Reader(self._take(n)))

    def remaining(self) -> int:
        return len(self._data) - self._pos


def _encode_value(v: Any) -> bytes:
    """Encode one field-table value with its type octet (RabbitMQ dialect)."""
    if isinstance(v, bool):
        return b"t" + struct.pack(">B", int(v))
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"I" + struct.pack(">i", v)
        return b"l" + struct.pack(">q", v)
    if isinstance(v, float):
        return b"d" + struct.pack(">d", v)
    if isinstance(v, str):
        raw = v.encode("utf-8")
        return b"S" + struct.pack(">I", len(raw)) + raw
    if isinstance(v, (bytes, bytearray)):
        return b"S" + struct.pack(">I", len(v)) + bytes(v)
    if isinstance(v, dict):
        body = _encode_table(v)
        return b"F" + struct.pack(">I", len(body)) + body
    if isinstance(v, (list, tuple)):
        body = b"".join(_encode_value(item) for item in v)
        return b"A" + struct.pack(">I", len(body)) + body
    if v is None:
        return b"V"
    raise ProtocolError(f"cannot encode table value of type {type(v).__name__}")


def _encode_table(table: Dict[str, Any]) -> bytes:
    out = []
    for key, value in table.items():
        raw = key.encode("utf-8")
        out.append(struct.pack(">B", len(raw)) + raw + _encode_value(value))
    return b"".join(out)


def _decode_value(r: Reader) -> Any:
    kind = r._take(1)
    if kind == b"t":
        return bool(r._take(1)[0])
    if kind == b"b":
        return struct.unpack(">b", r._take(1))[0]
    if kind == b"B":
        return r._take(1)[0]
    if kind == b"s":
        return struct.unpack(">h", r._take(2))[0]
    if kind == b"u":
        return struct.unpack(">H", r._take(2))[0]
    if kind == b"I":
        return struct.unpack(">i", r._take(4))[0]
    if kind == b"i":
        return struct.unpack(">I", r._take(4))[0]
    if kind == b"l":
        return struct.unpack(">q", r._take(8))[0]
    if kind == b"f":
        return struct.unpack(">f", r._take(4))[0]
    if kind == b"d":
        return struct.unpack(">d", r._take(8))[0]
    if kind == b"D":  # decimal: scale octet + long
        scale = r._take(1)[0]
        return struct.unpack(">i", r._take(4))[0] / (10 ** scale)
    if kind == b"S":
        n = struct.unpack(">I", r._take(4))[0]
        return r._take(n).decode("utf-8", "replace")
    if kind == b"x":
        n = struct.unpack(">I", r._take(4))[0]
        return r._take(n)
    if kind == b"T":
        return struct.unpack(">Q", r._take(8))[0]
    if kind == b"F":
        n = struct.unpack(">I", r._take(4))[0]
        return _decode_table(Reader(r._take(n)))
    if kind == b"A":
        n = struct.unpack(">I", r._take(4))[0]
        sub = Reader(r._take(n))
        items = []
        while sub.remaining():
            items.append(_decode_value(sub))
        return items
    if kind == b"V":
        return None
    raise ProtocolError(f"unknown field-table value type {kind!r}")


def _decode_table(r: Reader) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    while r.remaining():
        n = r._take(1)[0]
        key = r._take(n).decode("utf-8")
        out[key] = _decode_value(r)
    return out


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------


def encode_frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return struct.pack(">BHI", ftype, channel, len(payload)) + payload + bytes([FRAME_END])


def encode_method(channel: int, method: Tuple[int, int], *args: Any) -> bytes:
    """Encode a method frame using the METHOD_ARGS layout for ``method``."""
    w = Writer()
    w.short(method[0])
    w.short(method[1])
    layout = METHOD_ARGS[method]
    if len(args) != len(layout):
        raise ProtocolError(
            f"method {method} takes {len(layout)} args, got {len(args)}"
        )
    for code, arg in zip(layout, args):
        if code == "o":
            w.octet(arg)
        elif code == "h":
            w.short(arg)
        elif code == "l":
            w.long(arg)
        elif code == "q":
            w.longlong(arg)
        elif code == "s":
            w.shortstr(arg)
        elif code == "S":
            w.longstr(arg)
        elif code == "F":
            w.table(arg)
        elif code == "b":
            w.bit(arg)
        else:  # pragma: no cover - layout strings are static
            raise ProtocolError(f"bad layout code {code!r}")
    return encode_frame(FRAME_METHOD, channel, w.getvalue())


def decode_method(payload: bytes) -> Tuple[Tuple[int, int], List[Any]]:
    """Decode a method frame payload into ((class, method), args)."""
    r = Reader(payload)
    method = (r.short(), r.short())
    layout = METHOD_ARGS.get(method)
    if layout is None:
        raise ProtocolError(f"unsupported method {method}")
    args: List[Any] = []
    for code in layout:
        if code == "o":
            args.append(r.octet())
        elif code == "h":
            args.append(r.short())
        elif code == "l":
            args.append(r.long())
        elif code == "q":
            args.append(r.longlong())
        elif code == "s":
            args.append(r.shortstr())
        elif code == "S":
            args.append(r.longstr())
        elif code == "F":
            args.append(r.table())
        elif code == "b":
            args.append(r.bit())
    return method, args


def encode_content_header(
    channel: int, body_size: int, properties: Optional[Dict[str, Any]] = None
) -> bytes:
    """Encode a basic-class content header frame."""
    properties = properties or {}
    w = Writer()
    w.short(CLASS_BASIC)
    w.short(0)  # weight, always 0
    w.longlong(body_size)
    flags = 0
    vals = Writer()
    for i, (name, code) in enumerate(BASIC_PROPERTIES):
        value = properties.get(name)
        if value is None:
            continue
        flags |= 1 << (15 - i)
        if code == "s":
            vals.shortstr(value)
        elif code == "o":
            vals.octet(value)
        elif code == "q":
            vals.longlong(value)
        elif code == "F":
            vals.table(value)
    w.short(flags)
    return encode_frame(FRAME_HEADER, channel, w.getvalue() + vals.getvalue())


def decode_content_header(payload: bytes) -> Tuple[int, Dict[str, Any]]:
    """Decode a content header payload into (body_size, properties)."""
    r = Reader(payload)
    class_id = r.short()
    if class_id != CLASS_BASIC:
        raise ProtocolError(f"unexpected content class {class_id}")
    r.short()  # weight
    body_size = r.longlong()
    flags = r.short()
    props: Dict[str, Any] = {}
    for i, (name, code) in enumerate(BASIC_PROPERTIES):
        if not flags & (1 << (15 - i)):
            continue
        if code == "s":
            props[name] = r.shortstr()
        elif code == "o":
            props[name] = r.octet()
        elif code == "q":
            props[name] = r.longlong()
        elif code == "F":
            props[name] = r.table()
    return body_size, props


def encode_body_frames(channel: int, body: bytes, frame_max: int) -> List[bytes]:
    """Split ``body`` into body frames honouring the negotiated frame-max."""
    # frame overhead: 7-byte header + 1-byte end marker
    chunk = max(frame_max - 8, 1)
    return [
        encode_frame(FRAME_BODY, channel, body[i:i + chunk])
        for i in range(0, len(body), chunk)
    ] or [encode_frame(FRAME_BODY, channel, b"")]


async def read_frame(reader) -> Tuple[int, int, bytes]:
    """Read one frame from an ``asyncio.StreamReader``."""
    header = await reader.readexactly(7)
    ftype, channel, size = struct.unpack(">BHI", header)
    payload = await reader.readexactly(size)
    end = await reader.readexactly(1)
    if end[0] != FRAME_END:
        raise ProtocolError(f"bad frame end {end!r}")
    return ftype, channel, payload
