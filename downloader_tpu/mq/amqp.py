"""Asyncio AMQP 0-9-1 client implementing the :class:`MessageQueue` surface.

Capability-equivalent to the reference's ``triton-core/amqp`` stack:
amqplib for the protocol plus amqp-connection-manager for automatic
reconnect/resubscribe (/root/reference/yarn.lock:3574-3575), constructed and
connected at /root/reference/lib/main.js:46-47 and consumed via
``listen``/``publish``/``close`` with per-delivery ``ack``/``nack``
(/root/reference/lib/main.js:145-150,164,168,172,200).

Pure stdlib asyncio — no external AMQP dependency.  Framing lives in
:mod:`downloader_tpu.mq.wire`; this module owns the connection state
machine:

- PLAIN-auth handshake, tune negotiation, heartbeats both directions
- one data channel (the pipeline's whole surface is two queues)
- durable queue declaration on first use, broker-side prefetch via
  ``basic.qos``
- consume/deliver with at-least-once settlement; a crashed handler nacks
  for redelivery, mirroring the in-memory broker's contract
- automatic reconnect with exponential backoff and consumer re-subscribe;
  settlements for deliveries from a dead connection are dropped so the
  broker's redelivery provides the at-least-once guarantee
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional, Set, Tuple
from urllib.parse import unquote, urlparse

from . import wire
from .base import Delivery, Handler, MessageQueue

DEFAULT_PORT = 5672
DEFAULT_TLS_PORT = 5671
DEFAULT_FRAME_MAX = 131072
RPC_TIMEOUT = 30.0


class AccessRefused(ConnectionError):
    """The broker refused the handshake (bad credentials / vhost).

    Permanent: retrying with the same parameters cannot succeed, so the
    connect retry loop re-raises instead of backing off.
    """


def parse_amqp_url(url: str) -> Dict[str, Any]:
    """Parse ``amqp(s)://user:pass@host:port/vhost`` with RabbitMQ
    defaults (5672 plain, 5671 TLS)."""
    parsed = urlparse(url if "//" in url else f"amqp://{url}")
    if parsed.scheme not in ("amqp", "amqps", ""):
        raise ValueError(f"unsupported scheme {parsed.scheme!r}")
    tls = parsed.scheme == "amqps"
    vhost = unquote(parsed.path[1:]) if len(parsed.path) > 1 else "/"
    return {
        "host": parsed.hostname or "localhost",
        "port": parsed.port or (DEFAULT_TLS_PORT if tls else DEFAULT_PORT),
        "user": unquote(parsed.username) if parsed.username else "guest",
        "password": unquote(parsed.password) if parsed.password else "guest",
        "vhost": vhost,
        "tls": tls,
    }


class _Subscription:
    __slots__ = ("queue", "handler", "prefetch", "consumer_tag")

    def __init__(self, queue: str, handler: Handler, prefetch: int, tag: str):
        self.queue = queue
        self.handler = handler
        self.prefetch = prefetch
        self.consumer_tag = tag


class _PendingPublish:
    """A publish awaiting broker confirmation (confirm mode).

    Kept until the broker acks it; resent on a fresh connection if the old
    one died first — the amqp-connection-manager behavior the reference
    relies on for publish reliability.
    """

    __slots__ = ("queue", "body", "fut", "exchange", "headers")

    def __init__(self, queue: str, body: bytes, fut: asyncio.Future,
                 exchange: str = "", headers: Optional[dict] = None):
        self.queue = queue          # routing key when exchange is ""
        self.body = body
        self.fut = fut
        self.exchange = exchange    # fanout exchange name, "" = default
        self.headers = headers      # application headers (traceparent)


class _AmqpDelivery(Delivery):
    __slots__ = ("_client", "_tag", "_epoch", "_body", "_redelivered",
                 "_settled", "_headers")

    def __init__(self, client: "AmqpQueue", tag: int, epoch: int,
                 body: bytes, redelivered: bool,
                 headers: Optional[dict] = None):
        self._client = client
        self._tag = tag
        self._epoch = epoch
        self._body = body
        self._redelivered = redelivered
        self._settled = False
        self._headers = headers or {}

    @property
    def body(self) -> bytes:
        return self._body

    @property
    def redelivered(self) -> bool:
        return self._redelivered

    @property
    def headers(self) -> dict:
        return self._headers

    async def ack(self) -> None:
        if self._settled:
            return
        self._settled = True
        await self._client._settle(self._tag, self._epoch, ack=True)

    async def nack(self, requeue: bool = True) -> None:
        if self._settled:
            return
        self._settled = True
        await self._client._settle(self._tag, self._epoch, ack=False, requeue=requeue)


class AmqpQueue(MessageQueue):
    """A resilient connection to an AMQP 0-9-1 broker (e.g. RabbitMQ)."""

    CHANNEL = 1

    def __init__(
        self,
        url: str,
        heartbeat: int = 30,
        reconnect_initial: float = 0.1,
        reconnect_max: float = 5.0,
        connect_attempts: Optional[int] = None,
        logger=None,
        ssl_context=None,
    ):
        """``amqps://`` URLs negotiate TLS (default port 5671) using
        ``ssl_context`` or a default verifying context."""
        self._params = parse_amqp_url(url)
        self._ssl_context = ssl_context
        self._want_heartbeat = heartbeat
        self._reconnect_initial = reconnect_initial
        self._reconnect_max = reconnect_max
        # None = retry the initial connect forever (the reference's
        # amqp-connection-manager behavior: a worker booting before its
        # broker waits for it rather than crash-looping)
        self._connect_attempts = connect_attempts
        self._logger = logger

        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._frame_max = DEFAULT_FRAME_MAX
        self._heartbeat = heartbeat
        self._epoch = 0  # bumped per (re)connect; stale settlements are dropped
        self._connected = asyncio.Event()
        self._closing = False

        self._read_task: Optional[asyncio.Task] = None
        self._heartbeat_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._last_recv = 0.0

        self._rpc_lock = asyncio.Lock()
        self._send_lock = asyncio.Lock()
        self._pending_rpc: Optional[Tuple[Tuple[int, int], asyncio.Future]] = None

        self._declared: Set[str] = set()
        self._declared_exchanges: Set[str] = set()
        # (queue, exchange, exclusive) bindings, replayed on reconnect
        self._bindings: List[Tuple[str, str, bool]] = []
        self._subscriptions: Dict[str, _Subscription] = {}  # by consumer tag
        self._consuming = True
        self._next_tag = 0
        self._handlers: Set[asyncio.Task] = set()

        # publisher-confirm state: seq -> entry for the live connection,
        # plus the ordered set of entries not yet confirmed by any broker
        self._publish_seq = 0
        self._unconfirmed: Dict[int, _PendingPublish] = {}
        self._pending_publishes: Dict[_PendingPublish, None] = {}

        # in-flight content assembly (consumer_tag, delivery_tag, redelivered)
        self._pending_deliver: Optional[Tuple[str, int, bool]] = None
        self._pending_size = 0
        self._pending_chunks: List[bytes] = []
        self._pending_props: Optional[dict] = None

    # -- connection lifecycle -------------------------------------------

    async def connect(self) -> None:
        if self._connected.is_set() and not self._closing:
            # idempotent: a second connect() (e.g. Telemetry.connect after
            # the caller already connected the queue) must not stack a new
            # connection over the live one
            return
        delay = self._reconnect_initial
        attempt = 0
        while True:
            try:
                await self._establish()
                return
            except AccessRefused:
                raise
            except (ConnectionError, OSError, wire.ProtocolError,
                    asyncio.IncompleteReadError) as err:
                attempt += 1
                if (self._connect_attempts is not None
                        and attempt >= self._connect_attempts):
                    raise
                if self._logger is not None:
                    self._logger.warn(
                        "amqp connect failed, retrying", error=repr(err),
                        attempt=attempt)
                await asyncio.sleep(delay)
                delay = min(delay * 2, self._reconnect_max)

    async def _establish(self) -> None:
        p = self._params
        ssl_ctx = None
        if p.get("tls"):
            import ssl as ssl_mod

            ssl_ctx = self._ssl_context or ssl_mod.create_default_context()
        reader, writer = await asyncio.open_connection(
            p["host"], p["port"], ssl=ssl_ctx
        )
        try:
            await self._handshake(reader, writer)
        except BaseException:
            writer.close()
            raise
        self._reader, self._writer = reader, writer
        self._epoch += 1
        self._declared.clear()
        self._declared_exchanges.clear()
        self._publish_seq = 0
        self._unconfirmed.clear()
        self._last_recv = time.monotonic()
        self._read_task = asyncio.create_task(self._read_loop())
        if self._heartbeat:
            self._heartbeat_task = asyncio.create_task(self._heartbeat_loop())
        self._connected.set()
        # re-establish exchange bindings (exclusive tap queues died with
        # the old connection and must be re-created before re-binding)
        for queue, exchange, exclusive in list(self._bindings):
            await self._ensure_exchange(exchange)
            await self._ensure_queue(queue, exclusive=exclusive)
            await self._send_bind(queue, exchange)
        # restore consumers on a fresh connection
        if self._consuming:
            for sub in list(self._subscriptions.values()):
                await self._start_consumer(sub)
        # resend publishes the dead connection never confirmed
        for entry in list(self._pending_publishes):
            if not entry.fut.done():
                await self._send_publish(entry)

    async def _handshake(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        p = self._params
        writer.write(wire.PROTOCOL_HEADER)
        await writer.drain()

        async def expect(method: Tuple[int, int]) -> List[Any]:
            while True:
                ftype, _channel, payload = await wire.read_frame(reader)
                if ftype == wire.FRAME_HEARTBEAT:
                    continue
                if ftype != wire.FRAME_METHOD:
                    raise wire.ProtocolError(f"expected method frame, got {ftype}")
                got, args = wire.decode_method(payload)
                if got == wire.CONNECTION_CLOSE:
                    # close during handshake = refusal (403/530): permanent
                    raise AccessRefused(
                        f"server closed connection: {args[0]} {args[1]}")
                if got != method:
                    raise wire.ProtocolError(f"expected {method}, got {got}")
                return args

        await expect(wire.CONNECTION_START)
        client_props = {
            "product": "downloader-tpu",
            "capabilities": {"basic.nack": True, "consumer_cancel_notify": True},
        }
        response = f"\0{p['user']}\0{p['password']}"
        writer.write(wire.encode_method(
            0, wire.CONNECTION_START_OK, client_props, "PLAIN", response, "en_US"))
        await writer.drain()

        _ch_max, frame_max, hb = await expect(wire.CONNECTION_TUNE)
        self._frame_max = min(frame_max or DEFAULT_FRAME_MAX, DEFAULT_FRAME_MAX)
        # 0 from either side disables heartbeats (RabbitMQ negotiation rule)
        if hb and self._want_heartbeat:
            self._heartbeat = min(hb, self._want_heartbeat)
        else:
            self._heartbeat = 0
        writer.write(wire.encode_method(
            0, wire.CONNECTION_TUNE_OK, 1, self._frame_max, self._heartbeat))
        writer.write(wire.encode_method(
            0, wire.CONNECTION_OPEN, p["vhost"], "", False))
        await writer.drain()
        await expect(wire.CONNECTION_OPEN_OK)

        writer.write(wire.encode_method(self.CHANNEL, wire.CHANNEL_OPEN, ""))
        await writer.drain()
        await expect(wire.CHANNEL_OPEN_OK)

        # confirm mode: the broker acks every publish, so lost connections
        # can't silently drop messages (we resend unconfirmed ones)
        writer.write(wire.encode_method(self.CHANNEL, wire.CONFIRM_SELECT, False))
        await writer.drain()
        await expect(wire.CONFIRM_SELECT_OK)

    def _connection_lost(self, exc: Optional[BaseException]) -> None:
        if not self._connected.is_set() and self._reconnect_task:
            return
        self._connected.clear()
        if self._writer is not None:
            self._writer.close()
        if self._heartbeat_task:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        if self._pending_rpc is not None:
            _method, fut = self._pending_rpc
            if not fut.done():
                fut.set_exception(exc or ConnectionError("connection lost"))
            self._pending_rpc = None
        self._pending_deliver = None
        self._pending_chunks = []
        self._pending_props = None
        # stale per-connection confirm tags; the entries themselves stay in
        # _pending_publishes and are resent once reconnected
        self._unconfirmed.clear()
        if not self._closing and self._reconnect_task is None:
            if self._logger is not None:
                self._logger.warn("amqp connection lost, reconnecting",
                                  error=repr(exc) if exc else None)
            self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        delay = self._reconnect_initial
        while not self._closing:
            try:
                await self._establish()
            except asyncio.CancelledError:
                raise
            except Exception as err:
                if self._logger is not None:
                    self._logger.warn("amqp reconnect failed", error=repr(err))
                await asyncio.sleep(delay)
                delay = min(delay * 2, self._reconnect_max)
            else:
                self._reconnect_task = None
                return

    async def stop_consuming(self) -> None:
        self._consuming = False
        if not self._connected.is_set():
            return
        for sub in list(self._subscriptions.values()):
            try:
                await self._rpc(
                    wire.encode_method(
                        self.CHANNEL, wire.BASIC_CANCEL, sub.consumer_tag, False),
                    wire.BASIC_CANCEL_OK,
                )
            except (ConnectionError, OSError):
                # connection is gone: nothing is being consumed, and
                # _consuming=False keeps the reconnect loop from
                # restoring the subscriptions
                break
            # wire.ProtocolError / TimeoutError propagate: the consumer
            # may still be live on a healthy connection, and a caller
            # (intake pause / drain) must not report intake stopped when
            # it wasn't — a retry re-issues the cancels idempotently

    async def resume_consuming(self) -> None:
        """Re-issue basic.consume for every registered subscription
        (control-plane intake resume after :meth:`stop_consuming`).

        The subscriptions table survives the pause, so the same queues /
        handlers / qos come back; while disconnected, flipping
        ``_consuming`` is enough — the reconnect loop restores consumers
        on the next connection.

        Deliberately RE-ENTRANT: each subscription is basic.cancel'd
        (a no-op for a tag the broker doesn't know) before its consume,
        so a resume that half-failed on a slow broker can simply be
        retried — without this, a first attempt dying between the
        ``_consuming`` flip and the consume would make every retry a
        silent no-op and leave intake dead until the next reconnect.
        """
        if self._closing:
            raise RuntimeError("resume on closed queue connection")
        self._consuming = True
        if not self._connected.is_set():
            return  # reconnect loop restores consumers on connect
        for sub in list(self._subscriptions.values()):
            try:
                await self._rpc(
                    wire.encode_method(
                        self.CHANNEL, wire.BASIC_CANCEL,
                        sub.consumer_tag, False),
                    wire.BASIC_CANCEL_OK,
                )
                await self._start_consumer(sub)
            except (ConnectionError, OSError):
                return  # connection died: reconnect restores everything
            # wire.ProtocolError / TimeoutError propagate: the caller's
            # retry re-runs the cancel+consume pair idempotently

    async def close(self) -> None:
        self._closing = True
        if self._reconnect_task:
            self._reconnect_task.cancel()
            try:
                await self._reconnect_task
            except (asyncio.CancelledError, Exception):
                pass
            self._reconnect_task = None
        for task in list(self._handlers):
            task.cancel()
        for task in list(self._handlers):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._handlers.clear()
        if self._connected.is_set() and self._writer is not None:
            try:
                await self._rpc(
                    wire.encode_method(
                        0, wire.CONNECTION_CLOSE, 200, "bye", 0, 0),
                    wire.CONNECTION_CLOSE_OK,
                    timeout=2.0,
                )
            except (ConnectionError, wire.ProtocolError, asyncio.TimeoutError):
                pass
        self._connected.clear()
        for entry in list(self._pending_publishes):
            if not entry.fut.done():
                entry.fut.set_exception(
                    ConnectionError("connection closed before publish confirm"))
        self._pending_publishes.clear()
        self._unconfirmed.clear()
        for task in (self._read_task, self._heartbeat_task):
            if task:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        self._read_task = self._heartbeat_task = None
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._reader = self._writer = None

    # -- read loop & dispatch -------------------------------------------

    async def _read_loop(self) -> None:
        try:
            while True:
                ftype, channel, payload = await wire.read_frame(self._reader)
                self._last_recv = time.monotonic()
                if ftype == wire.FRAME_HEARTBEAT:
                    continue
                if ftype == wire.FRAME_METHOD:
                    self._on_method(channel, payload)
                elif ftype == wire.FRAME_HEADER:
                    _size, _props = wire.decode_content_header(payload)
                    self._pending_size = _size
                    self._pending_props = _props
                    self._pending_chunks = []
                    if _size == 0:
                        self._dispatch_delivery()
                elif ftype == wire.FRAME_BODY:
                    self._pending_chunks.append(payload)
                    if sum(map(len, self._pending_chunks)) >= self._pending_size:
                        self._dispatch_delivery()
        except asyncio.CancelledError:
            raise
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                wire.ProtocolError) as err:
            self._connection_lost(err)

    def _on_method(self, channel: int, payload: bytes) -> None:
        method, args = wire.decode_method(payload)
        if method == wire.BASIC_DELIVER:
            consumer_tag, delivery_tag, redelivered, _exchange, _rk = args
            self._pending_deliver = (consumer_tag, delivery_tag, redelivered)
            return
        if method == wire.BASIC_ACK:
            self._confirm(args[0], args[1], ok=True)
            return
        if method == wire.BASIC_NACK:
            self._confirm(args[0], args[1], ok=False)
            return
        if method in (wire.CONNECTION_CLOSE, wire.CHANNEL_CLOSE):
            # server-initiated close: acknowledge, then treat as lost
            reply = (wire.CONNECTION_CLOSE_OK if method == wire.CONNECTION_CLOSE
                     else wire.CHANNEL_CLOSE_OK)
            if self._writer is not None:
                self._writer.write(wire.encode_method(channel, reply))
            raise ConnectionError(f"server closed: {args[1]!r}")
        if self._pending_rpc is not None and method == self._pending_rpc[0]:
            _method, fut = self._pending_rpc
            self._pending_rpc = None
            if not fut.done():
                fut.set_result(args)
            return
        # unsolicited but harmless (e.g. basic.cancel-ok after a race)

    def _dispatch_delivery(self) -> None:
        if self._pending_deliver is None:
            self._pending_chunks = []
            return
        consumer_tag, delivery_tag, redelivered = self._pending_deliver
        body = b"".join(self._pending_chunks)
        props = self._pending_props or {}
        self._pending_deliver = None
        self._pending_chunks = []
        self._pending_props = None
        sub = self._subscriptions.get(consumer_tag)
        if sub is None:
            # delivery for a cancelled consumer: requeue it
            asyncio.ensure_future(
                self._settle(delivery_tag, self._epoch, ack=False, requeue=True))
            return
        delivery = _AmqpDelivery(self, delivery_tag, self._epoch, body,
                                 redelivered, headers=props.get("headers"))

        async def _run() -> None:
            try:
                await sub.handler(delivery)
            except asyncio.CancelledError:
                await delivery.nack(requeue=True)
                raise
            except Exception:
                # crashed handler: redeliver, like a channel close would
                await delivery.nack(requeue=True)

        task = asyncio.create_task(_run())
        self._handlers.add(task)
        task.add_done_callback(self._handlers.discard)

    async def _heartbeat_loop(self) -> None:
        interval = max(self._heartbeat / 2.0, 0.01)
        frame = wire.encode_frame(wire.FRAME_HEARTBEAT, 0, b"")
        while True:
            await asyncio.sleep(interval)
            if time.monotonic() - self._last_recv > 2 * self._heartbeat:
                # peer went silent: drop the transport; the read loop's error
                # path owns reconnection
                if self._writer is not None:
                    self._writer.close()
                return
            try:
                self._writer.write(frame)
                await self._writer.drain()
            except (ConnectionError, OSError):
                return

    # -- RPC & sends -----------------------------------------------------

    async def _rpc(self, frame: bytes, expect: Tuple[int, int],
                   timeout: float = RPC_TIMEOUT) -> List[Any]:
        async with self._rpc_lock:
            if self._writer is None:
                raise ConnectionError("not connected")
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending_rpc = (expect, fut)
            self._writer.write(frame)
            await self._writer.drain()
            return await asyncio.wait_for(fut, timeout)

    async def _ensure_queue(self, queue: str, exclusive: bool = False) -> None:
        if queue in self._declared:
            return
        # exclusive queues (telemetry taps) are transient: not durable,
        # auto-deleted with the connection; work queues are durable
        await self._rpc(
            wire.encode_method(
                self.CHANNEL, wire.QUEUE_DECLARE,
                0, queue, False, not exclusive, exclusive, exclusive,
                False, None),
            wire.QUEUE_DECLARE_OK,
        )
        self._declared.add(queue)

    async def _ensure_exchange(self, exchange: str) -> None:
        if exchange in self._declared_exchanges:
            return
        await self._rpc(
            wire.encode_method(
                self.CHANNEL, wire.EXCHANGE_DECLARE,
                0, exchange, "fanout", False, True, False, False, False,
                None),
            wire.EXCHANGE_DECLARE_OK,
        )
        self._declared_exchanges.add(exchange)

    async def _send_bind(self, queue: str, exchange: str) -> None:
        await self._rpc(
            wire.encode_method(
                self.CHANNEL, wire.QUEUE_BIND,
                0, queue, exchange, "", False, None),
            wire.QUEUE_BIND_OK,
        )

    async def bind_queue(self, queue: str, exchange: str,
                         exclusive: bool = False) -> None:
        """Declare a fanout ``exchange`` and bind ``queue`` to it (declaring
        the queue too; ``exclusive`` makes it a transient per-connection tap
        queue).  Bindings are replayed after a reconnect."""
        if self._closing:
            raise RuntimeError("bind on closed queue connection")
        await self._connected.wait()
        await self._ensure_exchange(exchange)
        await self._ensure_queue(queue, exclusive=exclusive)
        await self._send_bind(queue, exchange)
        entry = (queue, exchange, exclusive)
        if entry not in self._bindings:
            self._bindings.append(entry)

    async def _settle(self, delivery_tag: int, epoch: int, ack: bool,
                      requeue: bool = True) -> None:
        if epoch != self._epoch or not self._connected.is_set():
            # the delivery's connection is gone; the broker already requeued
            # every unacked message on that channel
            return
        if ack:
            frame = wire.encode_method(
                self.CHANNEL, wire.BASIC_ACK, delivery_tag, False)
        else:
            frame = wire.encode_method(
                self.CHANNEL, wire.BASIC_NACK, delivery_tag, False, requeue)
        try:
            self._writer.write(frame)
            await self._writer.drain()
        except (ConnectionError, OSError):
            pass

    # -- MessageQueue surface -------------------------------------------

    def _confirm(self, delivery_tag: int, multiple: bool, ok: bool) -> None:
        """Resolve publisher-confirm futures for an incoming (n)ack."""
        tags = ([t for t in self._unconfirmed if t <= delivery_tag]
                if multiple else [delivery_tag])
        for tag in tags:
            entry = self._unconfirmed.pop(tag, None)
            if entry is None:
                continue
            self._pending_publishes.pop(entry, None)
            if entry.fut.done():
                continue
            if ok:
                entry.fut.set_result(None)
            else:
                entry.fut.set_exception(
                    ConnectionError("broker rejected publish (basic.nack)"))

    async def _send_publish(self, entry: _PendingPublish) -> None:
        if entry.exchange:
            await self._ensure_exchange(entry.exchange)
        else:
            await self._ensure_queue(entry.queue)
        props: dict = {"delivery_mode": 2}
        if entry.headers:
            props["headers"] = entry.headers
        frames = [
            wire.encode_method(
                self.CHANNEL, wire.BASIC_PUBLISH,
                0, entry.exchange, entry.queue, False, False),
            wire.encode_content_header(
                self.CHANNEL, len(entry.body), props),
        ]
        frames.extend(
            wire.encode_body_frames(self.CHANNEL, entry.body, self._frame_max))
        async with self._send_lock:
            self._publish_seq += 1
            self._unconfirmed[self._publish_seq] = entry
            self._writer.write(b"".join(frames))
            await self._writer.drain()

    async def _publish_entry(self, entry: _PendingPublish) -> None:
        if self._closing:
            raise RuntimeError("publish on closed queue connection")
        await self._connected.wait()
        self._pending_publishes[entry] = None
        try:
            await self._send_publish(entry)
        except (ConnectionError, OSError, EOFError):
            # connection died mid-send (possibly before the read loop
            # noticed): _establish resends everything unconfirmed, so just
            # fall through to waiting on the confirm.  Worst case is a
            # duplicate publish — at-least-once, like the broker's delivery.
            # EOFError covers asyncio.IncompleteReadError: a drop DURING a
            # declare/bind RPC surfaces through the rpc future as the read
            # loop's readexactly EOF, not as a ConnectionError — it used
            # to escape here and fail a publish a reconnect would repair.
            if self._closing:
                self._pending_publishes.pop(entry, None)
                raise
        except BaseException:
            # anything a reconnect can't repair (e.g. RPC timeout on a live
            # connection) must surface, not hang on a confirm that will
            # never arrive
            self._pending_publishes.pop(entry, None)
            raise
        await entry.fut

    async def publish(self, queue: str, body: bytes,
                      headers: Optional[dict] = None) -> None:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._publish_entry(
            _PendingPublish(queue, body, fut, headers=headers))

    async def publish_exchange(self, exchange: str, body: bytes,
                               headers: Optional[dict] = None) -> None:
        """Publish to a fanout exchange: every bound queue gets a copy."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._publish_entry(
            _PendingPublish("", body, fut, exchange=exchange,
                            headers=headers)
        )

    async def listen(self, queue: str, handler: Handler, prefetch: int = 1) -> None:
        if self._closing:
            raise RuntimeError("listen on closed queue connection")
        await self._connected.wait()
        self._next_tag += 1
        sub = _Subscription(queue, handler, prefetch, f"ctag-{self._next_tag}")
        self._subscriptions[sub.consumer_tag] = sub
        self._consuming = True
        try:
            await self._start_consumer(sub)
        except (ConnectionError, OSError, EOFError):
            # EOFError = IncompleteReadError from a drop mid-RPC, same
            # repairable case as the publish path
            if self._closing:
                raise
            # the subscription is registered: the reconnect loop will
            # re-issue declare/qos/consume on the next connection
        except BaseException:
            # a failure a reconnect won't repair: unregister and surface
            self._subscriptions.pop(sub.consumer_tag, None)
            raise

    async def _start_consumer(self, sub: _Subscription) -> None:
        await self._ensure_queue(sub.queue)
        await self._rpc(
            wire.encode_method(
                self.CHANNEL, wire.BASIC_QOS, 0, sub.prefetch, False),
            wire.BASIC_QOS_OK,
        )
        await self._rpc(
            wire.encode_method(
                self.CHANNEL, wire.BASIC_CONSUME,
                0, sub.queue, sub.consumer_tag, False, False, False, False, None),
            wire.BASIC_CONSUME_OK,
        )
