"""Queue interface: the AMQP surface the pipeline actually uses.

Maps one-to-one onto the reference's ``triton-core/amqp`` usage:
``new AMQP(addr, 1, 2, prom); connect(); listen('v1.download', processor);
publish('v1.convert', encoded); close()``
(/root/reference/lib/main.js:46-47,164,172,200) with ``rmsg.ack()`` /
``rmsg.nack()`` settlement (/root/reference/lib/main.js:145-150,168).
Delivery is at-least-once: a nacked message is redelivered.
"""

from __future__ import annotations

import abc
from typing import Awaitable, Callable, Optional

Handler = Callable[["Delivery"], Awaitable[None]]


class Delivery(abc.ABC):
    """A single queue delivery awaiting settlement.

    The reference handler receives ``rmsg`` with ``rmsg.message.content``
    (bytes) and ``ack``/``nack`` methods (/root/reference/lib/main.js:63,168).
    """

    __slots__ = ()

    @property
    @abc.abstractmethod
    def body(self) -> bytes:
        """Raw message payload."""

    @property
    @abc.abstractmethod
    def redelivered(self) -> bool:
        """True if this message was previously delivered and nacked."""

    @property
    def headers(self) -> dict:
        """Application headers published with the message (AMQP basic
        properties ``headers`` table).  The pipeline uses them to carry
        W3C trace context (``traceparent``) across queue hops — the
        cross-service propagation triton's design provides for
        (/root/reference/lib/main.js:20 imports the tracer's serialize/
        unserialize) but the reference never wired up."""
        return {}

    @abc.abstractmethod
    async def ack(self) -> None:
        """Settle successfully; the broker drops the message."""

    @abc.abstractmethod
    async def nack(self, requeue: bool = True) -> None:
        """Settle unsuccessfully; with ``requeue`` the broker redelivers."""


class MessageQueue(abc.ABC):
    """A connection to a message broker."""

    @abc.abstractmethod
    async def connect(self) -> None:
        """Establish the connection (reference lib/main.js:47)."""

    @abc.abstractmethod
    async def close(self) -> None:
        """Tear down the connection and cancel consumers
        (reference lib/main.js:200)."""

    @abc.abstractmethod
    async def stop_consuming(self) -> None:
        """Stop pulling new deliveries but let in-flight handlers finish.

        Used by graceful shutdown: drain-then-close instead of cancelling
        handlers mid-stage."""

    async def resume_consuming(self) -> None:
        """Re-start the consumers registered via :meth:`listen` after a
        :meth:`stop_consuming` (control-plane intake pause/resume).

        Optional capability: the bundled backends implement it; the
        default raises so a backend that silently dropped subscriptions
        can't fake a resume."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support resuming consumers"
        )

    @abc.abstractmethod
    async def publish(self, queue: str, body: bytes,
                      headers: Optional[dict] = None) -> None:
        """Enqueue ``body`` onto ``queue`` (reference lib/main.js:164),
        optionally with application headers (e.g. ``traceparent``)."""

    @abc.abstractmethod
    async def listen(self, queue: str, handler: Handler, prefetch: int = 1) -> None:
        """Consume ``queue``, invoking ``handler`` per delivery.

        ``prefetch`` bounds in-flight unsettled deliveries per consumer
        (the reference passes prefetch params ``(1, 2)`` to its AMQP
        constructor, lib/main.js:46).
        """

    # -- fanout (optional capability) -----------------------------------
    # Work queues split deliveries among consumers; telemetry wants every
    # interested party to see every event.  Backends that support it
    # expose fanout exchanges: publish_exchange copies to all bound
    # queues; bind_queue attaches a (possibly exclusive/transient) queue.

    async def publish_exchange(self, exchange: str, body: bytes,
                               headers: Optional[dict] = None) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not support fanout exchanges"
        )

    async def bind_queue(self, queue: str, exchange: str,
                         exclusive: bool = False) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not support fanout exchanges"
        )
