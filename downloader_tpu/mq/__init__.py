"""Message-queue abstraction.

The reference's "distributed communication backend" is RabbitMQ via
``triton-core/amqp`` (SURVEY.md §5).  This package defines the exact queue
surface the reference consumes — ``connect`` / ``listen`` / ``publish`` /
``close`` with per-message ``ack``/``nack`` and consumer prefetch
(/root/reference/lib/main.js:46-47,145-150,164,172,200) — plus a hermetic
in-process broker so the whole pipeline is testable without a RabbitMQ
server (the reference's biggest test gap, SURVEY.md §4).
"""

from .base import Delivery, MessageQueue
from .memory import InMemoryBroker, MemoryQueue

__all__ = [
    "Delivery",
    "MessageQueue",
    "InMemoryBroker",
    "MemoryQueue",
    "new_queue",
    "resolve_backend",
]


def resolve_backend(config) -> str:
    """Resolve the configured queue backend name (``memory`` default)."""
    mq_cfg = config.get("rabbitmq") if config is not None else None
    if mq_cfg is None:
        return "memory"
    return mq_cfg.get("backend", "memory")


def new_queue(config, broker=None, logger=None) -> MessageQueue:
    """Build a broker connection from config.

    Capability-equivalent to ``new AMQP(dyn('rabbitmq'), 1, 2, prom)``
    (/root/reference/lib/main.js:46): the backend is selected by
    ``config.rabbitmq.backend`` — ``memory`` (default, hermetic; pass a
    shared :class:`InMemoryBroker`) or ``amqp`` (a real AMQP 0-9-1
    connection to the address resolved by ``dyn('rabbitmq')``).

    The reference opens separate connections for jobs and telemetry
    (lib/main.js:46-50); call this once per connection.

    An explicitly injected ``broker`` always wins over config — tests and
    benchmarks that hand in a hermetic broker must never end up on real
    sockets because of ambient configuration.
    """
    if broker is not None:
        return MemoryQueue(broker)
    backend = resolve_backend(config)
    if backend == "memory":
        return MemoryQueue(InMemoryBroker())
    if backend == "amqp":
        from ..platform.config import dyn
        from .amqp import AmqpQueue

        return AmqpQueue(dyn("rabbitmq", config), logger=logger)
    raise ValueError(f"unknown queue backend {backend!r}")
