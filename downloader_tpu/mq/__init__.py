"""Message-queue abstraction.

The reference's "distributed communication backend" is RabbitMQ via
``triton-core/amqp`` (SURVEY.md §5).  This package defines the exact queue
surface the reference consumes — ``connect`` / ``listen`` / ``publish`` /
``close`` with per-message ``ack``/``nack`` and consumer prefetch
(/root/reference/lib/main.js:46-47,145-150,164,172,200) — plus a hermetic
in-process broker so the whole pipeline is testable without a RabbitMQ
server (the reference's biggest test gap, SURVEY.md §4).
"""

from .base import Delivery, MessageQueue
from .memory import InMemoryBroker, MemoryQueue

__all__ = ["Delivery", "MessageQueue", "InMemoryBroker", "MemoryQueue"]
