"""Operator CLI: submit jobs and build torrents without writing a client.

The reference service is driven purely by other services publishing
protobuf ``api.Download`` messages onto ``v1.download``
(/root/reference/lib/main.js:172); operators had no tool to enqueue a job
by hand.  This closes that gap:

    python -m downloader_tpu.cli submit --id my-movie --name "My Movie" \
        --type MOVIE --source http --uri http://host/movie.mkv [--wait]
    python -m downloader_tpu.cli mktorrent /path/to/media \
        --tracker http://tracker:8000/announce --out media.torrent
    python -m downloader_tpu.cli magnet media.torrent
    python -m downloader_tpu.cli scrape media.torrent
    python -m downloader_tpu.cli status [--url http://host:3401]
    python -m downloader_tpu.cli jobs list|show ID|events ID|cancel ID \
        [--url ...]
    python -m downloader_tpu.cli fleet list|show WORKER [--url ...]
    python -m downloader_tpu.cli tenants [--url ...] [--json]
    python -m downloader_tpu.cli debug tasks|stacks [--url ...]
    python -m downloader_tpu.cli scrub [--json] [--local-only]
    python -m downloader_tpu.cli watch [--id my-movie]
    python -m downloader_tpu.cli upscale in.y4m out.y4m [--checkpoint-dir D]
    python -m downloader_tpu.cli train --data media/ --steps 500 \
        --checkpoint-dir ckpt/

``upscale``/``train`` drive the TPU compute surface directly (the same
code the config-gated ``upscale`` pipeline stage runs): batch-upscale a
Y4M file, or fit the upscaler on Y4M media self-supervised (HR crops
vs box-downsampled LR inputs) with orbax checkpoints the stage loads.

``submit``/``watch`` talk to the queue backend named in config (AMQP in
production; they refuse the in-memory backend, which cannot reach a
running service in another process).  ``--wait`` and ``watch`` tap the
fanout exchanges, so they observe without stealing deliveries from the
service's real consumers.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from . import schemas
from .platform.config import load_config
from .platform.logging import get_logger


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="downloader-tpu",
        description="Operator tools for the downloader staging service",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    submit = sub.add_parser("submit", help="enqueue one Download job")
    submit.add_argument("--id", required=True, help="media/job id")
    submit.add_argument("--name", required=True, help="media display name")
    submit.add_argument("--creator-id", default="cli",
                        help="creator/card id used in telemetry")
    submit.add_argument(
        "--type", default="MOVIE", type=str.upper,
        choices=list(schemas.MediaType.keys()),
    )
    submit.add_argument(
        "--source", default="HTTP", type=str.upper,
        choices=list(schemas.SourceType.keys()),
    )
    submit.add_argument("--uri", required=True,
                        help="magnet:, http(s)://, file://, or bucket:// URI")
    submit.add_argument(
        "--priority", default="NORMAL", type=str.upper,
        choices=list(schemas.JobPriority.keys()),
        help="scheduling class: HIGH starts before NORMAL before BULK "
             "when the service's run slots are contended",
    )
    submit.add_argument(
        "--tenant", default="",
        help="tenant identity for the service's weighted-fair scheduler "
             "and per-tenant quotas (absent/unknown = 'default')",
    )
    submit.add_argument(
        "--ttl", type=float, default=0.0, metavar="SECONDS",
        help="optional deadline from receipt: expired BULK jobs are "
             "dropped (EXPIRED), expired HIGH/NORMAL jobs are flagged "
             "but still run (0 = no deadline)",
    )
    submit.add_argument(
        "--mirror", action="append", default=[], metavar="URL",
        help="redundant origin for the SAME entity (repeatable): http(s) "
             "mirror URLs the racing fetcher spreads byte ranges across "
             "(per-origin breakers, straggler duplication, failover), or "
             "extra webseeds for a torrent source",
    )
    submit.add_argument(
        "--source-kind", default="AUTO", type=str.upper,
        choices=list(schemas.SourceKind.keys()),
        help="how the source URI is interpreted: AUTO (historical "
             "dispatch on --source), DIRECT (whole-entity fetch), or "
             "MANIFEST (HLS-style media playlist ingested segment by "
             "segment, live or VOD)",
    )
    submit.add_argument("--queue", default=schemas.DOWNLOAD_QUEUE)
    submit.add_argument("--wait", action="store_true",
                        help="tap telemetry and block until the job's "
                             "Convert message confirms completion")
    submit.add_argument("--wait-timeout", type=float, default=600.0,
                        help="seconds before --wait gives up (exit 124; "
                             "stall-dropped jobs emit no terminal event)")

    mk = sub.add_parser("mktorrent", help="build a .torrent from a path")
    mk.add_argument("path", help="file or directory to seed")
    mk.add_argument("--tracker", action="append", default=[],
                    help="announce URL (repeatable)")
    mk.add_argument("--webseed", action="append", default=[],
                    help="BEP 19 HTTP seed URL (repeatable)")
    def _piece_length(value: str) -> int:
        n = int(value)
        if n < (1 << 14):
            raise argparse.ArgumentTypeError(
                "piece length must be >= 16384 (BEP 3 block size)"
            )
        return n

    mk.add_argument("--piece-length", type=_piece_length, default=1 << 18)
    mk.add_argument("--out", required=True, help="output .torrent path")

    mag = sub.add_parser("magnet", help="print the magnet link of a .torrent")
    mag.add_argument("torrent", help=".torrent file path")

    scrape = sub.add_parser(
        "scrape", help="swarm stats (seeders/leechers) for a .torrent"
    )
    scrape.add_argument("torrent", help=".torrent file path")

    status = sub.add_parser(
        "status", help="query a running service's /health and key metrics"
    )
    status.add_argument("--url", default="http://127.0.0.1:3401",
                        help="service base URL (default local health port)")

    jobs = sub.add_parser(
        "jobs", help="list/inspect/cancel jobs via a service's admin API"
    )
    jobs_sub = jobs.add_subparsers(dest="jobs_command", required=True)

    def _jobs_common(p):
        p.add_argument("--url", default="http://127.0.0.1:3401",
                       help="service base URL (default local health port)")
        p.add_argument("--token", default=None,
                       help="bearer token for mutating endpoints "
                            "(default: $CONTROL_TOKEN)")

    jobs_list = jobs_sub.add_parser("list", help="list live + recent jobs")
    _jobs_common(jobs_list)
    jobs_list.add_argument("--state", default=None,
                           help="filter by lifecycle state "
                                "(RECEIVED/ADMITTED/RUNNING/PARKED/"
                                "PUBLISHING/DONE/FAILED/CANCELLED/"
                                "DROPPED_POISON/EXPIRED)")
    jobs_list.add_argument("--recovered", action="store_true",
                           help="only jobs that survived a worker crash "
                                "(journal-replayed placeholders and "
                                "their adopting redeliveries)")

    jobs_show = jobs_sub.add_parser("show", help="one job's full record")
    _jobs_common(jobs_show)
    jobs_show.add_argument("id", help="media/job id")

    jobs_events = jobs_sub.add_parser(
        "events", help="one job's flight-recorder timeline (state "
                       "transitions, waits, throughput samples, cache/"
                       "retry/settle decisions, correlation ids)"
    )
    _jobs_common(jobs_events)
    jobs_events.add_argument("id", help="media/job id")
    jobs_events.add_argument("--json", action="store_true",
                             help="raw JSON instead of the timeline view "
                                  "(with --follow: one JSON object per "
                                  "new event)")
    jobs_events.add_argument("--follow", "-f", action="store_true",
                             help="live-tail: re-poll until the job "
                                  "reaches a terminal state, printing "
                                  "only new events (incident triage)")
    jobs_events.add_argument("--interval", type=float, default=1.0,
                             help="--follow poll interval in seconds "
                                  "(default 1)")

    jobs_cancel = jobs_sub.add_parser(
        "cancel", help="cooperatively cancel a job (settled, not requeued)"
    )
    _jobs_common(jobs_cancel)
    jobs_cancel.add_argument("id", help="media/job id")
    jobs_cancel.add_argument("--reason", default="cli",
                             help="recorded in the job's terminal state")

    fleet = sub.add_parser(
        "fleet", help="inspect the fleet coordination plane (workers, "
                      "liveness, content leases, shared-tier stats)"
    )
    fleet_sub = fleet.add_subparsers(dest="fleet_command", required=True)
    fleet_list = fleet_sub.add_parser(
        "list", help="live workers + every live content lease"
    )
    fleet_list.add_argument("--url", default="http://127.0.0.1:3401",
                            help="service base URL (default local health "
                                 "port)")
    fleet_list.add_argument("--json", action="store_true",
                            help="raw JSON instead of the table view")
    fleet_show = fleet_sub.add_parser(
        "show", help="one worker's latest heartbeat document (autoscale "
                     "signals, held leases, shared-tier stats)"
    )
    fleet_show.add_argument("id", help="worker id (see `fleet list`)")
    fleet_show.add_argument("--url", default="http://127.0.0.1:3401",
                            help="service base URL")
    fleet_top = fleet_sub.add_parser(
        "top", help="live-refreshing fleet overview console (GET "
                    "/v1/fleet/overview): members, queue depths, burn "
                    "rates, open breakers, routing decisions, tenant "
                    "queue shares, top hops, and the placement "
                    "controller's plan"
    )
    fleet_top.add_argument("--url", default="http://127.0.0.1:3401",
                           help="service base URL (any worker serves "
                                "the aggregated view)")
    fleet_top.add_argument("--interval", type=float, default=2.0,
                           help="refresh cadence, seconds (default 2)")
    fleet_top.add_argument("--once", action="store_true",
                           help="render one frame and exit (no screen "
                                "clearing — scriptable)")
    fleet_top.add_argument("--json", action="store_true",
                           help="raw JSON frames instead of the console "
                                "view (JSONL with --interval looping)")

    trace = sub.add_parser(
        "trace", help="cross-worker trace timelines (GET /v1/trace/{id}: "
                      "this worker's segments + peer digests + live "
                      "peer admin APIs, joined on one trace id)"
    )
    trace_sub = trace.add_subparsers(dest="trace_command", required=True)
    trace_show = trace_sub.add_parser(
        "show", help="one trace's assembled timeline: every worker's "
                     "events merged in wall-clock order, spans, hop "
                     "ledgers"
    )
    trace_show.add_argument("id", help="32-hex trace id (see `jobs show` "
                                       "traceId, or any log line)")
    trace_show.add_argument("--url", default="http://127.0.0.1:3401",
                            help="service base URL (default local health "
                                 "port)")
    trace_show.add_argument("--json", action="store_true",
                            help="raw JSON instead of the timeline view")
    trace_show.add_argument("--local", action="store_true",
                            help="this worker's view only (skip the "
                                 "coordination store and peer hops)")

    tenants = sub.add_parser(
        "tenants", help="tenancy + overload posture: per-tenant weights/"
                        "caps/quotas, live queue depth and slot "
                        "occupancy, saturation snapshot"
    )
    tenants.add_argument("--url", default="http://127.0.0.1:3401",
                         help="service base URL (default local health "
                              "port)")
    tenants.add_argument("--json", action="store_true",
                         help="raw JSON instead of the table view")

    incident = sub.add_parser(
        "incident", help="incident plane: export forensic bundles, "
                         "replay them as deterministic chaos scenarios, "
                         "diff breach signatures"
    )
    incident_sub = incident.add_subparsers(dest="incident_command",
                                           required=True)

    def _incident_common(p):
        p.add_argument("--url", default="http://127.0.0.1:3401",
                       help="service base URL (default local health port)")
        p.add_argument("--token", default=None,
                       help="bearer token for mutating endpoints "
                            "(default: $CONTROL_TOKEN)")

    incident_list = incident_sub.add_parser(
        "list", help="exported bundle summaries (GET /v1/incidents)")
    _incident_common(incident_list)
    incident_list.add_argument("--json", action="store_true",
                               help="raw JSON instead of the table view")

    incident_show = incident_sub.add_parser(
        "show", help="one full bundle by bundleId, job id, or trace id")
    _incident_common(incident_show)
    incident_show.add_argument("id", help="bundleId | job id | trace id")
    incident_show.add_argument("--out", default=None,
                               help="write the bundle JSON to a file "
                                    "instead of stdout")

    incident_export = incident_sub.add_parser(
        "export", help="snapshot a live/recent job into the ring now "
                       "(POST /v1/incidents/{id}/export, trigger=manual)")
    _incident_common(incident_export)
    incident_export.add_argument("id", help="job id | trace id")
    incident_export.add_argument("--out", default=None,
                                 help="also write the bundle JSON here")

    incident_replay = incident_sub.add_parser(
        "replay", help="compile a bundle into a deterministic chaos "
                       "scenario and run it on a fresh SoakRig fleet, "
                       "then diff breach signatures (same signature = "
                       "the incident reproduces)")
    _incident_common(incident_replay)
    incident_replay.add_argument(
        "id", nargs="?", default=None,
        help="bundleId | job id | trace id to pull from --url "
             "(or use --bundle)")
    incident_replay.add_argument("--bundle", default=None,
                                 help="read the bundle from a JSON file "
                                      "instead of the admin API")
    incident_replay.add_argument("--runs", type=int, default=1,
                                 help="consecutive replays; ALL must "
                                      "match (default 1; the bench's "
                                      "round-trip guard uses 2)")
    incident_replay.add_argument("--compile-only", action="store_true",
                                 help="print the compiled scenario and "
                                      "exit without running a fleet")
    incident_replay.add_argument("--no-report", action="store_true",
                                 help="skip POSTing the verdict back to "
                                      "--url (/v1/incidents/verdict)")

    incident_diff = incident_sub.add_parser(
        "diff", help="compare the breach signatures of two bundle JSON "
                     "files (exit 0 = same signature)")
    incident_diff.add_argument("original", help="bundle JSON file")
    incident_diff.add_argument("replay", help="bundle JSON file")

    debug = sub.add_parser(
        "debug", help="runtime introspection against a running service"
    )
    debug_sub = debug.add_subparsers(dest="debug_command", required=True)
    debug_tasks = debug_sub.add_parser(
        "tasks", help="live asyncio tasks + event-loop lag stats"
    )
    debug_tasks.add_argument("--url", default="http://127.0.0.1:3401",
                             help="service base URL")
    debug_stacks = debug_sub.add_parser(
        "stacks", help="every thread's and task's current stack "
                       "(the SIGUSR1 dump, over HTTP)"
    )
    debug_stacks.add_argument("--url", default="http://127.0.0.1:3401",
                              help="service base URL")

    scrub = sub.add_parser(
        "scrub", help="run one integrity scrub pass over the local store "
                      "(cache entries, co-located shared tier, staged "
                      "workdir outputs) and print the verdict counts"
    )
    scrub.add_argument("--json", action="store_true", dest="as_json",
                       help="machine-readable verdict counts")
    scrub.add_argument(
        "--local-only", action="store_true",
        help="skip the shared tier entirely: no shared-tier scan and no "
             "repairs from it (mismatched cache entries quarantine "
             "instead); use when the store is unreachable from here")

    watch = sub.add_parser(
        "watch", help="tail job status/progress telemetry from the queue"
    )
    watch.add_argument("--id", default=None,
                       help="only show events for this media id")
    watch.add_argument("--count", type=int, default=0,
                       help="exit after N events (0 = run until ^C)")

    upscale = sub.add_parser(
        "upscale", help="upscale Y4M (or, with --decode, any container "
                        "an external decoder reads) through the TPU model"
    )
    upscale.add_argument("src", help="input .y4m path (any container "
                                     "with --decode)")
    upscale.add_argument("dst", help="output .y4m path (2x dimensions)")
    upscale.add_argument("--checkpoint-dir", default=None,
                         help="orbax checkpoint dir with trained params "
                              "(default: random init)")
    upscale.add_argument("--batch", type=int, default=8,
                         help="frames per device dispatch")
    upscale.add_argument("--decode", action="store_true",
                         help="pipe src through the decoder's "
                              "yuv4mpegpipe output first")
    upscale.add_argument("--decoder", default=None,
                         help="decoder binary (implies --decode; "
                              "default ffmpeg)")
    upscale.add_argument("--encode", action="store_true",
                         help="pipe the upscaled y4m through an encoder "
                              "into dst (compressed container out)")
    upscale.add_argument("--encoder", default=None,
                         help="encoder binary (implies --encode; "
                              "default ffmpeg)")
    upscale.add_argument("--encode-arg", action="append", default=None,
                         metavar="ARG", dest="encode_args",
                         help="encoder args before the output path "
                              "(repeatable; REPLACES the default set "
                              "'-c:v libx264 -preset veryfast -crf 18', "
                              "so restate what you still want)")

    train = sub.add_parser(
        "train", help="fit the upscaler on Y4M media (self-supervised SR)"
    )
    train.add_argument("--data", required=True,
                       help=".y4m file or directory of .y4m files")
    train.add_argument("--steps", type=int, default=200)
    train.add_argument("--batch", type=int, default=8)
    train.add_argument("--crop", type=int, default=64,
                       help="high-res crop edge (LR input is crop/scale)")
    train.add_argument("--lr", type=float, default=1e-3,
                       help="adam learning rate")
    train.add_argument("--checkpoint-dir", default=None,
                       help="orbax dir to save to / resume from")
    train.add_argument("--save-every", type=int, default=100)
    train.add_argument("--model-axis", type=int, default=1,
                       help="tensor-parallel axis size on multi-device")
    train.add_argument("--seed", type=int, default=0)
    train.add_argument("--scale", type=int, default=2,
                       help="upscale factor (match instance.upscale.scale)")
    train.add_argument("--features", type=int, default=128,
                       help="conv width (match instance.upscale.features)")
    train.add_argument("--depth", type=int, default=4,
                       help="conv layers (match instance.upscale.depth)")

    return parser


async def _submit(args) -> int:
    from .mq import new_queue, resolve_backend

    config = load_config("converter")
    logger = get_logger("downloader-cli")
    if resolve_backend(config) == "memory":
        print(
            "config selects the in-memory queue backend, which lives and "
            "dies inside one process — a running service cannot see this "
            "submission. Configure `rabbitmq: {backend: amqp}` first.",
            file=sys.stderr,
        )
        return 2
    msg = schemas.Download(
        media=schemas.Media(
            id=args.id,
            creator_id=args.creator_id,
            name=args.name,
            type=schemas.MediaType.Value(args.type),
            source=schemas.SourceType.Value(args.source),
            source_uri=args.uri,
        ),
        priority=schemas.JobPriority.Value(args.priority),
        tenant=args.tenant,
        ttl_seconds=max(args.ttl, 0.0),
        source_kind=schemas.SourceKind.Value(args.source_kind),
    )
    msg.mirrors.extend(args.mirror)
    from .platform.tracing import format_traceparent, init_tracer

    tracer = init_tracer("downloader-cli", logger, config)
    mq = new_queue(config, logger=logger)
    await mq.connect()
    try:
        # the submit span's context rides the message headers, so the
        # service's job span (and the downstream Convert) parent to it —
        # one trace across processes (VERDICT r4 missing-item 2)
        with tracer.span("submit", jobId=args.id) as span:
            headers = {"traceparent": format_traceparent(span)}
            if not args.wait:
                await mq.publish(args.queue, schemas.encode(msg),
                                 headers=headers)
                print(f"submitted {args.id} -> {args.queue}")
                return 0
            return await _submit_and_wait(mq, args, msg, headers)
    finally:
        try:
            await mq.close()
        finally:
            # flush the submit span even when the queue close fails —
            # a missing root span breaks the whole trace (review r5)
            await asyncio.to_thread(tracer.close)


async def _bind_telemetry_taps(mq, on_status, on_progress) -> None:
    """Bind exclusive tap queues to the telemetry fanout exchanges and
    start consuming them — copies of every event, without stealing
    deliveries from the real telemetry consumers."""
    import os

    from .platform.telemetry import PROGRESS_EXCHANGE, STATUS_EXCHANGE

    tap = os.urandom(4).hex()
    status_q = f"v1.telemetry.tap.{tap}.status"
    progress_q = f"v1.telemetry.tap.{tap}.progress"
    await mq.bind_queue(status_q, STATUS_EXCHANGE, exclusive=True)
    await mq.bind_queue(progress_q, PROGRESS_EXCHANGE, exclusive=True)
    await mq.listen(status_q, on_status)
    await mq.listen(progress_q, on_progress)


async def _submit_and_wait(mq, args, msg, headers=None) -> int:
    """Publish, then follow the job until its Convert message appears.

    Taps are bound BEFORE the publish so no event can be missed.  The
    Convert message is the only true completion signal: it is published
    after the done marker, and ERRORED statuses are informational (the
    job is redelivered and may still succeed).  Jobs the service drops
    via the stall policy emit no terminal event at all, so the wait is
    bounded by --wait-timeout (exit 124)."""
    import os

    errored = schemas.TelemetryStatus.Value("ERRORED")
    done = asyncio.Event()

    async def on_status(delivery):
        event = schemas.decode(schemas.TelemetryStatusEvent, delivery.body)
        await delivery.ack()
        if event.media_id != args.id:
            return
        name = schemas.TelemetryStatus.Name(event.status)
        suffix = "\t(will retry)" if event.status == errored else ""
        print(f"{args.id}\tstatus\t{name}{suffix}", flush=True)

    async def on_progress(delivery):
        event = schemas.decode(schemas.TelemetryProgressEvent, delivery.body)
        await delivery.ack()
        if event.media_id == args.id:
            print(f"{args.id}\tprogress\t{event.percent}%", flush=True)

    async def on_convert(delivery):
        event = schemas.decode(schemas.Convert, delivery.body)
        await delivery.ack()
        if event.media.id == args.id:
            done.set()

    await _bind_telemetry_taps(mq, on_status, on_progress)
    convert_tap = f"v1.convert.tap.{os.urandom(4).hex()}"
    await mq.bind_queue(convert_tap, schemas.CONVERT_EXCHANGE,
                        exclusive=True)
    await mq.listen(convert_tap, on_convert)

    await mq.publish(args.queue, schemas.encode(msg), headers=headers)
    print(f"submitted {args.id} -> {args.queue}", flush=True)
    try:
        async with asyncio.timeout(args.wait_timeout):
            await done.wait()
    except TimeoutError:
        print(f"{args.id}: no completion within {args.wait_timeout:.0f}s "
              "(stall-dropped jobs emit no terminal event)",
              file=sys.stderr)
        return 124
    except (KeyboardInterrupt, asyncio.CancelledError):
        return 130
    print(f"{args.id} staged (Convert published)")
    return 0


async def _status(args) -> int:
    import aiohttp

    base = args.url.rstrip("/")
    timeout = aiohttp.ClientTimeout(total=10)  # diagnostics must not hang
    async with aiohttp.ClientSession(timeout=timeout) as session:
        try:
            async with session.get(f"{base}/health") as resp:
                health = await resp.json()
                # reference parity: an idle worker answers 500
                busy = resp.status == 200
            print(f"health: {'busy' if busy else 'idle'} {health}")
            async with session.get(f"{base}/metrics") as resp:
                text = await resp.text()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as err:
            print(f"{base}: unreachable ({err})", file=sys.stderr)
            return 2
    wanted = ("jobs_consumed_total", "jobs_completed_total",
              "jobs_failed_total", "jobs_skipped_total", "jobs_active",
              "bytes_downloaded_total", "bytes_uploaded_total")
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        if any(key in line for key in wanted):
            print(line)
    return 0


async def _jobs(args) -> int:
    """Drive the control plane's admin API (health.py port)."""
    import json

    import aiohttp

    base = args.url.rstrip("/")
    token = args.token or os.environ.get("CONTROL_TOKEN")
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    timeout = aiohttp.ClientTimeout(total=60)  # drain-adjacent ops can wait
    async with aiohttp.ClientSession(timeout=timeout,
                                     headers=headers) as session:
        try:
            if args.jobs_command == "list":
                params = {"state": args.state} if args.state else {}
                if args.recovered:
                    params["recovered"] = "true"
                async with session.get(f"{base}/v1/jobs",
                                       params=params) as resp:
                    body = await resp.json()
                    if resp.status != 200:
                        print(json.dumps(body), file=sys.stderr)
                        return 1
                if body.get("intakePaused"):
                    print("# intake PAUSED", file=sys.stderr)
                for job in body.get("jobs", []):
                    stage = job.get("stage") or "-"
                    percent = job.get("percent")
                    progress = f"{percent}%" if percent is not None else "-"
                    print(f"{job['id']}\t{job['state']}\t{stage}\t{progress}"
                          f"\t{job.get('priority', 'NORMAL')}")
                return 0
            if args.jobs_command == "show":
                async with session.get(
                    f"{base}/v1/jobs/{args.id}"
                ) as resp:
                    body = await resp.json()
                    print(json.dumps(body, indent=2, sort_keys=True))
                    return 0 if resp.status == 200 else 1
            if args.jobs_command == "events":
                from .control.registry import TERMINAL_STATES

                # --follow: re-poll until the job settles, printing only
                # events not yet shown.  ``eventsDropped + len(events)``
                # is the record's total-events-ever counter, so new
                # events are exactly the tail past what was printed —
                # correct even when the bounded ring wraps mid-tail.
                printed_total = 0
                header_shown = False
                while True:
                    async with session.get(
                        f"{base}/v1/jobs/{args.id}/events"
                    ) as resp:
                        body = await resp.json()
                        if resp.status != 200:
                            print(json.dumps(body), file=sys.stderr)
                            return 1
                    if args.json and not args.follow:
                        print(json.dumps(body, indent=2, sort_keys=True))
                        return 0
                    if not header_shown and not args.json:
                        header_shown = True
                        print(f"# {body['id']}\tstate={body['state']}\t"
                              f"traceId={body.get('traceId')}")
                        if body.get("eventsDropped"):
                            print(f"# {body['eventsDropped']} older "
                                  "events dropped (ring bound)",
                                  file=sys.stderr)
                    dropped = body.get("eventsDropped", 0)
                    events = body.get("events", [])
                    start = max(printed_total - dropped, 0)
                    for event in events[start:]:
                        if args.json:
                            # --follow --json: one JSON object per NEW
                            # event (jq-able stream), not repeated
                            # whole-body dumps
                            print(json.dumps(event, sort_keys=True),
                                  flush=True)
                            continue
                        event = dict(event)
                        ts = event.pop("t", "")
                        kind = event.pop("kind", "?")
                        rest = " ".join(
                            f"{k}={v}" for k, v in event.items())
                        print(f"{ts}\t{kind}\t{rest}", flush=True)
                    printed_total = dropped + len(events)
                    if not args.follow or body["state"] in TERMINAL_STATES:
                        return 0
                    await asyncio.sleep(max(args.interval, 0.1))
            # cancel
            async with session.post(
                f"{base}/v1/jobs/{args.id}/cancel",
                json={"reason": args.reason},
            ) as resp:
                body = await resp.json()
                print(json.dumps(body, indent=2, sort_keys=True))
                return 0 if resp.status in (200, 202) else 1
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as err:
            print(f"{base}: unreachable ({err})", file=sys.stderr)
            return 2


def render_overview(body: dict) -> list:
    """The `fleet top` frame lines for one GET /v1/fleet/overview body
    (pure: unit-testable without a terminal or a fleet)."""
    lines = []
    overview = body.get("overview") or {}
    totals = overview.get("totals") or {}
    degraded = body.get("degraded", False)
    header = (f"# fleet overview via {body.get('workerId')}"
              + (f"  age={body.get('overviewAgeSeconds')}s"
                 if body.get("overviewAgeSeconds") is not None else "")
              + (f"  aggregated by {overview.get('updatedBy')}"
                 if overview.get("updatedBy") else "")
              + ("  [DEGRADED: local view only]" if degraded else ""))
    lines.append(header)
    for err in body.get("errors") or []:
        lines.append(f"# error: {err}")
    members = overview.get("workers")
    if members is None:
        # degraded to local-only: render this worker's own view so the
        # console stays useful mid-incident
        local = body.get("local") or {}
        members = [{"workerId": local.get("workerId"),
                    "signals": local.get("signals"),
                    "digest": local.get("digest"),
                    "heartbeatAt": None, "leases": "-"}]
    import time as _time

    now = _time.time()
    plan = body.get("plan")
    lines.append("WORKER            QUEUE ACTIVE LEASES  "
                 "BURN fast/slow (worst)   BREAKERS     "
                 "DECISION      BEAT")
    for member in members:
        signals = member.get("signals") or {}
        digest = member.get("digest")
        burn = "-"
        breakers = "-"
        decision = "-"
        if isinstance(digest, dict):
            last = digest.get("lastDecision")
            if isinstance(last, dict) and last.get("outcome"):
                decision = str(last["outcome"])
            if (isinstance(plan, dict)
                    and member.get("workerId") in (plan.get("drain")
                                                   or [])):
                decision = "drain"
        if isinstance(digest, dict):
            rates = digest.get("burn") or {}
            if rates:
                worst = max(
                    rates.items(),
                    key=lambda kv: ((kv[1] or {}).get("fast", 0.0),
                                    (kv[1] or {}).get("slow", 0.0)))
                burn = (f"{worst[0]} "
                        f"{(worst[1] or {}).get('fast', 0):.2f}/"
                        f"{(worst[1] or {}).get('slow', 0):.2f}")
            open_breakers = digest.get("openBreakers") or {}
            if open_breakers:
                breakers = ",".join(
                    f"{dep}:{(info or {}).get('reason') or 'open'}"
                    for dep, info in sorted(open_breakers.items()))
        elif digest is None:
            burn = "(no digest)"  # pre-digest worker: listed, not lost
        beat = member.get("heartbeatAt")
        beat_s = (f"{max(now - float(beat), 0.0):.1f}s"
                  if isinstance(beat, (int, float)) else "-")
        lines.append(
            f"{str(member.get('workerId'))[:17]:<17} "
            f"{signals.get('queue_depth', '-'):>5} "
            f"{signals.get('active_jobs', '-'):>6} "
            f"{str(member.get('leases', '-')):>6}  "
            f"{burn:<24} {breakers:<12} {decision:<13} {beat_s}")
    shares = totals.get("tenantShares") or {}
    if shares:
        lines.append("tenant queue shares: " + "  ".join(
            f"{tenant}={share:.0%}"
            for tenant, share in sorted(shares.items())))
    hops = totals.get("topHops") or []
    if hops:
        lines.append("top hops (s/GB): " + "  ".join(
            f"{h.get('hop')}={h.get('secondsPerGb')}" for h in hops))
    cpu_per_gb = totals.get("cpuSPerGb")
    if cpu_per_gb is not None:
        top = (f"  top offender: {hops[0].get('hop')}"
               f"={hops[0].get('secondsPerGb')}" if hops else "")
        lines.append(f"staging copy cost (cpu s/GB): {cpu_per_gb}{top}")
    ratio = totals.get("hopReconcileRatioMixed")
    if ratio is not None:
        lines.append(f"hop/stage reconcile (mixed, unguarded): {ratio}")
    scrub = totals.get("scrub") or {}
    if any(scrub.get(k) for k in ("clean", "repaired", "quarantined")):
        lines.append(
            f"scrub: clean={scrub.get('clean', 0)} "
            f"repaired={scrub.get('repaired', 0)} "
            f"quarantined={scrub.get('quarantined', 0)}")
    if isinstance(plan, dict):
        admission = plan.get("admission") or {}
        shed = ("SHED BULK (" + str(admission.get("reason") or "") + ")"
                if admission.get("shedBulk") else "admit all")
        drain = ",".join(plan.get("drain") or []) or "none"
        tail = plan.get("decisions") or []
        last = (f"  last: {tail[-1].get('kind')} ({tail[-1].get('why')})"
                if tail else "")
        lines.append(
            f"plan[{plan.get('epoch')}] by {plan.get('updatedBy')}: "
            f"{shed}  drain={drain}  "
            f"desired={plan.get('desiredWorkers')} "
            f"({plan.get('scale')}){last}")
    return lines


async def _fleet_top(args) -> int:
    """`cli fleet top`: a live-refreshing console over GET
    /v1/fleet/overview — the fleet's burn rates, breakers, tenant
    shares, and worst hops on one screen, from any worker."""
    import json

    import aiohttp

    base = args.url.rstrip("/")
    timeout = aiohttp.ClientTimeout(total=30)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        while True:
            try:
                async with session.get(
                        f"{base}/v1/fleet/overview") as resp:
                    body = await resp.json()
                    if resp.status != 200:
                        print(json.dumps(body), file=sys.stderr)
                        return 1
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as err:
                print(f"{base}: unreachable ({err})", file=sys.stderr)
                if args.once:
                    return 2
                # a refreshing console must SURVIVE one dropped
                # connection or a worker restart — mid-incident is
                # exactly when the operator is watching; keep the
                # last frame on screen and retry next interval
                await asyncio.sleep(max(args.interval, 0.2))
                continue
            if args.json:
                print(json.dumps(body, sort_keys=True))
            else:
                if not args.once:
                    # clear + home: a refreshing console, not a scroll
                    print("\x1b[2J\x1b[H", end="")
                for line in render_overview(body):
                    print(line)
            if args.once:
                return 0
            try:
                await asyncio.sleep(max(args.interval, 0.2))
            except (KeyboardInterrupt, asyncio.CancelledError):
                return 0


async def _fleet(args) -> int:
    """Drive the fleet endpoints (mirrors the `jobs` UX)."""
    import json
    import time

    import aiohttp

    if args.fleet_command == "top":
        return await _fleet_top(args)
    base = args.url.rstrip("/")
    timeout = aiohttp.ClientTimeout(total=30)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        try:
            if args.fleet_command == "show":
                async with session.get(
                    f"{base}/v1/fleet/{args.id}"
                ) as resp:
                    body = await resp.json()
                    print(json.dumps(body, indent=2, sort_keys=True))
                    return 0 if resp.status == 200 else 1
            async with session.get(f"{base}/v1/fleet") as resp:
                body = await resp.json()
                if resp.status != 200:
                    print(json.dumps(body), file=sys.stderr)
                    return 1
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as err:
            print(f"{base}: unreachable ({err})", file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(body, indent=2, sort_keys=True))
        return 0
    if not body.get("enabled"):
        print(f"# fleet plane disabled on {body.get('workerId') or base}",
              file=sys.stderr)
        return 0
    now = time.time()
    print(f"# this worker: {body.get('workerId')}")
    for worker in body.get("workers", []):
        signals = worker.get("signals") or {}
        beat_age = now - float(worker.get("heartbeatAt", now))
        stats = worker.get("stats") or {}
        print(f"{worker.get('workerId')}\tbeat={beat_age:.1f}s ago"
              f"\tqueue={signals.get('queue_depth', '-')}"
              f"\tactive={signals.get('active_jobs', '-')}"
              f"\tleases={len(worker.get('leases') or [])}"
              f"\tsharedHits={stats.get('sharedHits', 0)}"
              f"\tsharedFills={stats.get('sharedFills', 0)}")
    for lease in body.get("leases", []):
        flag = "EXPIRED" if lease.get("expired") else "live"
        print(f"lease {lease.get('key', '')[:16]}\t{flag}"
              f"\towner={lease.get('owner')}"
              f"\tfence={lease.get('fence')}")
    return 0


async def _trace(args) -> int:
    """Render GET /v1/trace/{id}: one wall-clock-ordered timeline of
    every worker's events for the trace, plus spans and hop ledgers."""
    import json

    import aiohttp

    base = args.url.rstrip("/")
    timeout = aiohttp.ClientTimeout(total=30)  # peer hops can add up
    params = {"scope": "local"} if args.local else {}
    async with aiohttp.ClientSession(timeout=timeout) as session:
        try:
            async with session.get(f"{base}/v1/trace/{args.id}",
                                   params=params) as resp:
                body = await resp.json()
                if resp.status != 200:
                    print(json.dumps(body), file=sys.stderr)
                    return 1
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as err:
            print(f"{base}: unreachable ({err})", file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(body, indent=2, sort_keys=True))
        return 0
    from .control.trace import merged_timeline

    print(f"# trace {body['traceId']}\tworkers="
          f"{','.join(body.get('workers') or []) or '-'}")
    if body.get("degraded"):
        print("# DEGRADED view (coordination/peer trouble): "
              + "; ".join(body.get("errors") or []), file=sys.stderr)
    for segment in body.get("segments") or []:
        hops = segment.get("hopLedger") or {}
        hop_view = " ".join(
            f"{hop}={entry.get('seconds')}s/{entry.get('bytes')}B"
            for hop, entry in hops.items()
        )
        print(f"# job {segment.get('jobId')}\t{segment.get('state')}"
              f"\tworker={segment.get('workerId')}"
              f"\tsource={segment.get('source')}"
              + (f"\tlink={segment['link']}" if segment.get("link")
                 else "")
              + (f"\n#   hops: {hop_view}" if hop_view else ""))
    for row in merged_timeline(body):
        ts = row.pop("t", "")
        kind = row.pop("kind", "?")
        worker = row.pop("workerId", "-")
        job = row.pop("jobId", "-")
        rest = " ".join(f"{k}={v}" for k, v in row.items())
        print(f"{ts}\t{worker}\t{job}\t{kind}\t{rest}")
    spans = body.get("spans") or []
    if spans:
        print(f"# {len(spans)} span(s)")
        for span in sorted(spans, key=lambda s: s.get("startTime") or 0):
            print(f"{span.get('startTime')}\t{span.get('workerId') or '-'}"
                  f"\tspan\t{span.get('name')}"
                  f"\tduration={round(span.get('duration', 0), 4)}s"
                  + (f"\terror={span['error']}" if span.get("error")
                     else ""))
    return 0


async def _tenants(args) -> int:
    """Render GET /v1/tenants (mirrors the `fleet list` UX)."""
    import json

    import aiohttp

    base = args.url.rstrip("/")
    timeout = aiohttp.ClientTimeout(total=10)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        try:
            async with session.get(f"{base}/v1/tenants") as resp:
                body = await resp.json()
                if resp.status != 200:
                    print(json.dumps(body), file=sys.stderr)
                    return 1
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as err:
            print(f"{base}: unreachable ({err})", file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(body, indent=2, sort_keys=True))
        return 0
    overload = body.get("overload") or {}
    if overload.get("saturated"):
        print("# worker SATURATED: shedding BULK "
              f"(reasons: {','.join(overload.get('reasons', []))})",
              file=sys.stderr)
    if not body.get("configured"):
        print("# no tenants.* config: every job runs as 'default'",
              file=sys.stderr)
    for name, t in sorted((body.get("tenants") or {}).items()):
        cap = t.get("maxConcurrent")
        print(f"{name}\tweight={t.get('weight')}"
              f"\tcap={cap if cap is not None else '-'}"
              f"\tqueued={t.get('queued', 0)}"
              f"\trunning={t.get('runningSlots', 0)}"
              f"\twaiting={t.get('waitingForSlot', 0)}"
              f"\tdl={t.get('downloadRateLimit') or '-'}"
              f"\tul={t.get('uploadRateLimit') or '-'}")
    return 0


async def _incident(args) -> int:
    """Drive the incident plane (downloader_tpu/incident; ISSUE 18):
    list/show/export bundles over the admin API, replay one on a fresh
    SoakRig fleet, and diff breach signatures."""
    import json

    import aiohttp

    if args.incident_command == "diff":
        from .incident.replay import bundle_signature, diff_signatures

        with open(args.original, encoding="utf-8") as fh:
            original = json.load(fh)
        with open(args.replay, encoding="utf-8") as fh:
            replay = json.load(fh)
        verdict = diff_signatures(bundle_signature(original),
                                  bundle_signature(replay))
        _print_signature_diff(verdict)
        return 0 if verdict["match"] else 1

    if args.incident_command == "replay":
        return await _incident_replay(args)

    base = args.url.rstrip("/")
    token = args.token or os.environ.get("CONTROL_TOKEN")
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    timeout = aiohttp.ClientTimeout(total=30)
    async with aiohttp.ClientSession(timeout=timeout,
                                     headers=headers) as session:
        try:
            if args.incident_command == "list":
                async with session.get(f"{base}/v1/incidents") as resp:
                    body = await resp.json()
                    if resp.status != 200:
                        print(json.dumps(body), file=sys.stderr)
                        return 1
                if args.json:
                    print(json.dumps(body, indent=2, sort_keys=True))
                    return 0
                if not body.get("enabled"):
                    print("# incident plane disabled "
                          "(incident.enabled: false)", file=sys.stderr)
                verdict = body.get("lastVerdict")
                if verdict is not None:
                    print("# last replay verdict: "
                          + ("MATCH" if verdict.get("match")
                             else "DIVERGED"), file=sys.stderr)
                for row in body.get("incidents", []):
                    objectives = ",".join(row.get("objectives") or []) or "-"
                    print(f"{row.get('bundleId')}\t{row.get('trigger')}"
                          f"\t{row.get('jobId')}\t{row.get('state')}"
                          f"\tbreaches={row.get('breaches')}"
                          f"\tobjectives={objectives}"
                          f"\t{row.get('exportedAt')}")
                return 0

            if args.incident_command == "show":
                async with session.get(
                        f"{base}/v1/incidents/{args.id}") as resp:
                    body = await resp.json()
                    if resp.status != 200:
                        print(json.dumps(body), file=sys.stderr)
                        return 1
                return _emit_bundle(body, args.out)

            if args.incident_command == "export":
                async with session.post(
                        f"{base}/v1/incidents/{args.id}/export") as resp:
                    body = await resp.json()
                    if resp.status not in (200, 201):
                        print(json.dumps(body), file=sys.stderr)
                        return 1
                print(f"# exported {body.get('bundleId')} "
                      f"(trigger={body.get('trigger')})", file=sys.stderr)
                return _emit_bundle(body, args.out)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as err:
            print(f"{base}: unreachable ({err})", file=sys.stderr)
            return 2
    raise AssertionError("unreachable")


def _emit_bundle(bundle: dict, out) -> int:
    import json

    blob = json.dumps(bundle, indent=2, sort_keys=True)
    if out:
        with open(out, "w", encoding="utf-8") as fh:
            fh.write(blob + "\n")
        print(f"# wrote {out}", file=sys.stderr)
    else:
        print(blob)
    return 0


def _print_signature_diff(verdict: dict) -> None:
    import json

    for name, field in verdict["fields"].items():
        mark = "=" if field["match"] else "!"
        print(f"{mark} {name}\toriginal={json.dumps(field['original'])}"
              f"\treplay={json.dumps(field['replay'])}")
    print("match" if verdict["match"] else "DIVERGED")


async def _incident_replay(args) -> int:
    """Pull (or read) a bundle, compile it, run the scenario on a fresh
    SoakRig fleet --runs times, and require EVERY replay to reproduce
    the original breach signature."""
    import json
    import tempfile

    import aiohttp

    from .incident.compiler import compile_bundle, scenario_profile
    from .incident.replay import (diff_signatures,
                                  signature_from_incidents)

    base = args.url.rstrip("/")
    if args.bundle:
        with open(args.bundle, encoding="utf-8") as fh:
            bundle = json.load(fh)
    elif args.id:
        timeout = aiohttp.ClientTimeout(total=30)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            try:
                async with session.get(
                        f"{base}/v1/incidents/{args.id}") as resp:
                    bundle = await resp.json()
                    if resp.status != 200:
                        print(json.dumps(bundle), file=sys.stderr)
                        return 1
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as err:
                print(f"{base}: unreachable ({err})", file=sys.stderr)
                return 2
    else:
        print("incident replay: give a bundle id or --bundle FILE",
              file=sys.stderr)
        return 1

    scenario = compile_bundle(bundle)
    if args.compile_only:
        print(json.dumps(scenario, indent=2, sort_keys=True))
        return 0

    # the SoakTestWorld builder lives with the tests (it wires MiniAmqp
    # + MiniS3 + loopback origins around the rig) — imported the same
    # way the bench does
    tests_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "tests")
    tests_dir = os.path.abspath(tests_dir)
    if tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    from test_soak import SoakTestWorld

    original_sig = scenario["signature"]
    print(f"# replaying {scenario.get('source')}: "
          f"{len(scenario['faultPlan'])} fault rule(s), "
          f"{scenario['profile'].get('jobs')} jobs x{args.runs} run(s)",
          file=sys.stderr)
    all_match = True
    last_verdict = None
    for run in range(max(args.runs, 1)):
        profile = scenario_profile(scenario)
        with tempfile.TemporaryDirectory() as tmp:
            world = await SoakTestWorld.create(tmp, profile)
            try:
                await world.rig.run(world.workload)
                replay_sig = signature_from_incidents(world.rig.incidents)
            finally:
                await world.close()
        verdict = diff_signatures(original_sig, replay_sig)
        last_verdict = verdict
        print(f"# run {run + 1}/{args.runs}: "
              + ("signature MATCH" if verdict["match"] else "DIVERGED"),
              file=sys.stderr)
        _print_signature_diff(verdict)
        all_match = all_match and verdict["match"]

    if not args.no_report and last_verdict is not None:
        # best-effort: land the verdict on the worker that exported the
        # bundle (incident_replay_signature_match gauge)
        token = args.token or os.environ.get("CONTROL_TOKEN")
        headers = ({"Authorization": f"Bearer {token}"} if token else {})
        try:
            timeout = aiohttp.ClientTimeout(total=10)
            async with aiohttp.ClientSession(timeout=timeout,
                                             headers=headers) as session:
                async with session.post(
                        f"{base}/v1/incidents/verdict",
                        json={"match": all_match,
                              "bundleId": bundle.get("bundleId"),
                              "fields": last_verdict["fields"]}) as resp:
                    await resp.read()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            pass
    return 0 if all_match else 1


async def _debug(args) -> int:
    """Drive the runtime-introspection endpoints (/debug/*)."""
    import json

    import aiohttp

    base = args.url.rstrip("/")
    timeout = aiohttp.ClientTimeout(total=10)  # diagnostics must not hang
    async with aiohttp.ClientSession(timeout=timeout) as session:
        try:
            async with session.get(
                f"{base}/debug/{args.debug_command}"
            ) as resp:
                body = await resp.json()
                if resp.status != 200:
                    print(json.dumps(body), file=sys.stderr)
                    return 1
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as err:
            print(f"{base}: unreachable ({err})", file=sys.stderr)
            return 2
    if args.debug_command == "tasks":
        lag = body.get("loopLag") or {}
        print(f"# loop lag: last={lag.get('last')} max={lag.get('max')}")
        for task in body.get("tasks", []):
            top = task["stack"][-1] if task.get("stack") else "-"
            print(f"{task['name']}\t{task['coro']}\t{top}")
        return 0
    for thread in body.get("threads", []):
        print(f"== thread {thread['name']} ({thread['threadId']})")
        for line in thread.get("stack", []):
            print(line)
    for task in body.get("tasks", []):
        print(f"== task {task['name']} ({task['coro']})")
        for line in task.get("stack", []):
            print(f"  {line}")
    return 0


async def _scrub(args) -> int:
    """One synchronous scrub pass, in-process (no running service).

    Builds the same cache/fleet/workdir trio the orchestrator hands its
    background scrubber and runs a single ``scan()`` — so an operator
    can force a full integrity pass (post-incident, after swapping a
    disk) without waiting out ``scrub.interval``, including against a
    stopped instance.  ``scrub.enabled: false`` only removes the
    BACKGROUND loop; an explicit invocation always runs.  Exit 0 when
    nothing was quarantined (clean or repaired are both fine), 1 when
    something was (bytes lost their last healthy replica — page on it),
    2 when the shared tier is unreachable and ``--local-only`` wasn't
    given (refusing to quarantine entries a reachable tier would have
    repaired).
    """
    import json

    from .fleet.plane import FleetPlane, resolve_worker_id
    from .platform.config import cfg_get
    from .stages.download import job_download_dir
    from .store import new_client
    from .store.cache import ContentCache
    from .store.scrub import (DEFAULT_INTERVAL, DEFAULT_RATE_MB_S,
                              Scrubber)

    config = load_config("converter")
    logger = get_logger("downloader-scrub")
    cache = ContentCache.from_config(config, logger=logger)
    fleet = None
    if not args.local_only:
        try:
            fleet = FleetPlane.from_config(
                config, worker_id=resolve_worker_id(config),
                store=new_client(config), logger=logger,
            )
        except Exception as err:
            print(
                f"shared tier unavailable ({type(err).__name__}: {err}); "
                "re-run with --local-only to scrub without repairs",
                file=sys.stderr,
            )
            return 2
    scrubber = Scrubber(
        cache=cache, fleet=fleet,
        workdir_root=os.path.dirname(job_download_dir(config, "_probe")),
        quarantine_dir=cfg_get(config, "scrub.quarantine_dir", None),
        interval=float(cfg_get(config, "scrub.interval",
                               DEFAULT_INTERVAL)),
        rate_bytes=float(cfg_get(config, "scrub.rate_mb_s",
                                 DEFAULT_RATE_MB_S)) * 1e6,
        logger=logger,
    )
    counts = await scrubber.scan()
    snap = scrubber.snapshot()
    if args.as_json:
        print(json.dumps({**counts,
                          "passSeconds": snap.get("lastPassSeconds")}))
    else:
        print(f"scrub pass complete: clean={counts['clean']} "
              f"repaired={counts['repaired']} "
              f"quarantined={counts['quarantined']} "
              f"({snap.get('lastPassSeconds', 0.0)}s)")
    return 0 if counts["quarantined"] == 0 else 1


async def _watch(args) -> int:
    from .mq import new_queue, resolve_backend

    config = load_config("converter")
    logger = get_logger("downloader-cli")
    if resolve_backend(config) == "memory":
        print(
            "config selects the in-memory queue backend; telemetry from a "
            "running service is not reachable from this process. Configure "
            "`rabbitmq: {backend: amqp}` first.",
            file=sys.stderr,
        )
        return 2

    seen = 0
    done = asyncio.Event()

    def _emit(line: str) -> None:
        nonlocal seen
        print(line, flush=True)
        seen += 1
        if args.count and seen >= args.count:
            done.set()

    async def on_status(delivery):
        event = schemas.decode(schemas.TelemetryStatusEvent, delivery.body)
        await delivery.ack()
        if args.id and event.media_id != args.id:
            return
        name = schemas.TelemetryStatus.Name(event.status)
        _emit(f"{event.media_id}\tstatus\t{name}")

    async def on_progress(delivery):
        event = schemas.decode(schemas.TelemetryProgressEvent, delivery.body)
        await delivery.ack()
        if args.id and event.media_id != args.id:
            return
        name = schemas.TelemetryStatus.Name(event.status)
        _emit(f"{event.media_id}\tprogress\t{name}\t{event.percent}%")

    mq = new_queue(config, logger=logger)
    await mq.connect()
    try:
        await _bind_telemetry_taps(mq, on_status, on_progress)
        try:
            await done.wait()
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass
    finally:
        await mq.close()
    return 0


def _mktorrent(args) -> int:
    from .torrent import make_metainfo

    meta = make_metainfo(
        args.path,
        piece_length=args.piece_length,
        trackers=args.tracker,
        webseeds=args.webseed,
    )
    with open(args.out, "wb") as fh:
        fh.write(meta.to_torrent_bytes())
    print(f"{args.out}: {meta.num_pieces} pieces x {meta.piece_length} "
          f"({meta.total_length} bytes), infohash {meta.info_hash.hex()}")
    return 0


async def _scrape(args) -> int:
    from .torrent import tracker as tracker_mod
    from .torrent.metainfo import parse_torrent_bytes

    with open(args.torrent, "rb") as fh:
        meta = parse_torrent_bytes(fh.read())
    if not meta.trackers:
        print("torrent has no trackers to scrape", file=sys.stderr)
        return 2
    # trackers are independent: query them concurrently so dead ones
    # don't serialize their timeouts in front of the live ones
    results = await asyncio.gather(
        *(tracker_mod.scrape(url, meta.info_hash) for url in meta.trackers),
        return_exceptions=True,
    )
    failures = 0
    for url, stats in zip(meta.trackers, results):
        if isinstance(stats, BaseException):
            print(f"{url}\terror\t{stats}", file=sys.stderr)
            failures += 1
            continue
        print(f"{url}\tseeders={stats.seeders}\tleechers={stats.leechers}"
              f"\tcompleted={stats.completed}")
    return 0 if failures < len(meta.trackers) else 1


def _upscale(args) -> int:
    try:
        from .compute.pipeline import FrameUpscaler
    except ImportError:
        print("upscale needs the [compute] extra (jax/flax)", file=sys.stderr)
        return 2
    import shutil

    # naming a decoder/encoder (or passing encode args) implies the mode
    # (a --decoder without --decode would otherwise be silently ignored
    # and die parsing the container).  Resolve binaries BEFORE
    # FrameUpscaler(): JAX backend init costs seconds (and hangs on a
    # wedged device tunnel) — a usage error must not pay that.
    decoder = encoder = None
    if getattr(args, "decode", False) or getattr(args, "decoder", None):
        name = args.decoder or "ffmpeg"
        decoder = shutil.which(name)
        if decoder is None:
            print(f"decoder {name!r} not found on PATH", file=sys.stderr)
            return 2
    if (getattr(args, "encode", False) or getattr(args, "encoder", None)
            or getattr(args, "encode_args", None)):
        name = args.encoder or "ffmpeg"
        encoder = shutil.which(name)
        if encoder is None:
            print(f"encoder {name!r} not found on PATH", file=sys.stderr)
            return 2
    upscaler = FrameUpscaler(
        batch=args.batch, checkpoint_dir=args.checkpoint_dir
    )
    try:
        from .compute.transcode import DEFAULT_ENCODE_ARGS, transcode

        # transcode writes through a private temp and renames onto dst
        # only on success: it NEVER touches dst on failure, so a
        # pre-existing output from an earlier run survives any error
        # (no caller-side stat heuristics — coarse-mtime filesystems
        # defeat those)
        frames = transcode(
            upscaler, args.src, args.dst,
            decoder=decoder, encoder=encoder,
            encode_args=(args.encode_args if getattr(args, "encode_args", None)
                         else DEFAULT_ENCODE_ARGS),
        )
    except RuntimeError as err:
        # clean operator error instead of a traceback
        print(f"transcode failed: {err}", file=sys.stderr)
        return 1
    print(f"upscaled {frames} frames -> {args.dst}")
    return 0


def _train(args) -> int:
    try:
        from .compute.trainer import TrainerSettings, discover_media, train
    except ImportError:
        print("train needs the [compute] extra (jax/flax/optax)",
              file=sys.stderr)
        return 2
    paths = discover_media(args.data)
    settings = TrainerSettings(
        steps=args.steps,
        batch=args.batch,
        crop=args.crop,
        learning_rate=args.lr,
        checkpoint_dir=args.checkpoint_dir,
        save_every=args.save_every,
        model_axis=args.model_axis,
        seed=args.seed,
        scale=args.scale,
        features=args.features,
        depth=args.depth,
    )
    summary = train(paths, settings, log=print)
    print(
        f"trained to step {summary['final_step']} "
        f"(loss {summary['final_loss']:.6f}, batch {summary['batch']}, "
        f"devices {summary['devices']})"
    )
    return 0


def _magnet(args) -> int:
    from .torrent.magnet import make_magnet
    from .torrent.metainfo import parse_torrent_bytes

    with open(args.torrent, "rb") as fh:
        meta = parse_torrent_bytes(fh.read())
    print(make_magnet(meta.info_hash, meta.name, meta.trackers))
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "submit":
        return asyncio.run(_submit(args))
    if args.command == "mktorrent":
        return _mktorrent(args)
    if args.command == "magnet":
        return _magnet(args)
    if args.command == "scrape":
        return asyncio.run(_scrape(args))
    if args.command == "status":
        return asyncio.run(_status(args))
    if args.command == "jobs":
        return asyncio.run(_jobs(args))
    if args.command == "fleet":
        return asyncio.run(_fleet(args))
    if args.command == "trace":
        return asyncio.run(_trace(args))
    if args.command == "tenants":
        return asyncio.run(_tenants(args))
    if args.command == "incident":
        return asyncio.run(_incident(args))
    if args.command == "debug":
        return asyncio.run(_debug(args))
    if args.command == "scrub":
        return asyncio.run(_scrub(args))
    if args.command == "watch":
        return asyncio.run(_watch(args))
    if args.command == "upscale":
        return _upscale(args)
    if args.command == "train":
        return _train(args)
    raise AssertionError("unreachable")


if __name__ == "__main__":
    sys.exit(main())
