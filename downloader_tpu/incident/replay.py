"""Breach signatures, replay collection, and the incident diff.

A **breach signature** is the compact identity of an incident — the
fields that must match for a replay to count as a reproduction
(ISSUE 18 acceptance): the breached objective classes, the open-breaker
dependency + reason, the guilty hop, and whether cross-worker fencing
fired.  ``bundle_signature`` derives it from a bundle;
``signature_from_incidents`` picks the signature out of a replay
fleet's own auto-exported bundles (the replay runs the same incident
plane, so original and replay are compared bundle-to-bundle).

``diff_signatures`` is the triage verdict: ``match`` per field and
overall.  Same signature => the scenario reproduces the incident; a
later PR whose replay comes back green (no breach exported) is a
verified fix.
"""

from typing import Dict, List, Optional

#: what a breach-free run (or an empty ring) reduces to
EMPTY_SIGNATURE: Dict[str, object] = {
    "objectives": [],
    "breachKinds": [],
    "breaker": None,
    "guiltyHop": None,
    "fenced": False,
}

#: signature fields compared by diff_signatures, in triage order —
#: objective class first (what burned), then the breaker (what was
#: shedding), then attribution (where the time went / who fenced)
SIGNATURE_FIELDS = ("objectives", "breachKinds", "breaker", "guiltyHop",
                    "fenced")


def _guilty_hop(bundle: dict) -> Optional[str]:
    """The hop carrying the most wall seconds — first from the job's
    own ledger, falling back to the tracker-wide digest."""
    ledger = bundle.get("hopLedger") or {}
    hops = ledger.get("hops") if isinstance(ledger.get("hops"), dict) else ledger
    best, best_seconds = None, 0.0
    if isinstance(hops, dict):
        for name, doc in hops.items():
            seconds = doc.get("seconds", 0.0) if isinstance(doc, dict) else 0.0
            try:
                seconds = float(seconds)
            except (TypeError, ValueError):
                continue
            if seconds > best_seconds:
                best, best_seconds = name, seconds
    if best is not None:
        return best
    digest_hops = (bundle.get("digest") or {}).get("hops") or {}
    for name, doc in digest_hops.items():
        seconds = doc.get("seconds", 0.0) if isinstance(doc, dict) else 0.0
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            continue
        if seconds > best_seconds:
            best, best_seconds = name, seconds
    return best


def bundle_signature(bundle: dict) -> dict:
    """Derive the breach signature from a bundle (pure)."""
    breaches = bundle.get("breaches") or []
    objectives = sorted({
        str(e.get("objective")) for e in breaches if e.get("objective")})
    kinds = sorted({
        str(e.get("breach")) for e in breaches if e.get("breach")})
    breaker = None
    open_breakers = bundle.get("openBreakers") or {}
    for dep in sorted(open_breakers):
        doc = open_breakers[dep] or {}
        breaker = {"dependency": dep, "reason": doc.get("reason")}
        break
    fenced = int((bundle.get("fleetStats") or {}).get("fencedWrites") or 0)
    return {
        "objectives": objectives,
        "breachKinds": kinds,
        "breaker": breaker,
        "guiltyHop": _guilty_hop(bundle),
        "fenced": fenced > 0,
    }


def signature_from_incidents(bundles: List[dict]) -> dict:
    """The replay side of the diff: given the bundles a replay fleet
    exported, return the signature of the newest breach-carrying one
    (EMPTY_SIGNATURE when the replay came back green)."""
    for bundle in reversed(bundles):
        if bundle.get("breaches"):
            return bundle_signature(bundle)
    return dict(EMPTY_SIGNATURE)


def diff_signatures(original: dict, replay: dict) -> dict:
    """Field-by-field signature comparison; ``match`` = reproduced."""
    fields = {}
    for name in SIGNATURE_FIELDS:
        a, b = original.get(name), replay.get(name)
        fields[name] = {"original": a, "replay": b, "match": a == b}
    return {
        "match": all(f["match"] for f in fields.values()),
        "fields": fields,
    }


async def collect_incidents(urls: List[str], *,
                            timeout: float = 5.0) -> List[dict]:
    """Pull full bundles from a fleet's ``/v1/incidents`` endpoints
    (best-effort: an unreachable worker contributes nothing, matching
    the degradation contract of the endpoint itself)."""
    import aiohttp

    bundles: List[dict] = []
    client_timeout = aiohttp.ClientTimeout(total=timeout)
    async with aiohttp.ClientSession(timeout=client_timeout) as session:
        for base in urls:
            try:
                async with session.get(base + "/v1/incidents") as resp:
                    if resp.status != 200:
                        continue
                    listing = await resp.json()
            except Exception:
                continue
            for summary in listing.get("incidents") or []:
                bundle_id = summary.get("bundleId")
                if not bundle_id:
                    continue
                try:
                    async with session.get(
                            f"{base}/v1/incidents/{bundle_id}") as resp:
                        if resp.status != 200:
                            continue
                        bundles.append(await resp.json())
                except Exception:
                    continue
    # oldest-first by export stamp so signature_from_incidents's
    # "newest breach wins" holds across workers
    bundles.sort(key=lambda b: str(b.get("exportedAt") or ""))
    return bundles
