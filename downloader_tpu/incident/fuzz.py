"""Deterministic scenario fuzzer (`make fuzz-scenarios`; ISSUE 18 stretch).

Takes one compiled incident scenario (``compiler.compile_bundle``
output) and breeds seeded variants of it — shifted degradation windows,
swapped fault kinds, scaled job counts, stretched windows, jittered
publish rates — hunting for breach signatures the original incident
never produced.  Opt-in and deliberately NOT a CI job (like
``make soak-full``): executing a variant is a full SoakRig replay, so a
fuzz campaign is minutes-per-variant by construction.

DETERMINISM CONTRACT: every mutation is drawn from ``random.Random``
seeded by the caller; the same ``(scenario, seed, variants)`` triple
yields byte-identical variants on every run and every machine
(tests/test_incident.py::test_fuzz_is_deterministic).  No wall-clock,
no environment, no global RNG — the same discipline as the compiler,
because a fuzz-found breach is only worth filing if the seed replays
it.
"""

import copy
import json
import random
from typing import Dict, List, Optional, Tuple

from ..platform.faults import MODES, WINDOWED_KINDS
from .compiler import (DEFAULT_LEAD_S, REPLAY_JOB_CAP, REPLAY_JOB_FLOOR,
                       scenario_fault_plan_json)

#: sane fuzz-side clamps — wider than the compiler's replay clamps (the
#: point is to explore), still bounded so a variant stays runnable
FUZZ_JOB_FLOOR = max(REPLAY_JOB_FLOOR // 2, 3)
FUZZ_JOB_CAP = REPLAY_JOB_CAP * 2
MIN_RATE, MAX_RATE = 0.5, 8.0
MAX_SHIFT_S = 6.0
MAX_WINDOW_SCALE = 3.0

#: per-kind field defaults a swap must fill in so the mutated rule
#: stays a valid FaultRule (platform/faults.py) of its NEW kind
_KIND_DEFAULTS: Dict[str, Dict[str, object]] = {
    "brownout": {"latency_ms": 400.0, "jitter_ms": 120.0},
    "partition": {"blackhole": False},
    "flap": {"period_s": 2.0, "duty": 0.5},
}


def _windowed_rules(plan: List[dict]) -> List[int]:
    return [i for i, r in enumerate(plan)
            if r.get("kind") in WINDOWED_KINDS]


def _mut_shift_window(scenario: dict, rng: random.Random) -> Optional[str]:
    """Slide one degradation window earlier/later (floored at lead)."""
    plan = scenario["faultPlan"]
    idx = _windowed_rules(plan)
    if not idx:
        return None
    i = rng.choice(idx)
    shift = round(rng.uniform(-MAX_SHIFT_S, MAX_SHIFT_S), 2)
    lead = float(scenario.get("leadS") or DEFAULT_LEAD_S)
    old = float(plan[i].get("start_s", 0.0) or 0.0)
    plan[i]["start_s"] = round(max(old + shift, lead), 2)
    return (f"shift_window[{i}:{plan[i].get('kind')}] "
            f"start_s {old} -> {plan[i]['start_s']}")


def _mut_swap_kind(scenario: dict, rng: random.Random) -> Optional[str]:
    """Swap one windowed rule to a different windowed kind."""
    plan = scenario["faultPlan"]
    idx = _windowed_rules(plan)
    if not idx:
        return None
    i = rng.choice(idx)
    old = plan[i].get("kind")
    choices = sorted(WINDOWED_KINDS - {old})
    new = rng.choice(choices)
    plan[i]["kind"] = new
    for field_name, default in _KIND_DEFAULTS.get(new, {}).items():
        plan[i].setdefault(field_name, default)
    return f"swap_kind[{i}] {old} -> {new}"


def _mut_swap_mode(scenario: dict, rng: random.Random) -> Optional[str]:
    """Flip a partition/flap's asymmetry (all|writes|reads)."""
    plan = scenario["faultPlan"]
    idx = [i for i in _windowed_rules(plan)
           if plan[i].get("kind") in ("partition", "flap")]
    if not idx:
        return None
    i = rng.choice(idx)
    old = plan[i].get("mode", "all")
    new = rng.choice([m for m in MODES if m != old])
    plan[i]["mode"] = new
    return f"swap_mode[{i}] {old} -> {new}"


def _mut_stretch_window(scenario: dict, rng: random.Random) -> Optional[str]:
    """Scale one window's length (0 = open-ended stays open-ended)."""
    plan = scenario["faultPlan"]
    idx = [i for i in _windowed_rules(plan)
           if float(plan[i].get("window_s", 0.0) or 0.0) > 0.0]
    if not idx:
        return None
    i = rng.choice(idx)
    factor = round(rng.uniform(1.0 / MAX_WINDOW_SCALE, MAX_WINDOW_SCALE), 2)
    old = float(plan[i]["window_s"])
    plan[i]["window_s"] = round(max(old * factor, 0.5), 2)
    return f"stretch_window[{i}] window_s {old} -> {plan[i]['window_s']}"


def _mut_scale_jobs(scenario: dict, rng: random.Random) -> Optional[str]:
    """Scale the replay job count (clamped to the fuzz bounds)."""
    profile = scenario["profile"]
    factor = rng.choice((0.5, 1.5, 2.0))
    old = int(profile.get("jobs", REPLAY_JOB_FLOOR) or REPLAY_JOB_FLOOR)
    profile["jobs"] = int(min(max(round(old * factor), FUZZ_JOB_FLOOR),
                              FUZZ_JOB_CAP))
    return f"scale_jobs x{factor} {old} -> {profile['jobs']}"


def _mut_jitter_rate(scenario: dict, rng: random.Random) -> Optional[str]:
    """Scale the publish rate — same jobs, different arrival pressure."""
    profile = scenario["profile"]
    factor = round(rng.uniform(0.5, 2.0), 2)
    old = float(profile.get("publish_rate", 2.5) or 2.5)
    profile["publish_rate"] = round(
        min(max(old * factor, MIN_RATE), MAX_RATE), 2)
    return f"jitter_rate x{factor} {old} -> {profile['publish_rate']}"


#: the mutation menu, in a FIXED order (determinism: rng.choice over a
#: stable tuple, never over set iteration)
MUTATIONS = (
    _mut_shift_window,
    _mut_swap_kind,
    _mut_swap_mode,
    _mut_stretch_window,
    _mut_scale_jobs,
    _mut_jitter_rate,
)


def mutate_scenario(scenario: dict, rng: random.Random,
                    mutations: int = 2) -> Tuple[dict, List[str]]:
    """Apply ``mutations`` seeded mutations to a DEEP COPY of the
    scenario; returns (variant, human-readable mutation log).  A
    mutation that does not apply (e.g. no windowed rules to shift)
    draws again, bounded, so sparse plans still fuzz."""
    variant = copy.deepcopy(scenario)
    applied: List[str] = []
    attempts = 0
    while len(applied) < mutations and attempts < mutations * 8:
        attempts += 1
        note = rng.choice(MUTATIONS)(variant, rng)
        if note is not None:
            applied.append(note)
    # the profile carries the plan as env-var JSON (SoakProfile
    # contract): re-serialize so the mutated windows actually install
    variant["profile"]["fault_plan"] = scenario_fault_plan_json(variant)
    return variant, applied


def fuzz_scenarios(scenario: dict, *, seed: int = 0,
                   variants: int = 8,
                   mutations_per_variant: int = 2) -> List[dict]:
    """Breed ``variants`` deterministic mutants of one scenario.

    Each entry: ``{"name", "seed", "mutations": [...], "scenario"}``.
    One master ``Random(seed)`` drives the whole campaign, so variant
    N depends only on (scenario, seed, N) — re-running a campaign with
    the same seed reproduces every variant, and any single variant can
    be re-bred by replaying the campaign up to its index.
    """
    rng = random.Random(seed)
    out: List[dict] = []
    for i in range(max(int(variants), 0)):
        variant, applied = mutate_scenario(
            scenario, rng, mutations=mutations_per_variant)
        out.append({
            "name": f"fz-{seed}-{i:03d}",
            "seed": seed,
            "mutations": applied,
            "scenario": variant,
        })
    return out


async def _replay_variant(entry: dict, root: str) -> dict:
    """Run one variant on a fresh SoakRig fleet and return its breach
    signature (imports the test-side world builder the same way the
    bench does — the fuzzer is tooling, not a production path)."""
    import os
    import sys

    tests_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tests")
    if tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    from test_soak import SoakTestWorld

    from .compiler import scenario_profile
    from .replay import signature_from_incidents

    profile = scenario_profile(entry["scenario"])
    world = await SoakTestWorld.create(root, profile)
    try:
        report = await world.rig.run(world.workload)
        signature = signature_from_incidents(world.rig.incidents)
    finally:
        await world.close()
    return {
        "name": entry["name"],
        "mutations": entry["mutations"],
        "signature": signature,
        "guards_ok": bool(report.ok),
    }


async def run_campaign(scenario: dict, *, seed: int, variants: int,
                       execute: bool, log=print) -> dict:
    """The `make fuzz-scenarios` entry: breed variants, optionally
    replay each, and report any signature the original never had."""
    import tempfile

    from .replay import diff_signatures

    bred = fuzz_scenarios(scenario, seed=seed, variants=variants)
    original_sig = scenario.get("signature") or {}
    results: List[dict] = []
    novel: List[dict] = []
    for entry in bred:
        log(f"[fuzz] {entry['name']}: " + "; ".join(entry["mutations"]))
        if not execute:
            continue
        with tempfile.TemporaryDirectory() as tmp:
            result = await _replay_variant(entry, tmp)
        verdict = diff_signatures(original_sig, result["signature"])
        result["novel"] = not verdict["match"]
        results.append(result)
        if result["novel"]:
            novel.append(result)
            log(f"[fuzz] {entry['name']}: NEW breach signature "
                f"{json.dumps(result['signature'], sort_keys=True)}")
        else:
            log(f"[fuzz] {entry['name']}: signature unchanged")
    return {
        "seed": seed,
        "variants": [e["name"] for e in bred],
        "executed": len(results),
        "novelSignatures": novel,
        "campaign": results if execute else [
            {"name": e["name"], "mutations": e["mutations"]} for e in bred],
    }


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m downloader_tpu.incident.fuzz`` — see Makefile
    ``fuzz-scenarios`` (opt-in; deliberately not wired into CI)."""
    import argparse
    import asyncio
    import os
    import sys

    from .compiler import compile_bundle

    default_bundle = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "tests", "fixtures", "incident_bundle_v1.json")
    parser = argparse.ArgumentParser(
        description="seeded incident-scenario fuzzer (not a CI job)")
    parser.add_argument("--bundle", default=default_bundle,
                        help="incident bundle JSON to compile and fuzz")
    parser.add_argument("--seed", type=int, default=1818)
    parser.add_argument("--variants", type=int, default=6)
    parser.add_argument("--execute", action="store_true",
                        help="actually replay each variant on a SoakRig "
                             "fleet (minutes per variant)")
    args = parser.parse_args(argv)

    with open(args.bundle, encoding="utf-8") as fh:
        bundle = json.load(fh)
    scenario = compile_bundle(bundle)
    summary = asyncio.run(run_campaign(
        scenario, seed=args.seed, variants=args.variants,
        execute=args.execute))
    sys.stdout.write(json.dumps({k: v for k, v in summary.items()
                                 if k != "campaign"}, sort_keys=True) + "\n")
    return 1 if summary["novelSignatures"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
