"""Incident bundles: versioned trace -> replay snapshots.

A bundle is the forensic record of one job's breach: the flight-recorder
timeline, the job's journal lines, the ``slo_breach`` events with their
burn/budget context, the hop ledger, open-breaker reasons, the fleet
plan epoch + routing decision in force, the fault plan that was active,
and a config fingerprint.  It is self-describing (``schema``) and the
shipped field set is FROZEN like the proto wire table
(tests/test_incident.py::test_bundle_field_numbers_frozen): fields are
only ever *added*, never renumbered or retyped, so a bundle exported by
an old worker keeps loading and compiling on every later version.

Bundles are exported two ways: automatically when a settle stamps an
``slo_breach`` event (trigger ``breach``, bounded ring sized by
``incident.max_bundles``), or on demand through the admin API / CLI
(trigger ``manual``).  ``downloader_tpu.incident.compiler`` turns a
bundle into a replayable chaos scenario.
"""

import hashlib
import json
import time
from collections.abc import Mapping
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional

from ..platform.config import cfg_get

SCHEMA_VERSION = 1

# FROZEN wire table (name -> (field number, type label)).  Mirrors the
# proto discipline in tests/test_wire_freeze.py: numbers and types below
# never change; new fields take the next free number.  Unknown fields in
# a newer bundle are preserved by load_bundle (forward compat).
BUNDLE_FIELDS = {
    "schema": (1, "int"),
    "bundleId": (2, "str"),
    "exportedAt": (3, "str"),
    "trigger": (4, "str"),
    "workerId": (5, "str"),
    "job": (6, "object"),
    "timeline": (7, "list"),
    "timelineDropped": (8, "int"),
    "journal": (9, "list"),
    "breaches": (10, "list"),
    "slo": (11, "object"),
    "digest": (12, "object"),
    "hopLedger": (13, "object"),
    "openBreakers": (14, "object"),
    "placement": (15, "object"),
    "plan": (16, "object"),
    "faultPlan": (17, "list"),
    "fleetStats": (18, "object"),
    "breakerPolicy": (19, "object"),
    "sloPolicy": (20, "object"),
    "workload": (21, "object"),
    "configFingerprint": (22, "str"),
}

# the minimal set a bundle must carry to load; everything else degrades
# to an empty value so a truncated bundle still compiles best-effort
REQUIRED_FIELDS = ("schema", "bundleId", "job")

_TYPE_CHECKS = {
    "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "str": lambda v: isinstance(v, str),
    "list": lambda v: isinstance(v, list),
    "object": lambda v: isinstance(v, dict),
}

MAX_JOURNAL_LINES = 2000          # per-bundle bound on journal replay
MAX_JOURNAL_BYTES = 1 << 20       # never read more than 1 MiB of journal

DEFAULT_MAX_BUNDLES = 8

TRIGGER_BREACH = "breach"
TRIGGER_MANUAL = "manual"


class BundleError(ValueError):
    """Raised when a document cannot be loaded as an incident bundle."""


def _utc_iso() -> str:
    return datetime.now(timezone.utc).isoformat()


def _plain(value: Any):
    """Deep-coerce to plain JSON data.  Config sections arrive as
    Mapping views (ConfigNode), and recorder events may carry arbitrary
    kwargs; a bundle must serialize wherever it lands (the ring, the
    admin API, a file), so anything exotic degrades to ``str``."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, Mapping):
        return {str(k): _plain(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [_plain(v) for v in value]
    return str(value)


def config_fingerprint(config) -> str:
    """Stable digest of the effective config, so a replay can assert it
    ran against the same knobs (or show exactly that it did not)."""
    try:
        blob = json.dumps(config, sort_keys=True, default=str)
    except Exception:
        blob = repr(config)
    return hashlib.sha256(blob.encode("utf-8", "replace")).hexdigest()[:16]


def journal_lines_for(path: Optional[str], job_id: str,
                      max_lines: int = MAX_JOURNAL_LINES) -> List[dict]:
    """This job's journal lines (bounded, torn-tail tolerant).

    Reads at most the last MAX_JOURNAL_BYTES of the journal so a breach
    settle never stalls on a huge file; the journal's own rotation keeps
    the live segment far below that in practice.
    """
    if not path or not job_id:
        return []
    lines: List[dict] = []
    try:
        with open(path, "rb") as fh:
            fh.seek(0, 2)
            size = fh.tell()
            fh.seek(max(0, size - MAX_JOURNAL_BYTES))
            raw = fh.read(MAX_JOURNAL_BYTES)
    except OSError:
        return []
    for line in raw.splitlines():
        try:
            doc = json.loads(line)
        except (ValueError, UnicodeDecodeError):
            continue  # torn/partial line: same tolerance as journal.replay
        if isinstance(doc, dict) and doc.get("id") == job_id:
            lines.append(doc)
    return lines[-max_lines:]


def _workload_census(registry, now_mono: float) -> dict:
    """Job-mix context for the compiler: how many jobs of each priority
    class were in flight (or recently settled) when the breach fired,
    which tenants, and over what wall — enough to rebuild an equivalent
    SoakWorkload without shipping every record."""
    mix: Dict[str, int] = {}
    tenants = set()
    earliest = None
    records = []
    try:
        records = registry.jobs()
    except Exception:
        pass
    for rec in records:
        prio = getattr(rec, "priority", "NORMAL") or "NORMAL"
        mix[prio] = mix.get(prio, 0) + 1
        tenant = getattr(rec, "tenant", "") or ""
        if tenant:
            tenants.add(tenant)
        created = getattr(rec, "_created_mono", None)
        if created is not None:
            earliest = created if earliest is None else min(earliest, created)
    wall = round(now_mono - earliest, 3) if earliest is not None else 0.0
    return {
        "jobs": len(records),
        "mix": mix,
        "tenants": sorted(tenants),
        "wallS": max(wall, 0.0),
    }


def _open_breakers(breakers) -> dict:
    """Same shape as orchestrator.slo_digest()'s openBreakers block."""
    out: Dict[str, dict] = {}
    if breakers is None:
        return out
    try:
        reasons = breakers.open_reasons()
        for dep, state in breakers.states().items():
            if state != "closed":
                out[dep] = {"state": state, "reason": reasons.get(dep)}
    except Exception:
        pass
    return out


def _active_fault_plan(injector) -> List[dict]:
    rules = []
    if injector is None or not getattr(injector, "rules", None):
        return rules
    for rule in injector.rules:
        try:
            rules.append(rule.to_dict())
        except Exception:
            continue
    return rules


def _plan_in_force(fleet) -> Optional[dict]:
    if fleet is None:
        return None
    try:
        return fleet.plan_in_force()
    except Exception:
        return None


def build_bundle(orchestrator, record, *, trigger: str = TRIGGER_MANUAL) -> dict:
    """Snapshot one job's forensic state into a schema-v1 bundle.

    Synchronous and best-effort by design: it runs inside the settle
    path on auto-export, so every ingredient degrades to an empty value
    rather than raising.
    """
    recorder = getattr(record, "recorder", None)
    timeline = list(recorder.events()) if recorder is not None else []
    dropped = int(getattr(recorder, "dropped", 0) or 0) if recorder else 0
    breaches = [e for e in timeline if e.get("kind") == "slo_breach"]

    slo = getattr(orchestrator, "slo", None)
    slo_snapshot: dict = {}
    slo_digest: dict = {}
    if slo is not None:
        try:
            slo_snapshot = slo.snapshot()
            slo_digest = slo.digest()
        except Exception:
            pass

    journal = getattr(orchestrator, "journal", None)
    journal_path = getattr(journal, "path", None) if journal else None

    fleet = getattr(orchestrator, "fleet", None)
    fleet_stats: dict = {}
    if fleet is not None:
        try:
            fleet_stats = {
                "fencedWrites": int(fleet.stats.get("fencedWrites", 0)),
                "leaseTtl": float(getattr(fleet, "lease_ttl", 0.0)),
            }
        except Exception:
            fleet_stats = {}

    try:
        hop_ledger = record.hops.summary()
    except Exception:
        hop_ledger = {}

    config = getattr(orchestrator, "config", None) or {}
    job_id = getattr(record, "job_id", "") or ""
    exported_at = _utc_iso()
    seed = f"{job_id}|{exported_at}|{trigger}".encode("utf-8", "replace")
    bundle_id = "inc-" + hashlib.sha256(seed).hexdigest()[:12]

    return _plain({
        "schema": SCHEMA_VERSION,
        "bundleId": bundle_id,
        "exportedAt": exported_at,
        "trigger": trigger,
        "workerId": getattr(orchestrator, "worker_id", "") or "",
        "job": record.to_dict(),
        "timeline": timeline,
        "timelineDropped": dropped,
        "journal": journal_lines_for(journal_path, job_id),
        "breaches": breaches,
        "slo": slo_snapshot,
        "digest": slo_digest,
        "hopLedger": hop_ledger,
        "openBreakers": _open_breakers(getattr(orchestrator, "breakers", None)),
        "placement": {
            "routeKey": getattr(record, "route_key", None),
            "routeDecision": getattr(record, "route_decision", None),
            "planEpoch": getattr(record, "plan_epoch", None),
        },
        "plan": _plan_in_force(fleet),
        "faultPlan": _active_fault_plan(
            getattr(orchestrator, "_fault_injector", None)),
        "fleetStats": fleet_stats,
        "breakerPolicy": dict(cfg_get(config, "breakers", {}) or {}),
        "sloPolicy": dict(cfg_get(config, "slo", {}) or {}),
        "workload": _workload_census(
            getattr(orchestrator, "registry", None), time.monotonic())
        if getattr(orchestrator, "registry", None) is not None else {},
        "configFingerprint": config_fingerprint(config),
    })


def load_bundle(raw: Any) -> dict:
    """Validate a document as an incident bundle, tolerating unknown
    fields (forward compat) and missing optional ones (truncation)."""
    if not isinstance(raw, dict):
        raise BundleError("incident bundle must be a JSON object")
    for field in REQUIRED_FIELDS:
        if field not in raw:
            raise BundleError(f"incident bundle missing field {field!r}")
    schema = raw.get("schema")
    if not isinstance(schema, int) or schema < 1:
        raise BundleError(f"unsupported bundle schema {schema!r}")
    for name, (_num, type_label) in BUNDLE_FIELDS.items():
        if name in raw and raw[name] is not None:
            if not _TYPE_CHECKS[type_label](raw[name]):
                raise BundleError(
                    f"bundle field {name!r} must be {type_label}, "
                    f"got {type(raw[name]).__name__}")
    return dict(raw)  # unknown fields ride along untouched


def bundle_summary(bundle: dict) -> dict:
    """One ring/API row per bundle — enough to pick one to pull."""
    job = bundle.get("job") or {}
    breaches = bundle.get("breaches") or []
    objectives = sorted({
        str(e.get("objective")) for e in breaches if e.get("objective")})
    return {
        "bundleId": bundle.get("bundleId"),
        "schema": bundle.get("schema"),
        "exportedAt": bundle.get("exportedAt"),
        "trigger": bundle.get("trigger"),
        "jobId": job.get("id"),
        "traceId": job.get("traceId"),
        "state": job.get("state"),
        "breaches": len(breaches),
        "objectives": objectives,
        "planEpoch": (bundle.get("placement") or {}).get("planEpoch"),
    }


class IncidentStore:
    """Bounded in-memory ring of exported bundles, newest last.

    The ring (``incident.max_bundles``) bounds worst-case memory the
    same way the registry's terminal ring does: a breach storm evicts
    the oldest bundles instead of growing without bound.
    """

    def __init__(self, *, max_bundles: int = DEFAULT_MAX_BUNDLES,
                 auto_export: bool = True, metrics=None, logger=None):
        self.max_bundles = max(1, int(max_bundles))
        self.auto_export = bool(auto_export)
        self.metrics = metrics
        self.logger = logger
        self._ring: List[dict] = []
        self.exported_total = 0
        #: the latest replay verdict posted back to this worker
        #: (POST /v1/incidents/verdict) — surfaced on the listing
        self.last_verdict: Optional[dict] = None

    @classmethod
    def from_config(cls, config, *, metrics=None,
                    logger=None) -> Optional["IncidentStore"]:
        if not cfg_get(config, "incident.enabled", True):
            return None
        return cls(
            max_bundles=int(cfg_get(
                config, "incident.max_bundles", DEFAULT_MAX_BUNDLES)),
            auto_export=bool(cfg_get(config, "incident.auto_export", True)),
            metrics=metrics, logger=logger,
        )

    def __len__(self) -> int:
        return len(self._ring)

    def add(self, bundle: dict, *, trigger: Optional[str] = None) -> dict:
        trigger = trigger or bundle.get("trigger") or TRIGGER_MANUAL
        self._ring.append(bundle)
        evicted = len(self._ring) - self.max_bundles
        if evicted > 0:
            del self._ring[:evicted]
        self.exported_total += 1
        if self.metrics is not None:
            try:
                self.metrics.incident_bundles.labels(trigger=trigger).inc()
            except Exception:
                pass
        if self.logger is not None:
            try:
                self.logger.info(
                    "incident bundle exported",
                    bundleId=bundle.get("bundleId"), trigger=trigger,
                    jobId=(bundle.get("job") or {}).get("id"),
                    ringSize=len(self._ring))
            except Exception:
                pass
        return bundle_summary(bundle)

    def summaries(self) -> List[dict]:
        return [bundle_summary(b) for b in reversed(self._ring)]

    def get(self, ident: str) -> Optional[dict]:
        """Look a bundle up by bundleId, job id, or trace id (newest
        match wins)."""
        if not ident:
            return None
        for bundle in reversed(self._ring):
            job = bundle.get("job") or {}
            if ident in (bundle.get("bundleId"), job.get("id"),
                         job.get("traceId")):
                return bundle
        return None


def find_record(registry, ident: str):
    """Resolve a job id OR trace id to a registry record."""
    if registry is None or not ident:
        return None
    record = registry.get(ident)
    if record is not None:
        return record
    try:
        for rec in registry.jobs():
            if getattr(rec, "trace_id", None) == ident:
                return rec
    except Exception:
        pass
    return None


def export_incident(orchestrator, ident: str, *,
                    trigger: str = TRIGGER_MANUAL) -> Optional[dict]:
    """Export a bundle for a live or recently-settled job by job id or
    trace id; stores it in the ring when one is configured."""
    record = find_record(getattr(orchestrator, "registry", None), ident)
    if record is None:
        return None
    bundle = build_bundle(orchestrator, record, trigger=trigger)
    store = getattr(orchestrator, "incidents", None)
    if store is not None:
        store.add(bundle, trigger=trigger)
    return bundle
