"""Pure bundle -> replay-scenario compiler.

``compile_bundle`` turns an incident bundle into a deterministic chaos
scenario: a ``FAULT_PLAN`` (the degradation windows that were in force,
re-anchored to replay t0) plus a ``SoakProfile`` parameter set that
reproduces the same job mix, relative timing, priority classes, and
breaker/SLO policy — so the PR 13 soak machinery (SoakWorkload +
SoakRig) drives the replay unchanged.

PURITY CONTRACT: this module never reads the clock, the environment, or
any RNG.  Compiling the same bundle twice yields byte-identical
scenarios (tests/test_incident.py::test_compile_bundle_is_pure), which
is what makes "replay twice, same signature" a meaningful guard rather
than a coin flip.  Window re-anchoring is arithmetic on the bundle's
own ``start_s`` values: the windowed kinds are already expressed
relative to injector install, so the replay keeps every relative offset
and merely floors ``start_s`` at ``lead_s`` (the replay fleet needs a
beat to come up before the first window opens).
"""

import json
from typing import Any, Dict, List

from ..platform.faults import RULE_FIELDS, WINDOWED_KINDS
from .bundle import load_bundle
from .replay import bundle_signature

#: the replay fleet needs this long to boot before the first window
DEFAULT_LEAD_S = 1.0
#: replay job-count clamp: enough jobs to reproduce a mix-dependent
#: breach, few enough that `incident replay` stays minutes not hours
REPLAY_JOB_FLOOR = 6
REPLAY_JOB_CAP = 24
#: publish-rate clamp (jobs/s) when deriving relative timing from the
#: bundle's observed wall
MIN_PUBLISH_RATE = 1.0
MAX_PUBLISH_RATE = 6.0
DEFAULT_PUBLISH_RATE = 2.5
#: replay wall guard — generous vs the clamped job count
REPLAY_MAX_WALL_S = 110.0

DEFAULT_LEASE_TTL_S = 2.0


def _reanchor_rule(raw: dict, lead_s: float) -> dict:
    """One fault rule, re-anchored to replay t0.

    Keeps only the declarative RULE_FIELDS (a bundle from a newer
    version may carry keys this version's FaultRule would reject) and
    floors windowed starts at ``lead_s`` while preserving every
    relative offset between windows.
    """
    rule = {k: raw[k] for k in RULE_FIELDS if k in raw}
    if rule.get("kind") in WINDOWED_KINDS:
        try:
            start = float(rule.get("start_s", 0.0))
        except (TypeError, ValueError):
            start = 0.0
        rule["start_s"] = max(start, lead_s)
    return rule


def _derive_fractions(workload: dict) -> Dict[str, Any]:
    """Job mix -> SoakWorkload lane fractions.

    The hot lane alternates HIGH/NORMAL priorities, so reproducing N
    HIGH jobs takes a hot lane of ~2N; the bulk lane is 1:1 with BULK
    records.  Everything left lands in the plain NORMAL lane.
    """
    mix = workload.get("mix") or {}
    total = sum(int(v) for v in mix.values() if isinstance(v, int))
    if total <= 0:
        # empty census (e.g. a truncated bundle): fall back to the
        # degraded-profile defaults rather than a zero-job replay
        return {"hot_fraction": 0.5, "bulk_fraction": 0.25}
    high = int(mix.get("HIGH", 0))
    bulk = int(mix.get("BULK", 0))
    hot = min(round(2.0 * high / total, 3), 0.6)
    return {
        "hot_fraction": hot,
        "bulk_fraction": min(round(bulk / total, 3), 0.5),
    }


def _derive_publish_rate(workload: dict) -> float:
    """Relative timing: the bundle's observed jobs-over-wall, clamped.
    A bundle without a usable wall replays at the degraded default."""
    jobs = workload.get("jobs") or 0
    wall = workload.get("wallS") or 0.0
    try:
        jobs, wall = int(jobs), float(wall)
    except (TypeError, ValueError):
        return DEFAULT_PUBLISH_RATE
    if jobs <= 0 or wall <= 0.0:
        return DEFAULT_PUBLISH_RATE
    return round(min(max(jobs / wall, MIN_PUBLISH_RATE), MAX_PUBLISH_RATE), 2)


def compile_bundle(bundle: dict, *, lead_s: float = DEFAULT_LEAD_S) -> dict:
    """Compile an incident bundle into a replayable scenario (pure).

    Returns a plain JSON-able dict::

        {
          "schema":     bundle schema the scenario was compiled from,
          "source":     bundleId,
          "signature":  the original breach signature (the diff target),
          "faultPlan":  [rule dicts]  # FAULT_PLAN, re-anchored to t0
          "profile":    {SoakProfile.degraded(**profile) overrides},
          "leadS":      the re-anchor floor used,
        }
    """
    bundle = load_bundle(bundle)
    workload = bundle.get("workload") or {}
    fleet_stats = bundle.get("fleetStats") or {}

    fault_plan: List[dict] = [
        _reanchor_rule(r, lead_s)
        for r in (bundle.get("faultPlan") or []) if isinstance(r, dict)
    ]
    brownout_starts = [
        float(r.get("start_s", 0.0)) for r in fault_plan
        if r.get("kind") == "brownout"
    ]

    try:
        lease_ttl = float(fleet_stats.get("leaseTtl") or DEFAULT_LEASE_TTL_S)
    except (TypeError, ValueError):
        lease_ttl = DEFAULT_LEASE_TTL_S
    lease_ttl = min(max(lease_ttl, 1.0), 8.0)

    # a fenced write in the original means a stalled/stale leader lost
    # a race: replay re-creates it with one SIGSTOP stall held past the
    # lease TTL (the PR 14 stalled-leader drill)
    fenced = int(fleet_stats.get("fencedWrites") or 0)
    stalls = 1 if fenced > 0 else 0

    jobs = workload.get("jobs") or 0
    try:
        jobs = int(jobs)
    except (TypeError, ValueError):
        jobs = 0
    profile: Dict[str, Any] = {
        "jobs": min(max(jobs, REPLAY_JOB_FLOOR), REPLAY_JOB_CAP),
        "publish_rate": _derive_publish_rate(workload),
        "lease_ttl": lease_ttl,
        "stalls": stalls,
        "stall_interval": round(lead_s * 2.0, 3),
        "stall_duration": round(lease_ttl * 2.0, 3),
        "fault_plan": json.dumps(fault_plan, sort_keys=True),
        "brownout_start_s": min(brownout_starts) if brownout_starts else 0.0,
        "max_wall": REPLAY_MAX_WALL_S,
        **_derive_fractions(workload),
    }
    # the original breaker/SLO policy verbatim: the replay must trip
    # the same slow-call policy and burn the same budgets
    if bundle.get("breakerPolicy"):
        profile["breakers"] = bundle["breakerPolicy"]
    if bundle.get("sloPolicy"):
        profile["slo"] = bundle["sloPolicy"]

    return {
        "schema": bundle.get("schema"),
        "source": bundle.get("bundleId"),
        "signature": bundle_signature(bundle),
        "faultPlan": fault_plan,
        "profile": profile,
        "leadS": lead_s,
    }


def scenario_fault_plan_json(scenario: dict) -> str:
    """The scenario's FAULT_PLAN as the env-var JSON the injector reads."""
    return json.dumps(scenario.get("faultPlan") or [], sort_keys=True)


def scenario_profile(scenario: dict, **overrides):
    """Materialize the scenario as a SoakProfile (degraded-world base +
    the compiled overrides).  Imported lazily so compile_bundle stays
    usable without the soak package on the path."""
    from ..soak import SoakProfile

    params = dict(scenario.get("profile") or {})
    params.update(overrides)
    return SoakProfile.degraded(**params)
