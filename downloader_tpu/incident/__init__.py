"""Incident plane (ROADMAP item 5; ISSUE 18 tentpole).

Every forensic ingredient grown since the fault-tolerance layer — the
durable per-job journal (PR 8), the flight recorder + cross-worker trace
assembly (PR 9), ``slo_breach`` events with burn/budget context (PR 15),
the windowed fault plane (PR 14), and the SoakRig (PR 13) — existed as a
silo.  This package is the join: any production trace becomes a
repeatable, guard-checked chaos scenario.

- :mod:`~.bundle` — versioned (schema v1, frozen field table) forensic
  bundles: timeline, journal lines, breaches, hop ledger, open-breaker
  reasons, placement context, fault plan, config fingerprint.
  Auto-exported on breach into a bounded ring (``incident.max_bundles``)
  and served on ``GET /v1/incidents``.
- :mod:`~.compiler` — the PURE bundle -> scenario compiler: a
  ``FAULT_PLAN`` with degradation windows re-anchored to replay t0 plus
  ``SoakProfile`` overrides reproducing the job mix, relative timing and
  policy, driven by the PR 13 soak machinery unchanged.
- :mod:`~.replay` — breach signatures (`objective classes, open-breaker
  dependency+reason, guilty hop, fencing`), replay-fleet bundle
  collection, and ``diff_signatures`` — same signature => reproduced; a
  replay that comes back green after a fix is a verified fix.
- :mod:`~.fuzz` — the deterministic scenario fuzzer behind
  ``make fuzz-scenarios`` (opt-in, deliberately not CI): seeded
  mutations of a compiled plan hunting for NEW breach signatures.
"""

from .bundle import (BUNDLE_FIELDS, SCHEMA_VERSION, TRIGGER_BREACH,
                     TRIGGER_MANUAL, BundleError, IncidentStore,
                     build_bundle, bundle_summary, config_fingerprint,
                     export_incident, find_record, load_bundle)
from .compiler import compile_bundle, scenario_fault_plan_json, \
    scenario_profile
from .fuzz import fuzz_scenarios, mutate_scenario
from .replay import (EMPTY_SIGNATURE, SIGNATURE_FIELDS, bundle_signature,
                     collect_incidents, diff_signatures,
                     signature_from_incidents)

__all__ = [
    "BUNDLE_FIELDS", "SCHEMA_VERSION", "TRIGGER_BREACH", "TRIGGER_MANUAL",
    "BundleError", "IncidentStore", "build_bundle", "bundle_summary",
    "config_fingerprint", "export_incident", "find_record", "load_bundle",
    "compile_bundle", "scenario_fault_plan_json", "scenario_profile",
    "fuzz_scenarios", "mutate_scenario",
    "EMPTY_SIGNATURE", "SIGNATURE_FIELDS", "bundle_signature",
    "collect_incidents", "diff_signatures", "signature_from_incidents",
]
