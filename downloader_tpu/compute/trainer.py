"""Training driver: fit the upscaler on real media, self-supervised.

The reference has no training of any kind (SURVEY §5 — no tensor
compute); this driver completes the compute surface's loop so the model
the ``upscale`` stage runs can actually be produced inside the
framework: decode Y4M media (the same format the stage consumes), cut
high-res crops, synthesize the low-res inputs by box-downsampling, and
minimize reconstruction MSE with the jitted train step from
:mod:`.train` — on one chip or the full (data x model) mesh, with
orbax checkpoints that the stage's ``checkpoint_dir`` option loads
directly.

TPU-first notes: the hot loop is ONE jitted step with donated state
(no host round-trips besides the scalar loss and the next batch); batch
size is rounded up to the data-axis size so every device gets equal
shards; host-side data prep is numpy (the device never sees decode
work).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from .video import Y4MReader

# numpy mirror of ops/colorspace's BT.601 full-range inverse (device code
# uses the jnp version; data prep stays on the host by design)
_YCC2RGB = np.array(
    [
        [1.0, 0.0, 1.402],
        [1.0, -0.344136, -0.714136],
        [1.0, 1.772, 0.0],
    ],
    dtype=np.float32,
)


@dataclasses.dataclass(frozen=True)
class TrainerSettings:
    steps: int = 200
    batch: int = 8
    crop: int = 64  # high-res crop edge; LR input is crop/scale
    learning_rate: float = 1e-3
    checkpoint_dir: Optional[str] = None
    save_every: int = 100
    log_every: int = 20
    seed: int = 0
    model_axis: int = 1
    # model geometry — must match the ``instance.upscale.*`` config of
    # the stage that will load the checkpoint
    scale: int = 2
    features: int = 128
    depth: int = 4


def _frame_to_rgb(y: np.ndarray, cb: np.ndarray, cr: np.ndarray,
                  sub_h: int, sub_w: int) -> np.ndarray:
    """Planar uint8 YCbCr (subsampled chroma) -> HxWx3 float32 RGB in
    [0, 1]; nearest-neighbor chroma upsample, matching the device path."""
    yf = y.astype(np.float32)
    cbf = cb.astype(np.float32).repeat(sub_h, axis=0).repeat(sub_w, axis=1)
    crf = cr.astype(np.float32).repeat(sub_h, axis=0).repeat(sub_w, axis=1)
    ycc = np.stack([yf, cbf - 128.0, crf - 128.0], axis=-1)
    return np.clip(ycc @ _YCC2RGB.T, 0.0, 255.0) / 255.0


def hr_crop_stream(paths: Sequence[str], crop: int,
                   rng: np.random.Generator) -> Iterator[np.ndarray]:
    """Endless stream of (crop, crop, 3) float32 RGB crops from Y4M files.

    Files cycle; each decoded frame yields one random crop (cheap decode
    amortization without holding whole files in memory)."""
    if not paths:
        raise ValueError("no training media given")
    while True:
        for path in paths:
            with open(path, "rb") as fh:
                reader = Y4MReader(fh)
                sub_h, sub_w = reader.header.subsampling
                if (reader.header.height < crop
                        or reader.header.width < crop):
                    raise ValueError(
                        f"{path}: {reader.header.width}x"
                        f"{reader.header.height} smaller than crop {crop}"
                    )
                for y, cb, cr in reader:
                    rgb = _frame_to_rgb(y, cb, cr, sub_h, sub_w)
                    top = int(rng.integers(0, rgb.shape[0] - crop + 1))
                    left = int(rng.integers(0, rgb.shape[1] - crop + 1))
                    yield rgb[top:top + crop, left:left + crop]


def box_downsample(hr: np.ndarray, scale: int) -> np.ndarray:
    """(..., H, W, 3) -> (..., H/scale, W/scale, 3) by box mean — the
    degradation model pairing LR inputs with HR targets."""
    *lead, h, w, c = hr.shape
    hr = hr.reshape(*lead, h // scale, scale, w // scale, scale, c)
    return hr.mean(axis=(-4, -2))


def discover_media(data: str) -> List[str]:
    """A .y4m file, or a directory scanned (sorted) for .y4m files."""
    if os.path.isfile(data):
        return [data]
    found = sorted(
        os.path.join(data, name)
        for name in os.listdir(data)
        if name.endswith(".y4m")
    )
    if not found:
        raise FileNotFoundError(f"no .y4m media under {data}")
    return found


def train(paths: Sequence[str], settings: TrainerSettings = TrainerSettings(),
          log: Optional[Callable[[str], None]] = None) -> dict:
    """Run the training loop; returns a summary dict (final step/loss).

    Resumes from ``checkpoint_dir``'s latest step when one exists, so a
    preempted run continues — single-chip and mesh states are
    interchangeable (see :mod:`.checkpoint`).
    """
    import jax

    from .checkpoint import restore_state, save_state
    from .models.upscaler import UpscalerConfig
    from .parallel.mesh import make_mesh, shard_batch, shard_params
    from .train import make_train_step

    emit = log or (lambda _line: None)
    config = UpscalerConfig(
        scale=settings.scale,
        features=settings.features,
        depth=settings.depth,
    )
    scale = config.scale
    if settings.crop % scale:
        raise ValueError(f"crop {settings.crop} not divisible by scale {scale}")

    n_devices = len(jax.devices())
    plan = None
    if n_devices > 1:
        model_axis = settings.model_axis
        if n_devices % model_axis:
            raise ValueError(
                f"{n_devices} devices not divisible by model axis {model_axis}"
            )
        plan = make_mesh(n_devices, model_axis=model_axis)

    # equal shards per data-axis device
    data_axis = plan.mesh.shape["data"] if plan is not None else 1
    batch = -(-settings.batch // data_axis) * data_axis

    train_step, init_state = make_train_step(
        config, learning_rate=settings.learning_rate
    )
    rng = jax.random.PRNGKey(settings.seed)
    lr_edge = settings.crop // scale
    params, opt_state = init_state(rng, sample_shape=(1, lr_edge, lr_edge, 3))

    start_step = 0
    if settings.checkpoint_dir and os.path.isdir(settings.checkpoint_dir):
        try:
            start_step, params, opt_state = restore_state(
                settings.checkpoint_dir, params, opt_state, plan=plan
            )
            emit(f"resumed from step {start_step}")
        except FileNotFoundError:
            pass

    if plan is not None:
        params = shard_params(plan, params)
        opt_state = shard_params(plan, opt_state)

    step_fn = jax.jit(train_step, donate_argnums=(0, 1))
    crops = hr_crop_stream(paths, settings.crop, np.random.default_rng(settings.seed))

    last_loss = float("nan")
    loss = None
    started = time.monotonic()
    step = start_step
    for step in range(start_step + 1, start_step + settings.steps + 1):
        hr = np.stack([next(crops) for _ in range(batch)])
        lr = box_downsample(hr, scale).astype(np.float32)
        if plan is not None:
            lr = shard_batch(plan, lr)
            hr = shard_batch(plan, hr)
            with plan.mesh:
                params, opt_state, loss = step_fn(params, opt_state, lr, hr)
        else:
            params, opt_state, loss = step_fn(params, opt_state, lr, hr)
        if step % settings.log_every == 0 or step == start_step + 1:
            last_loss = float(loss)
            rate = (step - start_step) / (time.monotonic() - started)
            # signals live in [0,1], so PSNR = -10 log10(MSE) directly
            psnr = -10.0 * np.log10(max(last_loss, 1e-12))
            emit(f"step {step} loss {last_loss:.6f} "
                 f"psnr {psnr:.2f}dB ({rate:.1f} steps/s)")
        if settings.checkpoint_dir and step % settings.save_every == 0:
            save_state(settings.checkpoint_dir, step, params, opt_state)
            emit(f"checkpoint saved at step {step}")
    if loss is not None:
        last_loss = float(loss)

    if settings.checkpoint_dir and settings.steps:
        save_state(settings.checkpoint_dir, step, params, opt_state)
        emit(f"checkpoint saved at step {step}")
    return {
        "final_step": step,
        "final_loss": last_loss,
        "final_psnr_db": -10.0 * float(np.log10(max(last_loss, 1e-12))),
        "batch": batch,
        "devices": n_devices,
        "mesh": dict(plan.mesh.shape) if plan is not None else None,
    }
