"""Flagship model: an efficient sub-pixel video-frame upscaler.

ESPCN-style super-resolution (conv feature extraction + sub-pixel pixel
shuffle) — the classic "media transcode/upscale" workload the pipeline's
downstream converter would run.  TPU-first choices:

- NHWC layout with channel counts that are multiples of the 128-lane vector
  register width, so XLA tiles convs onto the MXU without padding
- bfloat16 activations/params with fp32 loss accumulation
- static shapes only; the whole forward is one fused XLA computation
- feature (channel) dimension is shardable for tensor parallelism
  (see ``compute.parallel``)
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.pixel_shuffle import pixel_shuffle


@dataclasses.dataclass(frozen=True)
class UpscalerConfig:
    scale: int = 2              # spatial upscale factor
    features: int = 128         # conv width (multiple of 128 for MXU/VPU)
    depth: int = 4              # number of hidden conv layers
    channels: int = 3           # RGB
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16


class Upscaler(nn.Module):
    """(B, H, W, C) -> (B, H*scale, W*scale, C)

    :meth:`backbone` exposes the pre-shuffle sub-pixel maps
    (B, H, W, C*scale^2) — the inference engine's fused output tail does
    colorspace + quantize in the sub-pixel domain BEFORE the shuffle
    (measured 33% off the 720p stage step on a v5e, BASELINE.md r3).
    :meth:`trunk` exposes the pre-head features (B, H, W, features) —
    the engine's s2d head (r4) replaces the lane-starved C_out=scale^2*3
    head conv with a stride-2 packed conv built from the SAME ``subpixel``
    params (see ``ops.s2d_head``).  The param tree is identical on every
    path (``stem``, ``body_i``, ``subpixel`` — setup-defined so all three
    entry points share one set of submodules).
    """

    config: UpscalerConfig = UpscalerConfig()

    def setup(self):
        cfg = self.config
        self.stem = nn.Conv(
            cfg.features, (5, 5), padding="SAME",
            dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
        )
        self.body = [
            nn.Conv(
                cfg.features, (3, 3), padding="SAME",
                dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
            )
            for _ in range(cfg.depth - 1)
        ]
        # project to scale^2 * channels sub-pixel maps
        self.subpixel = nn.Conv(
            cfg.channels * cfg.scale * cfg.scale, (3, 3), padding="SAME",
            dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
        )

    def trunk(self, frames: jax.Array) -> jax.Array:
        """Stem + residual body: the pre-head feature maps."""
        x = frames.astype(self.config.compute_dtype)
        x = nn.relu(self.stem(x))
        for conv in self.body:
            x = nn.relu(conv(x)) + x  # residual keeps deep stacks trainable
        return x

    def backbone(self, frames: jax.Array) -> jax.Array:
        return self.subpixel(self.trunk(frames))

    def __call__(self, frames: jax.Array) -> jax.Array:
        return pixel_shuffle(self.backbone(frames), self.config.scale)


def init_params(rng: jax.Array, config: UpscalerConfig = UpscalerConfig(),
                sample_shape=(1, 32, 32, 3)):
    model = Upscaler(config)
    params = model.init(rng, jnp.zeros(sample_shape, jnp.float32))
    return model, params


def param_paths(config: UpscalerConfig = UpscalerConfig()) -> "list[str]":
    """Every param leaf path (``/``-joined, under the flax ``params``
    collection) the model creates — derivable from the config alone, no
    init needed.  The partition-table coverage test checks the regex →
    PartitionSpec rules against THIS list, so a new submodule shows up
    as a failing rule match before it ever reaches a mesh."""
    mods = ["stem"] + [f"body_{i}" for i in range(config.depth - 1)]
    mods.append("subpixel")
    return [f"params/{m}/{leaf}" for m in mods for leaf in ("kernel", "bias")]
