"""Flagship model: an efficient sub-pixel video-frame upscaler.

ESPCN-style super-resolution (conv feature extraction + sub-pixel pixel
shuffle) — the classic "media transcode/upscale" workload the pipeline's
downstream converter would run.  TPU-first choices:

- NHWC layout with channel counts that are multiples of the 128-lane vector
  register width, so XLA tiles convs onto the MXU without padding
- bfloat16 activations/params with fp32 loss accumulation
- static shapes only; the whole forward is one fused XLA computation
- feature (channel) dimension is shardable for tensor parallelism
  (see ``compute.parallel``)
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.pixel_shuffle import pixel_shuffle


@dataclasses.dataclass(frozen=True)
class UpscalerConfig:
    scale: int = 2              # spatial upscale factor
    features: int = 128         # conv width (multiple of 128 for MXU/VPU)
    depth: int = 4              # number of hidden conv layers
    channels: int = 3           # RGB
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16


class Upscaler(nn.Module):
    """(B, H, W, C) -> (B, H*scale, W*scale, C)

    :meth:`backbone` exposes the pre-shuffle sub-pixel maps
    (B, H, W, C*scale^2) — the inference engine's fused output tail does
    colorspace + quantize in the sub-pixel domain BEFORE the shuffle
    (measured 33% off the 720p stage step on a v5e, BASELINE.md r3), so
    it needs the tensor the pixel shuffle would consume.  The param tree
    is identical either way.
    """

    config: UpscalerConfig = UpscalerConfig()

    @nn.compact
    def backbone(self, frames: jax.Array) -> jax.Array:
        cfg = self.config
        x = frames.astype(cfg.compute_dtype)

        x = nn.Conv(
            cfg.features, (5, 5), padding="SAME",
            dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
            name="stem",
        )(x)
        x = nn.relu(x)

        for i in range(cfg.depth - 1):
            residual = x
            x = nn.Conv(
                cfg.features, (3, 3), padding="SAME",
                dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
                name=f"body_{i}",
            )(x)
            x = nn.relu(x) + residual  # residual keeps deep stacks trainable

        # project to scale^2 * channels sub-pixel maps
        return nn.Conv(
            cfg.channels * cfg.scale * cfg.scale, (3, 3), padding="SAME",
            dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
            name="subpixel",
        )(x)

    def __call__(self, frames: jax.Array) -> jax.Array:
        return pixel_shuffle(self.backbone(frames), self.config.scale)


def init_params(rng: jax.Array, config: UpscalerConfig = UpscalerConfig(),
                sample_shape=(1, 32, 32, 3)):
    model = Upscaler(config)
    params = model.init(rng, jnp.zeros(sample_shape, jnp.float32))
    return model, params
