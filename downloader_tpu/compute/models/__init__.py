from .upscaler import Upscaler, UpscalerConfig

__all__ = ["Upscaler", "UpscalerConfig"]
