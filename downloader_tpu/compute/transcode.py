"""External decode / encode front- and back-ends around the upscaler.

The pipeline deals exclusively in compressed containers (the process
stage's extension whitelist — reference lib/process.js:15-20), but the
TPU engine speaks raw planar Y4M.  This module closes the gap in BOTH
directions with external codec subprocesses, streaming — no intermediate
raw file ever touches disk:

    decoder:  <binary> -i <src> -f yuv4mpegpipe -pix_fmt yuv420p -loglevel error -
                  |  (y4m over a pipe)
    engine.upscale_to(decoder.stdout, encoder.stdin)
                  |  (upscaled y4m over a pipe)
    encoder:  <binary> -y -f yuv4mpegpipe -i - -loglevel error <args...> <dst>

``ffmpeg`` satisfies the contract out of the box and is the production
default for both ends; any binary speaking the same flag subset works
(e.g. the in-repo OpenCV-backed ``downloader-tpu-codec`` shim for hosts
without ffmpeg).  Either end is optional: decoder-only emits raw Y4M
(the pre-encode behavior), encoder-only ingests an already-raw Y4M
source, neither reduces to plain file-to-file upscaling.

Subprocess hygiene, shared by both ends:

- stderr goes to a temp FILE, never a pipe — a chatty codec could fill a
  pipe buffer and deadlock against our stream reads/writes; the tail is
  replayed into the raised error instead.
- stdin of the DECODER is /dev/null: ffmpeg with an inherited tty
  enables interactive key handling (a stray 'q' kills the decode).
  The encoder's stdin IS the y4m stream, so it gets ``-y`` — without it
  an existing dst makes ffmpeg prompt for overwrite confirmation ON
  STDIN, eating the start of the stream and hanging the transcode.
"""

from __future__ import annotations

import glob
import itertools
import os
import subprocess
import tempfile
from typing import Optional, Sequence

from ..utils.stale import PART_TEMP_RE as _PART_RE
from ..utils.stale import probe_stale

# per-call-unique temp suffix: two concurrent transcodes to the same dst
# in one process must not interleave into one temp (same lesson as the
# fs store's ingest temps); naming pattern + reclaim policy are shared
# with the fs store in utils/stale.py
_PART_SEQ = itertools.count()

# x264 in a matroska container: the downstream converter's own deliverable
# class (reference pipeline containers, lib/process.js:15-20).  CRF 18 is
# visually-lossless-grade for upscaled content; veryfast keeps the encoder
# off the critical path of the device pipeline.
DEFAULT_ENCODE_ARGS = ("-c:v", "libx264", "-preset", "veryfast", "-crf", "18")


def decoder_command(binary: str, src: str) -> list:
    return [binary, "-i", src, "-f", "yuv4mpegpipe", "-pix_fmt", "yuv420p",
            "-loglevel", "error", "-"]


def encoder_command(binary: str, dst: str,
                    encode_args: Sequence[str]) -> list:
    return [binary, "-y", "-f", "yuv4mpegpipe", "-i", "-",
            "-loglevel", "error", *encode_args, dst]


def _tail(err_fh) -> str:
    err_fh.seek(0)
    return err_fh.read()[-500:].decode("utf-8", errors="replace").strip()


def transcode(
    engine,
    src: str,
    dst: str,
    *,
    decoder: Optional[str] = None,
    encoder: Optional[str] = None,
    encode_args: Sequence[str] = DEFAULT_ENCODE_ARGS,
    depth: int = 3,
) -> int:
    """Run ``src`` through (decode ->) upscale (-> encode) into ``dst``.

    Returns the number of frames processed.  Raises ``RuntimeError``
    with the failing codec's stderr tail on subprocess failure.  The
    output is written to a per-call-unique same-directory temp name
    (extension preserved — encoders infer the muxer from it) and
    renamed onto ``dst`` only after every process exited cleanly: a
    pre-existing ``dst`` survives ANY failure untouched, no partial
    output is ever visible under the final name, and no stat heuristics
    are needed (coarse-mtime filesystems made the old caller-side ones
    false-negative; review r4).  Temps orphaned by SIGKILL are reclaimed
    on the next transcode to the same ``dst`` once their writer pid is
    dead AND a cross-host grace period has passed (the pid probe is
    host-local — see :func:`..utils.stale.probe_stale`); within the
    grace window a redelivered job is still safe because the media walk
    skips part-temp names outright (``stages/process.py``).
    """
    _reclaim_stale_parts(dst)
    ext = os.path.splitext(dst)[1]
    tmp_dst = f"{dst}.part-{os.getpid()}.{next(_PART_SEQ)}{ext}"
    try:
        frames = _transcode(engine, src, tmp_dst, decoder, encoder,
                            encode_args, depth)
        os.replace(tmp_dst, dst)
        return frames
    except BaseException:
        try:
            os.unlink(tmp_dst)
        except OSError:
            pass
        raise


def _reclaim_stale_parts(dst: str) -> None:
    """Unlink ``dst``'s temp outputs whose writer process is gone; a
    LIVE pid may be a concurrent transcode racing for the same dst —
    leave its temp alone (its rename decides the race)."""
    for path in glob.glob(glob.escape(dst) + ".part-*"):
        match = _PART_RE.search(path)
        if match is None:
            continue
        stale, _age = probe_stale(path, int(match.group(1)))
        if stale:
            try:
                os.unlink(path)
            except OSError:
                pass


def _transcode(engine, src, dst, decoder, encoder, encode_args,
               depth) -> int:
    from .video import Y4MError

    dec = enc = None
    dec_err = enc_err = None
    try:
        dec_err = tempfile.TemporaryFile()
        enc_err = tempfile.TemporaryFile()
        if decoder is not None:
            dec = subprocess.Popen(
                decoder_command(decoder, src),
                stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
                stderr=dec_err,
            )
            src_fh = dec.stdout
        else:
            src_fh = open(src, "rb")
        try:
            if encoder is not None:
                enc = subprocess.Popen(
                    encoder_command(encoder, dst, encode_args),
                    stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
                    stderr=enc_err,
                )
                dst_fh = enc.stdin
                try:
                    frames = engine.upscale_to(src_fh, dst_fh, depth=depth)
                finally:
                    # EOF to the encoder even on failure paths: wait()
                    # below must not hang on an encoder still reading
                    try:
                        dst_fh.close()
                    except (BrokenPipeError, OSError):
                        pass
            else:
                with open(dst, "wb") as dst_fh:
                    frames = engine.upscale_to(src_fh, dst_fh, depth=depth)
        finally:
            if dec is None:
                src_fh.close()

        if enc is not None and enc.wait() != 0:
            raise RuntimeError(
                f"encoder exited {enc.returncode}: {_tail(enc_err)}"
            )
        if dec is not None and dec.wait() != 0:
            raise RuntimeError(
                f"decoder exited {dec.returncode}: {_tail(dec_err)}"
            )
        return frames

    except Y4MError as exc:
        # the y4m stream itself was bad.  With a decoder in front that
        # means the DECODER failed — wrap with its exit code and stderr;
        # a corrupt raw source propagates as the (already clear) Y4MError.
        if dec is not None:
            dec.kill()
            rc = dec.wait()
            raise RuntimeError(
                f"decoder produced invalid y4m (exit {rc}): {exc}; "
                f"{_tail(dec_err)}"
            ) from exc
        raise
    except BrokenPipeError as exc:
        if enc is None:
            raise  # dst itself is a broken pipe (e.g. a FIFO consumer died)
        # the ENCODER died under us mid-stream; its stderr says why
        raise RuntimeError(
            f"encoder exited {enc.wait()} mid-stream: {_tail(enc_err)}"
        ) from exc
    finally:
        for proc in (dec, enc):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
        for fh in (dec_err, enc_err):
            if fh is not None:
                fh.close()
