"""Training step for the upscaler (used by the multi-chip dry run and the
compute benchmarks).

One jitted function: forward (bfloat16) -> fp32 MSE -> grads -> adam update.
Sharding comes entirely from the input placements (params tensor-parallel on
``model``, batch split on ``data``); XLA inserts the gradient psums over the
mesh.  ``jax.checkpoint`` on the forward trades recompute for activation
memory, which is what you want for large frame batches in HBM.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import optax

from .models.upscaler import Upscaler, UpscalerConfig


def make_train_step(config: UpscalerConfig = UpscalerConfig(),
                    learning_rate: float = 1e-3):
    """Returns (train_step, init_state) for ``loss = MSE(model(lr), hr)``."""
    model = Upscaler(config)
    tx = optax.adam(learning_rate)

    @jax.checkpoint
    def forward(params, low_res):
        return model.apply(params, low_res)

    def loss_fn(params, low_res, high_res):
        pred = forward(params, low_res)
        # fp32 accumulation for the reduction regardless of compute dtype
        err = pred.astype(jnp.float32) - high_res.astype(jnp.float32)
        return jnp.mean(err * err)

    def train_step(params, opt_state, low_res, high_res
                   ) -> Tuple[Any, Any, jax.Array]:
        loss, grads = jax.value_and_grad(loss_fn)(params, low_res, high_res)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_state(rng: jax.Array, sample_shape=(1, 32, 32, 3)):
        params = model.init(rng, jnp.zeros(sample_shape, jnp.float32))
        opt_state = tx.init(params)
        return params, opt_state

    return train_step, init_state


def compile_train_step(config: UpscalerConfig = UpscalerConfig(),
                       mesh=None, learning_rate: float = 1e-3,
                       donate: bool = True, in_shardings=None):
    """``make_train_step`` compiled through the pjit-vs-shard_map
    chooser with the state args donated.

    This is where buffer donation is REAL: ``params``/``opt_state`` go
    in and come back the same shapes and dtypes, so XLA aliases them in
    place — the old state's HBM is never resident alongside the new
    (the caller's input arrays are consumed; ``is_deleted()`` afterwards,
    pinned by tests).  Sharding comes from the input placements unless
    explicit ``in_shardings`` are passed (then the chooser takes the
    pjit route).

    Returns ``(step, init_state, decision)``.
    """
    from .parallel.chooser import compile_step

    train_step, init_state = make_train_step(config, learning_rate)
    step, decision = compile_step(
        train_step, mesh, in_shardings=in_shardings,
        donate_argnums=(0, 1) if donate else ())
    return step, init_state, decision
