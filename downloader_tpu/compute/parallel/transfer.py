"""Double-buffered host↔device transfer queue + per-hop billing.

The upscale step is three hops, not one: ``h2d`` (stage the planes onto
the mesh), ``compute`` (the XLA step itself), ``d2h`` (gather display
planes back).  Serializing them is where the 0.065 pipeline overlap
came from — the device idled while the host copied.  ``TransferQueue``
keeps ``depth`` batches in flight: while batch N computes, batch N+1's
h2d is already enqueued and batch N-1's d2h drains via
``copy_to_host_async`` started at dispatch time.

Billing: each hop is timed at the point the host actually blocks, so
the numbers are honest on an async-dispatch backend —

- ``h2d``: wall time of the placement call.  Async backends make this
  near-zero until the transfer queue backs up; a regression that turns
  staging synchronous balloons exactly this hop.
- ``compute``: wall time of ``block_until_ready`` at drain.
- ``d2h``: wall time of the host gather after the result is ready
  (mostly prefetched by the async copy — that's the point).

``HopSink`` carries the billing target as thread-local state so a
worker thread deep inside ``engine.upscale_to`` can bill the current
job's HopLedger without threading a parameter through the decoder
stack.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Callable, Iterator, Optional

Sink = Callable[[str, int, float], None]


class HopSink:
    """Thread-local hop billing target.

    ``bound(note_hop)`` installs a sink for the current thread;
    ``note`` forwards to it (or drops the sample when unbound, so the
    engine works identically outside a job context — benches, tests,
    direct calls).
    """

    def __init__(self) -> None:
        self._local = threading.local()

    @contextlib.contextmanager
    def bound(self, note_hop: Sink):
        prev = getattr(self._local, "sink", None)
        self._local.sink = note_hop
        try:
            yield
        finally:
            self._local.sink = prev

    def note(self, hop: str, nbytes: int, seconds: float) -> None:
        sink = getattr(self._local, "sink", None)
        if sink is not None:
            sink(hop, nbytes, seconds)


@contextlib.contextmanager
def timed_hop(sink: Optional[HopSink], hop: str, nbytes: int):
    """Bill ``hop`` with the wall time of the enclosed block."""
    if sink is None:
        yield
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        sink.note(hop, nbytes, time.monotonic() - t0)


class TransferQueue:
    """Bounded in-flight queue of dispatched device batches.

    ``dispatch(*args)`` must enqueue device work and return a handle;
    ``fetch(handle)`` must block until that work is done and return the
    host-side result.  ``submit`` dispatches, then drains until fewer
    than ``depth`` handles remain in flight — so ``depth=1`` is the
    drain-after-every-dispatch serial bound (the overlap probe's lower
    reference) and ``depth >= 2`` is the classic double buffer: the
    host stages batch N+1 while the device runs batch N.  ``drain``
    flushes the tail.
    """

    def __init__(self, dispatch: Callable, fetch: Callable, *,
                 depth: int = 2) -> None:
        if depth < 1:
            raise ValueError(f"transfer queue depth must be >= 1: {depth}")
        self._dispatch = dispatch
        self._fetch = fetch
        self.depth = depth
        self._inflight: deque = deque()
        self.submitted = 0
        self.drained = 0

    def __len__(self) -> int:
        return len(self._inflight)

    def submit(self, *args) -> Iterator:
        """Enqueue one batch; yield any results that had to drain to
        keep fewer than ``depth`` batches in flight."""
        self._inflight.append(self._dispatch(*args))
        self.submitted += 1
        while len(self._inflight) >= self.depth:
            yield self._pop()

    def drain(self) -> Iterator:
        """Yield remaining results in submission order."""
        while self._inflight:
            yield self._pop()

    def _pop(self):
        out = self._fetch(self._inflight.popleft())
        self.drained += 1
        return out
