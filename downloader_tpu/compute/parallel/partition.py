"""Regex → PartitionSpec tables for model params.

``match_partition_rules`` walks a param pytree and assigns every leaf a
``PartitionSpec`` by matching the first rule whose regex hits the
``/``-joined key path.  Two deliberate hard edges:

- an UNMATCHED param raises — silently replicating a tensor the table
  forgot is how sharding rules drift between bench rounds.  If a param
  should be replicated, say so with an explicit rule.
- scalars (``ndim == 0``, e.g. optax step counts) are always ``P()``;
  no rule can shard a rank-0 array.

``UPSCALER_RULES`` is the production table for the upscaler: conv
kernels split their output-channel dim over ``model``, biases likewise,
and the sub-pixel head stays replicated (its channel count is
``scale^2 * channels``, not divisible by typical model-axis sizes).
The rules are disjoint by construction — every upscaler param matches
exactly one — and tests/test_compute_shard.py pins that property.
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

Rules = Sequence[Tuple[str, P]]

UPSCALER_RULES: Rules = (
    # sub-pixel head: replicated (channel count indivisible by model axis)
    (r"subpixel/(kernel|bias)", P()),
    # trunk conv kernels (kh, kw, cin, cout): split cout over `model`
    (r"(stem|body_\d+)/kernel", P(None, None, None, "model")),
    # trunk biases (cout,): follow their kernels' channel split
    (r"(stem|body_\d+)/bias", P("model")),
)


def _leaf_name(path: tuple) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "name", None)
        if key is None:
            key = getattr(p, "idx", p)
        parts.append(str(key))
    return "/".join(parts)


def spec_for(rules: Rules, name: str, value) -> P:
    """PartitionSpec for one leaf; raises if no rule matches.

    ``name`` is the ``/``-joined key path; ``value`` only needs ``ndim``.
    """
    if getattr(value, "ndim", None) == 0:
        return P()
    for pattern, spec in rules:
        if re.search(pattern, name):
            return spec
    raise ValueError(f"Partition rule not found for param: {name}")


def match_partition_rules(rules: Rules, params):
    """Map a param pytree to a pytree of PartitionSpecs (same structure).

    Exemplar-style: ``re.search`` over the joined key path, first match
    wins, rank-0 leaves replicate, unmatched leaves raise.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = [spec_for(rules, _leaf_name(path), value) for path, value in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def rule_audit(rules: Rules, params) -> dict:
    """Map leaf name → list of matching rule patterns (diagnostics; the
    exactly-one-match test asserts every list has length 1)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    audit = {}
    for path, value in flat:
        name = _leaf_name(path)
        if getattr(value, "ndim", None) == 0:
            continue  # scalars bypass the table entirely
        audit[name] = [pat for pat, _ in rules if re.search(pat, name)]
    return audit
