from .mesh import MeshPlan, make_mesh, shard_batch, shard_params

__all__ = ["MeshPlan", "make_mesh", "shard_batch", "shard_params"]
