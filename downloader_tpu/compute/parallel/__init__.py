from .mesh import MeshPlan, make_global, make_mesh, shard_batch, shard_params

__all__ = ["MeshPlan", "make_global", "make_mesh", "shard_batch",
           "shard_params"]
