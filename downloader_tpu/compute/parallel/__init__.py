from .chooser import Decision, choose, compile_step, decision_cache
from .mesh import MeshPlan, make_global, make_mesh, shard_batch, shard_params
from .partition import (
    UPSCALER_RULES, match_partition_rules, rule_audit, spec_for,
)
from .transfer import HopSink, TransferQueue, timed_hop

__all__ = [
    "Decision", "HopSink", "MeshPlan", "TransferQueue", "UPSCALER_RULES",
    "choose", "compile_step", "decision_cache", "make_global", "make_mesh",
    "match_partition_rules", "rule_audit", "shard_batch", "shard_params",
    "spec_for", "timed_hop",
]
