"""Device mesh + sharding plan for the compute stage.

Two mesh axes:

- ``data``  — data parallelism: the frame batch is split across this axis;
  gradient psums ride ICI (inserted automatically by XLA from the sharding
  annotations, scaling-book style: annotate, don't hand-schedule).
- ``model`` — tensor parallelism: conv feature (output-channel) dimensions
  are split across this axis, so each chip holds 1/T of every kernel and
  activations stay sharded on the channel dim through the elementwise ops.

The same plan compiles on one chip (both axes size 1), the driver's virtual
8-device CPU mesh, or a real multi-host slice — only the mesh shape changes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    mesh: Mesh

    @property
    def data_sharding(self) -> NamedSharding:
        """Batches: split the leading (batch) dim across ``data``."""
        return NamedSharding(self.mesh, P("data", None, None, None))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def param_spec(self, path: tuple, value) -> P:
        """Tensor-parallel param layout, resolved through the
        regex→PartitionSpec table in ``partition.py`` (single source of
        truth; an upscaler param the table doesn't know raises instead
        of silently replicating)."""
        from .partition import UPSCALER_RULES, _leaf_name, spec_for

        return spec_for(UPSCALER_RULES, _leaf_name(path), value)

    def param_sharding(self, path: tuple, value) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(path, value))


def make_mesh(n_devices: Optional[int] = None, model_axis: int = 1) -> MeshPlan:
    """Build a (data x model) mesh over the first ``n_devices`` devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"asked for {n} devices, have {len(devices)}")
    if n % model_axis != 0:
        raise ValueError(f"{n} devices not divisible by model axis {model_axis}")
    grid = np.array(devices[:n]).reshape(n // model_axis, model_axis)
    return MeshPlan(Mesh(grid, axis_names=("data", "model")))


def make_global(value, sharding: NamedSharding):
    """Assemble a (possibly multi-process) global array from a host
    value.

    ``jax.device_put`` can only target devices addressable by THIS
    process; on a mesh spanning several processes (multi-host training,
    or the two-process CPU harness in tests/test_multihost.py) each
    process must instead contribute its addressable shards of the same
    logically-global value — every host is assumed to hold an identical
    copy (same PRNG seed / same input pipeline slice convention), the
    standard multi-controller JAX recipe.  Single-process this reduces
    to a plain sharded placement.
    """
    if jax.process_count() == 1:
        # single-controller: plain sharded placement, no host round trip
        # (values may already live on device; over a tunneled chip a
        # d2h+h2d bounce costs real seconds)
        return jax.device_put(value, sharding)
    value = np.asarray(value)
    return jax.make_array_from_callback(
        value.shape, sharding, lambda idx: value[idx]
    )


def shard_params(plan: MeshPlan, params):
    """Place a param pytree according to the plan (named shardings; XLA
    partitions the arrays, collectives ride the mesh)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    placed = [
        make_global(value, plan.param_sharding(path, value))
        for path, value in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, placed)


def shard_batch(plan: MeshPlan, batch):
    """Place a batch (array or pytree of arrays) on the data axis.

    Mapped over leaves: ``make_global``'s multi-process branch indexes a
    single ndarray, so a tuple/dict batch that worked single-process
    (``device_put`` takes pytrees) would otherwise crash on a
    multi-process mesh."""
    return jax.tree_util.tree_map(
        lambda leaf: make_global(leaf, plan.data_sharding), batch
    )
