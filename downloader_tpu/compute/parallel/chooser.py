"""pjit-vs-shard_map compile chooser.

Two ways to run one step over a mesh:

- **pjit** (``jax.jit`` + explicit shardings): the caller states where
  inputs live, XLA propagates layouts and inserts collectives.  Right
  when the caller already placed its arrays (the inference engine
  device_puts planes under a NamedSharding before dispatch).
- **shard_map**: the function body runs per-shard with explicit specs;
  no layout search, no surprise resharding.  Right for even
  data-parallel batches where the caller thinks in per-device terms.

``choose`` picks per (mesh, batch shape) and caches the decision so a
hot loop never re-derives it; ``compile_step`` turns the decision into
a compiled callable with ``donate_argnums`` applied either way, so HBM
is not double-resident across steps regardless of strategy.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax

try:  # jax >= 0.6 promotes shard_map out of experimental
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jaxlib in some images
    from jax.experimental.shard_map import shard_map


@dataclasses.dataclass(frozen=True)
class Decision:
    strategy: str  # "jit" | "pjit" | "shard_map"
    reason: str


_DECISIONS: dict = {}


def _mesh_key(mesh) -> Optional[tuple]:
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape))


def choose(mesh, batch_shape: Optional[Tuple[int, ...]], *,
           explicit_shardings: bool, data_axis: str = "data") -> Decision:
    """Pick the compile strategy for one step; cached per (mesh, shape).

    ``batch_shape`` is the leading-dim shape of the batched argument
    (``None`` for shape-polymorphic callers — they get the mesh-level
    answer).  ``explicit_shardings`` says the caller provides
    in_shardings (arrays already placed) — the pjit precondition.
    """
    key = (_mesh_key(mesh), batch_shape, explicit_shardings, data_axis)
    hit = _DECISIONS.get(key)
    if hit is not None:
        return hit

    if mesh is None or mesh.size == 1:
        decision = Decision("jit", "single device: no mesh to map over")
    elif explicit_shardings:
        decision = Decision(
            "pjit", "explicit shardings provided: let XLA propagate")
    elif batch_shape is None:
        decision = Decision(
            "pjit", "shape-polymorphic: propagate from input placements")
    else:
        data = dict(zip(mesh.axis_names, mesh.devices.shape)).get(data_axis, 1)
        if batch_shape[0] % max(1, data) != 0:
            decision = Decision(
                "pjit",
                f"batch {batch_shape[0]} not divisible by "
                f"{data_axis}={data}: pjit pads, shard_map cannot")
        else:
            decision = Decision(
                "shard_map", "even data-parallel batch: per-shard specs")
    _DECISIONS[key] = decision
    return decision


def decision_cache() -> dict:
    """Snapshot of cached decisions (tests pin entries per fixture)."""
    return dict(_DECISIONS)


def clear_decisions() -> None:
    _DECISIONS.clear()


def compile_step(fn, mesh, *, batch_shape=None, data_axis="data",
                 in_shardings=None, out_shardings=None,
                 in_specs=None, out_specs=None, donate_argnums=()):
    """Compile ``fn`` per the cached decision; returns ``(compiled,
    decision)``.

    pjit route passes shardings straight to ``jax.jit``; shard_map
    route wraps ``fn`` with the given per-shard specs then jits the
    wrapper.  ``donate_argnums`` applies on every route.
    """
    decision = choose(mesh, batch_shape,
                      explicit_shardings=in_shardings is not None,
                      data_axis=data_axis)
    if decision.strategy == "jit":
        return jax.jit(fn, donate_argnums=donate_argnums), decision
    if decision.strategy == "pjit":
        return jax.jit(fn, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=donate_argnums), decision
    if in_specs is None or out_specs is None:
        raise ValueError(
            "shard_map chosen but in_specs/out_specs not provided; "
            "pass per-shard specs or place inputs and pass in_shardings")
    mapped = shard_map(fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)
    return jax.jit(mapped, donate_argnums=donate_argnums), decision
