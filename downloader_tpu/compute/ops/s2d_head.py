"""Space-to-depth head: fix the sub-pixel projection's MXU starvation.

The upscaler's head conv projects features (C=128) down to
``scale^2 * 3`` sub-pixel channels — C_out=12 at the default scale.  The
MXU produces 128 output lanes per pass regardless, so this conv runs at
~12/128 lane utilization; the r4 budget (`scripts/mfu_r4.py`) measured
it at ~27 ms of a ~100 ms 720p step against a ~1 ms flops bound — the
single largest unattributed cost in the v4-era accounting.

The fix is algebraic, not architectural: a SAME 3x3 conv evaluated at
the four positions of a 2x2 output block reads a shared 4x4 input
window.  Packing the four shifted 3x3 kernels into one stride-2 4x4
conv with 4x the output channels computes EXACTLY the same numbers —

    out3x3[b, 2i+di, 2j+dj, c] == out4x4[b, i, j, (di*2+dj)*C + c]

— with N = 4*C output lanes (48 at scale 2) for 16/9 the MACs.  The
kernel is built from the model's ordinary ``subpixel`` params at trace
time (constant-folded by XLA), so checkpoints, the trainer, and every
other path keep the plain 3x3 head.  Measured on the v5e: the full
720p stage step drops ~34% (100.2 -> 66.2 ms, interleaved race).

Requires even H and W (callers gate and fall back to the plain head).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pack_s2d_kernel(kernel: jax.Array) -> jax.Array:
    """(3, 3, Cin, C) SAME-conv kernel -> (4, 4, Cin, 4*C) stride-2
    packed kernel.  Output channel block g = di*2+dj holds the kernel
    shifted to sub-position (di, dj); blocks never overlap, zeros fill
    the taps outside each 3x3 sub-window."""
    kh, kw = kernel.shape[:2]
    if (kh, kw) != (3, 3):
        raise ValueError(f"s2d packing expects a 3x3 kernel, got {kh}x{kw}")
    blocks = [
        jnp.pad(kernel, ((di, 1 - di), (dj, 1 - dj), (0, 0), (0, 0)))
        for di in (0, 1) for dj in (0, 1)
    ]
    return jnp.concatenate(blocks, axis=-1)


def s2d_head(feats: jax.Array, kernel: jax.Array, bias: jax.Array,
             compute_dtype=jnp.bfloat16) -> jax.Array:
    """Apply the packed head: (B, H, W, Cin) -> (B, H/2, W/2, 4*C).

    ``kernel``/``bias`` are the model's plain ``subpixel`` head params
    ((3, 3, Cin, C) / (C,)); H and W must be even."""
    b, h, w, _ = feats.shape
    if h % 2 or w % 2:
        raise ValueError(f"s2d head needs even dims, got {h}x{w}")
    k4 = pack_s2d_kernel(kernel).astype(compute_dtype)
    out = jax.lax.conv_general_dilated(
        feats.astype(compute_dtype), k4, (2, 2), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out + jnp.tile(bias, 4).astype(compute_dtype)
