"""Sub-pixel shuffle (depth-to-space) op.

The upscaler's only non-conv op: rearrange (B, H, W, C*r*r) into
(B, H*r, W*r, C).  The default path is pure ``jnp`` reshape/transpose.
Measured cost on a real v5e (720p, batch 8, bf16): ~6 ms — NOT free;
Mosaic must relayout the sub-lane-width channel dims (12 -> 3) across
lanes and sublanes.  Alternatives raced on hardware (BASELINE.md
"Compute-harness v3"): a stack-then-reshape formulation ties it, a
strided-scatter loses 60x, and an in-Pallas rank-4 transpose fails to
compile (MosaicError) — so the XLA transpose stands as the best known
implementation at ~7% of the forward.  A Pallas TPU kernel is provided
for the quantize tail used at inference (clip/round/f32->u8), which IS
worth fusing manually after the final conv.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pixel_shuffle(x: jax.Array, scale: int) -> jax.Array:
    """(B, H, W, C*scale^2) -> (B, H*scale, W*scale, C)."""
    b, h, w, c_full = x.shape
    if c_full % (scale * scale) != 0:
        raise ValueError(f"channels {c_full} not divisible by scale^2 {scale * scale}")
    c = c_full // (scale * scale)
    # (B,H,W,r,r,C) -> interleave the sub-pixel grids into space
    x = x.reshape(b, h, w, scale, scale, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h * scale, w * scale, c)


def pixel_shuffle_clip_u8(x: jax.Array, scale: int) -> jax.Array:
    """Inference tail: shuffle + clip to [0, 255] + round to uint8.

    The shuffle itself stays in XLA — it lowers to a layout change that the
    compiler folds into the surrounding ops, and the TPU vector unit's
    (sublane, lane) tiling makes a hand-written lane interleave strictly
    worse.  The quantize tail (clip/round/f32->u8) runs as a Pallas kernel
    on TPU (verified on hardware; Mosaic needs the i32 cast bridge), with
    the XLA path as fallback elsewhere (CPU tests, driver dry runs).
    """
    return quantize_u8(pixel_shuffle(x.astype(jnp.float32), scale))


def quantize_u8(x: jax.Array) -> jax.Array:
    """clip(round(x), 0, 255) -> uint8, via the Pallas kernel on TPU with
    the XLA path as fallback — the one dispatch point for the quantize
    tail (inference uses it too).

    The Pallas path is only attempted on shapes Mosaic accepts (lane dim
    a multiple of 128): a pallas_call that raises DURING tracing inside
    an enclosing jit leaks tracers and poisons the whole trace, so shape
    rejection must happen up front, not via try/except."""
    if (jax.default_backend() == "tpu" and x.ndim >= 2
            and x.shape[-1] % 128 == 0):
        try:
            return _pallas_quantize_u8(x)
        except Exception:  # pragma: no cover - pallas availability varies
            pass
    return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)


def _pallas_shuffle_clip(x: jax.Array, scale: int, interpret: bool = False) -> jax.Array:
    """Shuffle (XLA layout change) + Pallas-quantize; see
    :func:`pixel_shuffle_clip_u8` for why the split goes this way."""
    shuffled = pixel_shuffle(x.astype(jnp.float32), scale)
    return _pallas_quantize_u8(shuffled, interpret=interpret)


_ROW_BLOCK = 8  # sublane granularity


def _pallas_quantize_u8(x: jax.Array, interpret: bool = False) -> jax.Array:
    """Elementwise clip(round(x), 0, 255) -> uint8 as a Pallas TPU kernel.

    Operates on a 2D view (rows x row-bytes) in row blocks so VMEM holds
    one tile at a time regardless of frame size.  The f32->u8 conversion
    goes through i32 — Mosaic has no direct f32->u8 cast.
    """
    from jax.experimental import pallas as pl

    shape = x.shape
    rows = 1
    for dim in shape[:-2]:
        rows *= dim
    rows *= shape[-2]
    cols = shape[-1]
    flat = x.reshape(rows, cols)
    if rows % _ROW_BLOCK != 0:  # pragma: no cover - shapes here are even
        return jnp.clip(jnp.round(x), 0, 255).astype(jnp.uint8)

    def kernel(x_ref, o_ref):
        clipped = jnp.clip(jnp.round(x_ref[...]), 0, 255)
        o_ref[...] = clipped.astype(jnp.int32).astype(jnp.uint8)

    out = pl.pallas_call(
        kernel,
        grid=(rows // _ROW_BLOCK,),
        in_specs=[pl.BlockSpec((_ROW_BLOCK, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_ROW_BLOCK, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), jnp.uint8),
        interpret=interpret,
    )(flat)
    return out.reshape(shape)
