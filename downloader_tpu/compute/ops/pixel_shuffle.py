"""Sub-pixel shuffle (depth-to-space) op.

The upscaler's only non-conv op: rearrange (B, H, W, C*r*r) into
(B, H*r, W*r, C).  The default path is pure ``jnp`` reshape/transpose —
these lower to free layout changes that XLA fuses into the surrounding
convs, which is exactly what you want on TPU (no hand kernel can beat a
fused no-op).  A Pallas TPU kernel is provided as well for the fused
shuffle+clip postprocess variant used at inference (where the output is
quantized back to uint8 display range), since that elementwise tail is
worth fusing manually when it follows the final conv.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pixel_shuffle(x: jax.Array, scale: int) -> jax.Array:
    """(B, H, W, C*scale^2) -> (B, H*scale, W*scale, C)."""
    b, h, w, c_full = x.shape
    if c_full % (scale * scale) != 0:
        raise ValueError(f"channels {c_full} not divisible by scale^2 {scale * scale}")
    c = c_full // (scale * scale)
    # (B,H,W,r,r,C) -> interleave the sub-pixel grids into space
    x = x.reshape(b, h, w, scale, scale, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h * scale, w * scale, c)


def pixel_shuffle_clip_u8(x: jax.Array, scale: int) -> jax.Array:
    """Inference tail: shuffle + clip to [0, 255] + round to uint8.

    Uses a Pallas TPU kernel when running on TPU; falls back to the XLA
    path elsewhere (CPU tests, driver dry runs).
    """
    if jax.default_backend() == "tpu":
        try:
            return _pallas_shuffle_clip(x, scale)
        except Exception:  # pragma: no cover - pallas availability varies
            pass
    shuffled = pixel_shuffle(x.astype(jnp.float32), scale)
    return jnp.clip(jnp.round(shuffled), 0, 255).astype(jnp.uint8)


def _pallas_shuffle_clip(x: jax.Array, scale: int, interpret: bool = False) -> jax.Array:
    """Pallas kernel: per-(batch, row-block) tiles, VMEM-resident.

    Grid walks (batch, H); each program reads one (W, C*r*r) row slab,
    writes the r interleaved output rows.  Keeps the whole slab in VMEM and
    does the clip/round in-register, saving one HBM round-trip versus
    shuffle-then-postprocess.
    """
    from jax.experimental import pallas as pl

    b, h, w, c_full = x.shape
    r = scale
    c = c_full // (r * r)

    def kernel(x_ref, o_ref):
        slab = x_ref[...]  # (1, W, C*r*r)
        slab = slab.reshape(w, r, r, c).astype(jnp.float32)
        # (W, r_row, r_col, C) -> rows of the upscaled image
        rows = slab.transpose(1, 0, 2, 3).reshape(1, r, w * r, c)
        o_ref[...] = jnp.clip(jnp.round(rows), 0, 255).astype(jnp.uint8)

    out_shape = jax.ShapeDtypeStruct((b, h * r, w * r, c), jnp.uint8)
    return pl.pallas_call(
        kernel,
        grid=(b, h),
        in_specs=[
            pl.BlockSpec((1, 1, w, c_full), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, r, w * r, c), lambda i, j: (i, j, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(x)
