"""YCbCr <-> RGB conversion and chroma resampling, all jittable.

The upscale stage feeds planar YCbCr straight off a Y4M stream to the
device and gets planar YCbCr back: colorspace conversion, chroma
up/downsampling, the model forward, and the quantize tail are ONE XLA
computation, so no intermediate RGB frame ever round-trips HBM (let alone
the host).  That fusion is the point of doing the conversion in jnp
instead of on the CPU.

Coefficients are BT.601 full-range (the JPEG/Y4M ``C420jpeg`` convention):
    Y  =  0.299 R + 0.587 G + 0.114 B
    Cb = -0.168736 R - 0.331264 G + 0.5 B        + 128
    Cr =  0.5 R - 0.418688 G - 0.081312 B        + 128
and the exact inverse.  Everything operates in the 0..255 float domain on
(B, H, W) planes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Module-level constants are PLAIN numpy on purpose: a module-scope
# jnp.array binds whatever trace context is active at first import, so a
# lazy `import` inside a jitted function would store a tracer in these
# globals and poison every later trace (UnexpectedTracerError — hit on
# hardware in r3).  numpy constants are concrete everywhere and XLA
# embeds them just the same.

# forward (RGB -> YCbCr) matrix, rows = (Y, Cb, Cr)
_RGB2YCC = np.array(
    [
        [0.299, 0.587, 0.114],
        [-0.168736, -0.331264, 0.5],
        [0.5, -0.418688, -0.081312],
    ],
    dtype=np.float32,
)

# inverse (YCbCr -> RGB) matrix, rows = (R, G, B), applied to (Y, Cb-128, Cr-128)
_YCC2RGB = np.array(
    [
        [1.0, 0.0, 1.402],
        [1.0, -0.344136, -0.714136],
        [1.0, 1.772, 0.0],
    ],
    dtype=np.float32,
)


# All colorspace matmuls pin precision=HIGHEST: these are 3-wide
# contractions (free next to the convs), and the default TPU matmul
# precision rounds matvec vs matmul lowerings differently — measured ±1
# u8 disagreements between the fused and naive output tails on a v5e
# until both paths were pinned.


def ycbcr_to_rgb(y: jax.Array, cb: jax.Array, cr: jax.Array) -> jax.Array:
    """Full-res (B, H, W) float planes in 0..255 -> (B, H, W, 3) RGB 0..255."""
    ycc = jnp.stack([y, cb - 128.0, cr - 128.0], axis=-1)
    return jnp.matmul(ycc, _YCC2RGB.T, precision="highest")


# Model-domain input transform with the /255 normalization and the ±128
# chroma offsets FOLDED into the matrix and a bias vector.  Standalone
# elementwise passes over lane-dim-3 tensors run at 3/128 lane
# utilization on TPU, so whether they cost ~0 or ~30 ms/step depends on
# whether XLA fuses them into neighbors (measured both outcomes on a
# v5e: a synthetic variant paid 31 ms for a bare /255; the shipped
# nested-jit graph fused most of it and the fold nets ~2 ms).  Folding
# makes the cost structural instead of fusion-dependent.
_YCC2RGB_UNIT = (_YCC2RGB / 255.0).astype(np.float32)
_YCC2RGB_UNIT_BIAS = (
    -(128.0 / 255.0) * (_YCC2RGB[:, 1] + _YCC2RGB[:, 2])
).astype(np.float32)


def ycbcr_to_unit_rgb(y: jax.Array, cb: jax.Array, cr: jax.Array) -> jax.Array:
    """(B, H, W) YCbCr planes in 0..255 -> (B, H, W, 3) RGB in [0, 1]
    (the model's input domain), in one fused contraction."""
    ycc = jnp.stack([y, cb, cr], axis=-1)
    return (jnp.matmul(ycc, _YCC2RGB_UNIT.T, precision="highest")
            + _YCC2RGB_UNIT_BIAS)


def rgb_to_ycbcr(rgb: jax.Array):
    """(B, H, W, 3) RGB 0..255 -> three (B, H, W) float planes in 0..255."""
    ycc = jnp.matmul(rgb, _RGB2YCC.T, precision="highest")
    y = ycc[..., 0]
    cb = ycc[..., 1] + 128.0
    cr = ycc[..., 2] + 128.0
    return y, cb, cr


def upsample_chroma(plane: jax.Array, sub_h: int, sub_w: int) -> jax.Array:
    """(B, H/sub_h, W/sub_w) -> (B, H, W) by nearest-neighbor repeat.

    ``jnp.repeat`` with a static count lowers to a broadcast-reshape that
    XLA folds into the consuming matmul/conv — no gather, no copy.
    """
    if sub_h > 1:
        plane = jnp.repeat(plane, sub_h, axis=1)
    if sub_w > 1:
        plane = jnp.repeat(plane, sub_w, axis=2)
    return plane


def fused_subpixel_ycc(subpixel_rgb: jax.Array, scale: int):
    """Sub-pixel-domain output tail: colorspace + quantize BEFORE the
    pixel shuffle.

    Input: the model backbone's (B, H, W, scale^2*3) RGB sub-pixel maps
    in the MODEL's [0, 1] domain (any float dtype — the x255 display
    scaling is folded into the f32 transform coefficients so the
    astype+scale over the lane-dim-12 tensor never exists as a
    standalone, fusion-dependent pass; see the note at
    :data:`_YCC2RGB_UNIT`).  Output: ``(y_u8, cb_u8, cr_u8)`` with
    ``y`` at (B, H*scale, W*scale) and chroma at (B, H, W) — i.e. the
    4:2:0 planes for the ``scale``-upscaled frame when chroma subsampling
    equals ``scale``.

    Two algebraic identities make this much cheaper than
    shuffle-then-transform (33% off the whole 720p stage step on a v5e,
    BASELINE.md r3):

    - box-downsampling the shuffled full-res chroma by ``scale`` is
      EXACTLY the mean over each scale^2 sub-pixel channel group (the
      box filter commutes with the shuffle), so full-res chroma planes
      are never materialized; the chroma transform runs on channel
      means at (H, W);
    - the luma transform + quantize are elementwise, so they commute
      with the shuffle: transform+quantize the scale^2 luma channels at
      (H, W), then shuffle uint8 BYTES — 4x less relayout traffic than
      shuffling float32.

    Agreement with the naive shuffle-then-transform path: within one u8
    step everywhere, >97% byte-exact (pinned by
    ``test_fused_subpixel_tail_matches_naive`` and verified byte-exact
    on a real v5e for the shipped seeds).  The identities are exact
    algebraically; the folded factoring and chroma summation order
    differ in the last float ulp, so values on a rounding boundary may
    land one step away — on CPU as well as TPU.
    """
    from .pixel_shuffle import quantize_u8

    b, h, w, c_full = subpixel_rgb.shape
    r = scale
    if c_full != r * r * 3:
        raise ValueError(f"expected {r * r * 3} sub-pixel channels, got {c_full}")
    # channel index factorizes as (di, dj, rgb) — matching pixel_shuffle
    sub = subpixel_rgb.reshape(b, h, w, r * r, 3)
    # f32 coefficients upcast the (typically bf16) model output inside
    # the contraction — no separate astype pass
    y_sub = jnp.matmul(sub, 255.0 * _RGB2YCC[0],
                       precision="highest")        # (b, h, w, r*r)
    y_u8 = quantize_u8(y_sub)
    y_full = (
        y_u8.reshape(b, h, w, r, r)
        .transpose(0, 1, 3, 2, 4)
        .reshape(b, h * r, w * r)
    )
    mean_rgb = sub.mean(axis=3, dtype=jnp.float32)  # (b, h, w, 3)
    cb = jnp.matmul(mean_rgb, 255.0 * _RGB2YCC[1],
                    precision="highest") + 128.0
    cr = jnp.matmul(mean_rgb, 255.0 * _RGB2YCC[2],
                    precision="highest") + 128.0
    return y_full, quantize_u8(cb), quantize_u8(cr)


def fused_subpixel_ycc_s2d(packed: jax.Array, scale: int):
    """The fused sub-pixel tail for the s2d head's packed output.

    Input: ``(B, H/2, W/2, 4*scale^2*3)`` from :func:`ops.s2d_head.s2d_head`
    — channel block ``g = di*2+dj`` holds the ``scale^2*3`` sub-pixel
    maps of full-res position ``(2i+di, 2j+dj)``, each block laid out
    exactly like :func:`fused_subpixel_ycc`'s input.  Output: identical
    planes to ``fused_subpixel_ycc(h12, scale)`` on the corresponding
    unpacked tensor — ``y`` at (B, H*scale, W*scale), chroma at
    (B, H, W) — via a two-level shuffle (s2d block, then sub-pixel).
    The arithmetic per element is the same contraction, so the two
    paths agree exactly (pinned by ``test_s2d_tail_matches_fused``).
    """
    from .pixel_shuffle import quantize_u8

    b, hh, ww, c_full = packed.shape
    r = scale
    if c_full != 4 * r * r * 3:
        raise ValueError(
            f"expected {4 * r * r * 3} packed sub-pixel channels, got {c_full}")
    sub = packed.reshape(b, hh, ww, 4, r * r, 3)
    y_sub = jnp.matmul(sub, 255.0 * _RGB2YCC[0],
                       precision="highest")        # (b, hh, ww, 4, r*r)
    y_u8 = quantize_u8(y_sub)
    yv = y_u8.reshape(b, hh, ww, 2, 2, r, r)       # (di, dj, si, sj)
    y_full = (
        yv.transpose(0, 1, 3, 5, 2, 4, 6)          # rows i,di,si / cols j,dj,sj
        .reshape(b, hh * 2 * r, ww * 2 * r)
    )
    mean_rgb = sub.mean(axis=4, dtype=jnp.float32)  # (b, hh, ww, 4, 3)
    cb = jnp.matmul(mean_rgb, 255.0 * _RGB2YCC[1],
                    precision="highest") + 128.0
    cr = jnp.matmul(mean_rgb, 255.0 * _RGB2YCC[2],
                    precision="highest") + 128.0

    def _chroma(plane_u8):
        return (plane_u8.reshape(b, hh, ww, 2, 2)
                .transpose(0, 1, 3, 2, 4)
                .reshape(b, hh * 2, ww * 2))

    return y_full, _chroma(quantize_u8(cb)), _chroma(quantize_u8(cr))


def downsample_chroma(plane: jax.Array, sub_h: int, sub_w: int) -> jax.Array:
    """(B, H, W) -> (B, H/sub_h, W/sub_w) by box (mean) filter — the
    standard siting-agnostic decimation for re-encoding subsampled chroma."""
    if sub_h == 1 and sub_w == 1:
        return plane
    b, h, w = plane.shape
    plane = plane.reshape(b, h // sub_h, sub_h, w // sub_w, sub_w)
    return plane.mean(axis=(2, 4))
