"""YCbCr <-> RGB conversion and chroma resampling, all jittable.

The upscale stage feeds planar YCbCr straight off a Y4M stream to the
device and gets planar YCbCr back: colorspace conversion, chroma
up/downsampling, the model forward, and the quantize tail are ONE XLA
computation, so no intermediate RGB frame ever round-trips HBM (let alone
the host).  That fusion is the point of doing the conversion in jnp
instead of on the CPU.

Coefficients are BT.601 full-range (the JPEG/Y4M ``C420jpeg`` convention):
    Y  =  0.299 R + 0.587 G + 0.114 B
    Cb = -0.168736 R - 0.331264 G + 0.5 B        + 128
    Cr =  0.5 R - 0.418688 G - 0.081312 B        + 128
and the exact inverse.  Everything operates in the 0..255 float domain on
(B, H, W) planes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# forward (RGB -> YCbCr) matrix, rows = (Y, Cb, Cr)
_RGB2YCC = jnp.array(
    [
        [0.299, 0.587, 0.114],
        [-0.168736, -0.331264, 0.5],
        [0.5, -0.418688, -0.081312],
    ],
    dtype=jnp.float32,
)

# inverse (YCbCr -> RGB) matrix, rows = (R, G, B), applied to (Y, Cb-128, Cr-128)
_YCC2RGB = jnp.array(
    [
        [1.0, 0.0, 1.402],
        [1.0, -0.344136, -0.714136],
        [1.0, 1.772, 0.0],
    ],
    dtype=jnp.float32,
)


def ycbcr_to_rgb(y: jax.Array, cb: jax.Array, cr: jax.Array) -> jax.Array:
    """Full-res (B, H, W) float planes in 0..255 -> (B, H, W, 3) RGB 0..255."""
    ycc = jnp.stack([y, cb - 128.0, cr - 128.0], axis=-1)
    return ycc @ _YCC2RGB.T


def rgb_to_ycbcr(rgb: jax.Array):
    """(B, H, W, 3) RGB 0..255 -> three (B, H, W) float planes in 0..255."""
    ycc = rgb @ _RGB2YCC.T
    y = ycc[..., 0]
    cb = ycc[..., 1] + 128.0
    cr = ycc[..., 2] + 128.0
    return y, cb, cr


def upsample_chroma(plane: jax.Array, sub_h: int, sub_w: int) -> jax.Array:
    """(B, H/sub_h, W/sub_w) -> (B, H, W) by nearest-neighbor repeat.

    ``jnp.repeat`` with a static count lowers to a broadcast-reshape that
    XLA folds into the consuming matmul/conv — no gather, no copy.
    """
    if sub_h > 1:
        plane = jnp.repeat(plane, sub_h, axis=1)
    if sub_w > 1:
        plane = jnp.repeat(plane, sub_w, axis=2)
    return plane


def downsample_chroma(plane: jax.Array, sub_h: int, sub_w: int) -> jax.Array:
    """(B, H, W) -> (B, H/sub_h, W/sub_w) by box (mean) filter — the
    standard siting-agnostic decimation for re-encoding subsampled chroma."""
    if sub_h == 1 and sub_w == 1:
        return plane
    b, h, w = plane.shape
    plane = plane.reshape(b, h // sub_h, sub_h, w // sub_w, sub_w)
    return plane.mean(axis=(2, 4))
