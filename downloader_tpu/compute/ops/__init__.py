from .pixel_shuffle import pixel_shuffle

__all__ = ["pixel_shuffle"]
