"""Optional TPU compute subsystem: the downstream "converter" demo.

The reference pipeline's entire job is to stage media for a downstream
converter service (it publishes ``api.Convert`` jobs — SURVEY.md §1); the
reference itself contains **no tensor compute** (SURVEY.md §5: long-context /
parallelism are N/A).  This package is the TPU-native demonstration of that
downstream stage: a JAX/Flax video-frame super-resolution model ("media
upscale" transcode), with

- ``models/``   — the flagship upscaler network (bfloat16, NHWC, MXU-sized
                  convs)
- ``ops/``      — custom ops (Pallas kernel with an XLA fallback)
- ``parallel/`` — device-mesh + sharding helpers (data-parallel batch,
                  tensor-parallel feature dim) for multi-chip execution
- ``train.py``  — a jittable training step used by the multi-chip dry run

It is deliberately optional: the staging pipeline never imports JAX, and the
compute stage plugs in through the same stage contract as download/process/
upload.
"""
