"""Inference path for the converter demo: frames in, display frames out.

One jitted function per (config): bf16 forward through the upscaler,
then the quantize tail (Pallas kernel on TPU, XLA elsewhere) straight to
uint8 display range — the whole pipeline is a single XLA computation, so
activations never round-trip HBM between "model" and "postprocess".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .models.upscaler import Upscaler, UpscalerConfig
from .ops.pixel_shuffle import _pallas_quantize_u8


def make_infer_fn(config: UpscalerConfig = UpscalerConfig()):
    """Returns ``infer(params, frames_u8) -> upscaled_u8``.

    Input frames are uint8 (B, H, W, C) as a media decoder would hand
    them; output is uint8 (B, H*scale, W*scale, C).  Normalization to the
    model's [0, 1] float range and re-quantization live inside the jit.
    """
    model = Upscaler(config)
    # backend choice is a trace-time constant: the Pallas quantize kernel
    # is verified on TPU hardware; other backends take the XLA path
    use_pallas = jax.default_backend() == "tpu"

    @jax.jit
    def infer(params, frames_u8: jax.Array) -> jax.Array:
        x = frames_u8.astype(jnp.float32) / 255.0
        out = model.apply(params, x)           # bf16 forward (incl. shuffle)
        scaled = out.astype(jnp.float32) * 255.0
        if use_pallas:
            return _pallas_quantize_u8(scaled)
        return jnp.clip(jnp.round(scaled), 0, 255).astype(jnp.uint8)

    return infer


@functools.lru_cache(maxsize=4)
def _cached_infer(config: UpscalerConfig):
    return make_infer_fn(config)


def upscale_frames(params, frames_u8,
                   config: UpscalerConfig = UpscalerConfig()):
    """Convenience wrapper with a cached jitted function per config."""
    return _cached_infer(config)(params, frames_u8)
