"""Inference path for the converter demo: frames in, display frames out.

One jitted function per (config): bf16 forward through the upscaler,
then the quantize tail (Pallas kernel on TPU, XLA elsewhere) straight to
uint8 display range — the whole pipeline is a single XLA computation, so
activations never round-trip HBM between "model" and "postprocess".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .models.upscaler import Upscaler, UpscalerConfig
from .ops.pixel_shuffle import quantize_u8


@functools.lru_cache(maxsize=4)
def make_infer_fn(config: UpscalerConfig = UpscalerConfig()):
    """Returns ``infer(params, frames_u8) -> upscaled_u8`` (cached per
    config, so every caller shares one compiled function).

    Input frames are uint8 (B, H, W, C) as a media decoder would hand
    them; output is uint8 (B, H*scale, W*scale, C).  Normalization to the
    model's [0, 1] float range and re-quantization live inside the jit.
    """
    model = Upscaler(config)

    @jax.jit
    def infer(params, frames_u8: jax.Array) -> jax.Array:
        x = frames_u8.astype(jnp.float32) / 255.0
        out = model.apply(params, x)           # bf16 forward (incl. shuffle)
        return quantize_u8(out.astype(jnp.float32) * 255.0)

    return infer


def upscale_frames(params, frames_u8,
                   config: UpscalerConfig = UpscalerConfig()):
    """Convenience wrapper around the cached jitted function."""
    return make_infer_fn(config)(params, frames_u8)
