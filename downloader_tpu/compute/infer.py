"""Inference path for the converter demo: frames in, display frames out.

One jitted function per (config): bf16 forward through the upscaler,
then the quantize tail (Pallas kernel on TPU, XLA elsewhere) straight to
uint8 display range — the whole pipeline is a single XLA computation, so
activations never round-trip HBM between "model" and "postprocess".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .models.upscaler import Upscaler, UpscalerConfig
from .ops.pixel_shuffle import quantize_u8
from .parallel.chooser import compile_step


@functools.lru_cache(maxsize=4)
def make_infer_fn(config: UpscalerConfig = UpscalerConfig(), mesh=None):
    """Returns ``infer(params, frames_u8) -> upscaled_u8`` (cached per
    (config, mesh), so every caller shares one compiled function).

    Input frames are uint8 (B, H, W, C) as a media decoder would hand
    them; output is uint8 (B, H*scale, W*scale, C).  Normalization to the
    model's [0, 1] float range and re-quantization live inside the jit.

    With ``mesh`` the batch dim is data-parallel over its ``data`` axis
    and params replicate, routed through the pjit-vs-shard_map chooser
    like the planar engine (compute/pipeline.py).
    """
    model = Upscaler(config)

    def infer(params, frames_u8: jax.Array) -> jax.Array:
        x = frames_u8.astype(jnp.float32) / 255.0
        out = model.apply(params, x)           # bf16 forward (incl. shuffle)
        return quantize_u8(out.astype(jnp.float32) * 255.0)

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        in_shardings = (NamedSharding(mesh, P()),
                        NamedSharding(mesh, P("data", None, None, None)))
        compiled, _decision = compile_step(fn=infer, mesh=mesh,
                                           in_shardings=in_shardings)
    else:
        compiled, _decision = compile_step(fn=infer, mesh=None)
    return compiled


def upscale_frames(params, frames_u8,
                   config: UpscalerConfig = UpscalerConfig()):
    """Convenience wrapper around the cached jitted function."""
    return make_infer_fn(config)(params, frames_u8)
