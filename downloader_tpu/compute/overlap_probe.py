"""Paced-source overlap probe for :meth:`FrameUpscaler.upscale_to`.

One implementation shared by the bench (`bench.py` `stream_overlap_*`
extras) and the regression test
(`test_upscale_stream_pipelines_io_and_compute`) — two copies of this
harness would drift and silently measure different things (review r4).

The drill: feed the engine a Y4M source that blocks a fixed interval
per frame (a rate-limited decoder pipe), measure wall time for the
serial lower bound (depth=1 — drain after every dispatch) vs the
pipelined path (depth=3), plus pure-IO and pure-compute references.
``overlap = (serial - pipelined) / min(io, compute)`` is the fraction
of the hideable time actually hidden: ~0 means dispatch/fetch
serialize; >= ~0.9 means the in-flight queue works.
"""

from __future__ import annotations

import io
import os
import time
from typing import Optional

import numpy as np

from .video import Y4MHeader, Y4MWriter


def measure_overlap(
    engine,
    height: int = 96,
    width: int = 160,
    batches: int = 12,
    frame_interval: float = 0.0125,
    rng: Optional[np.random.Generator] = None,
) -> dict:
    """Run the drill on ``engine`` (already constructed, any backend).

    Returns ``{io_s, compute_s, serial_s, pipelined_s, overlap}``.
    The engine's compile happens inside (one warmup batch) so none of
    the timings include tracing.
    """
    rng = rng or np.random.default_rng(0)
    per_batch = engine.batch
    frames = [
        (rng.integers(0, 256, (height, width), np.uint8),
         rng.integers(0, 256, (height // 2, width // 2), np.uint8),
         rng.integers(0, 256, (height // 2, width // 2), np.uint8))
        for _ in range(per_batch)
    ]
    y = np.stack([f[0] for f in frames])
    cb = np.stack([f[1] for f in frames])
    cr = np.stack([f[2] for f in frames])
    engine.upscale_batch(y, cb, cr, 2, 2)  # compile outside the timings

    start = time.monotonic()
    for _ in range(batches):
        engine.upscale_batch(y, cb, cr, 2, 2)
    compute_s = time.monotonic() - start

    buf = io.BytesIO()
    writer = Y4MWriter(buf, Y4MHeader(width=width, height=height))
    for i in range(batches * per_batch):
        writer.write_frame(*frames[i % per_batch])
    data = buf.getvalue()

    class PacedSource:
        """Y4M source that blocks like a rate-limited decoder pipe."""

        def __init__(self):
            self._buf = io.BytesIO(data)

        def readline(self, n=-1):
            return self._buf.readline(n)

        def read(self, n=-1):
            time.sleep(frame_interval)
            return self._buf.read(n)

    walls = {}
    for depth in (1, 3):  # 1 = drain-after-every-dispatch serial bound
        with open(os.devnull, "wb") as sink:
            start = time.monotonic()
            n = engine.upscale_to(PacedSource(), sink, depth=depth)
        walls[depth] = time.monotonic() - start
        assert n == batches * per_batch, (n, batches * per_batch)

    io_s = batches * per_batch * frame_interval
    return {
        "io_s": io_s,
        "compute_s": compute_s,
        "serial_s": walls[1],
        "pipelined_s": walls[3],
        "overlap": (walls[1] - walls[3]) / min(io_s, compute_s),
    }
