"""Streaming Y4M (YUV4MPEG2) reader/writer.

Y4M is the one raw video format the framework can decode without an
external codec stack: a one-line ASCII header (``YUV4MPEG2 W.. H.. F..
C420jpeg``) followed by ``FRAME`` records of planar YCbCr bytes.  It is
what ``ffmpeg -f yuv4mpegpipe`` emits, so a production deployment puts a
decode front-end ahead of the upscale stage and pipes y4m through it; the
TPU path (see :mod:`.pipeline`) is format-independent planar uint8.

Supported chroma samplings: the 4:2:0 family (``C420``, ``C420jpeg``,
``C420mpeg2``, ``C420paldv`` — siting differences don't matter to a
box-filter resampler), ``C422`` and ``C444``.  Frame-level parameters on
``FRAME`` lines are preserved-by-ignoring (the spec allows them; nothing
in the wild needs them interpreted for decode).
"""

from __future__ import annotations

import dataclasses
from typing import BinaryIO, Iterator, Optional, Tuple

import numpy as np

Y4M_MAGIC = b"YUV4MPEG2"

# colorspace tag -> (chroma height divisor, chroma width divisor)
_SUBSAMPLING = {
    "420": (2, 2),
    "420jpeg": (2, 2),
    "420mpeg2": (2, 2),
    "420paldv": (2, 2),
    "422": (1, 2),
    "444": (1, 1),
}


class Y4MError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Y4MHeader:
    width: int
    height: int
    fps_num: int = 25
    fps_den: int = 1
    interlace: str = "p"
    aspect: str = "1:1"
    colorspace: str = "420jpeg"

    @property
    def subsampling(self) -> Tuple[int, int]:
        return _SUBSAMPLING[self.colorspace]

    @property
    def chroma_shape(self) -> Tuple[int, int]:
        sub_h, sub_w = self.subsampling
        return self.height // sub_h, self.width // sub_w

    @property
    def frame_bytes(self) -> int:
        ch, cw = self.chroma_shape
        return self.height * self.width + 2 * ch * cw

    def scaled(self, scale: int) -> "Y4MHeader":
        return dataclasses.replace(
            self, width=self.width * scale, height=self.height * scale
        )

    def encode(self) -> bytes:
        return (
            f"{Y4M_MAGIC.decode()} W{self.width} H{self.height} "
            f"F{self.fps_num}:{self.fps_den} I{self.interlace} "
            f"A{self.aspect} C{self.colorspace}\n"
        ).encode("ascii")


def parse_header(line: bytes) -> Y4MHeader:
    parts = line.strip().split(b" ")
    if not parts or parts[0] != Y4M_MAGIC:
        raise Y4MError("not a YUV4MPEG2 stream")
    fields = {}
    for part in parts[1:]:
        if len(part) < 2:
            continue
        fields[chr(part[0])] = part[1:].decode("ascii")
    try:
        width = int(fields["W"])
        height = int(fields["H"])
    except (KeyError, ValueError):
        raise Y4MError("Y4M header missing W/H") from None
    fps_num, fps_den = 25, 1
    if "F" in fields and ":" in fields["F"]:
        num, den = fields["F"].split(":", 1)
        try:
            fps_num, fps_den = int(num), int(den)
        except ValueError:
            raise Y4MError(f"bad Y4M frame rate {fields['F']!r}") from None
    colorspace = fields.get("C", "420jpeg")
    if colorspace not in _SUBSAMPLING:
        raise Y4MError(f"unsupported Y4M colorspace C{colorspace}")
    sub_h, sub_w = _SUBSAMPLING[colorspace]
    if width % sub_w or height % sub_h:
        raise Y4MError(
            f"frame {width}x{height} not divisible by C{colorspace} subsampling"
        )
    return Y4MHeader(
        width=width,
        height=height,
        fps_num=fps_num,
        fps_den=fps_den,
        interlace=fields.get("I", "p"),
        aspect=fields.get("A", "1:1"),
        colorspace=colorspace,
    )


class Y4MReader:
    """Iterate (y, cb, cr) uint8 planes from a y4m byte stream."""

    def __init__(self, fh: BinaryIO):
        self._fh = fh
        self.header = parse_header(self._read_line())

    def _read_line(self) -> bytes:
        line = self._fh.readline(4096)
        if not line.endswith(b"\n"):
            raise Y4MError("truncated Y4M header line")
        return line

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        hdr = self.header
        ch, cw = hdr.chroma_shape
        y_bytes = hdr.height * hdr.width
        c_bytes = ch * cw
        while True:
            marker = self._fh.readline(4096)
            if not marker:
                return  # clean EOF
            if not marker.startswith(b"FRAME"):
                raise Y4MError(f"expected FRAME marker, got {marker[:20]!r}")
            data = self._fh.read(hdr.frame_bytes)
            if len(data) != hdr.frame_bytes:
                raise Y4MError("truncated Y4M frame payload")
            buf = np.frombuffer(data, dtype=np.uint8)
            yield (
                buf[:y_bytes].reshape(hdr.height, hdr.width),
                buf[y_bytes : y_bytes + c_bytes].reshape(ch, cw),
                buf[y_bytes + c_bytes :].reshape(ch, cw),
            )


class Y4MWriter:
    """Write (y, cb, cr) uint8 planes as a y4m byte stream."""

    def __init__(self, fh: BinaryIO, header: Y4MHeader):
        self._fh = fh
        self.header = header
        fh.write(header.encode())

    def write_frame(self, y: np.ndarray, cb: np.ndarray, cr: np.ndarray) -> None:
        hdr = self.header
        if (
            y.shape != (hdr.height, hdr.width)
            or cb.shape != hdr.chroma_shape
            or cr.shape != hdr.chroma_shape
        ):
            raise Y4MError(
                f"frame planes {y.shape}/{cb.shape}/{cr.shape} do not match "
                f"header {hdr.width}x{hdr.height} C{hdr.colorspace}"
            )
        self._fh.write(b"FRAME\n")
        self._fh.write(np.ascontiguousarray(y, dtype=np.uint8).tobytes())
        self._fh.write(np.ascontiguousarray(cb, dtype=np.uint8).tobytes())
        self._fh.write(np.ascontiguousarray(cr, dtype=np.uint8).tobytes())


def sniff_y4m(path: str) -> Optional[Y4MHeader]:
    """Return the parsed header if ``path`` is a Y4M stream, else None."""
    try:
        with open(path, "rb") as fh:
            magic = fh.read(len(Y4M_MAGIC))
            if magic != Y4M_MAGIC:
                return None
            fh.seek(0)
            return parse_header(fh.readline(4096))
    except (OSError, Y4MError):
        return None
