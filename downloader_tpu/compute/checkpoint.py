"""Checkpoint save/restore for the compute stage's training state.

The staging pipeline's own "checkpointing" is job-level (the S3 ``done``
marker + byte/piece/part-level transfer resume — SURVEY.md §5); this
module is the tensor-side counterpart for the converter demo: orbax-backed
save/restore of (params, opt_state, step) that round-trips sharded
arrays.  On restore the arrays are placed back onto the caller's mesh
shardings, so training resumes with the same (data x model) layout it
left off with — single-chip and multi-chip states are interchangeable
because orbax stores the logical array, not the device layout.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple


def _manager(directory: str):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True),
    )


def save_state(directory: str, step: int, params: Any, opt_state: Any) -> None:
    """Write checkpoint ``step`` under ``directory`` (keeps last 3)."""
    import orbax.checkpoint as ocp

    mgr = _manager(os.path.abspath(directory))
    try:
        mgr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
        )
        mgr.wait_until_finished()
    finally:
        mgr.close()


def latest_step(directory: str) -> Optional[int]:
    import orbax.checkpoint as ocp

    mgr = _manager(os.path.abspath(directory))
    try:
        return mgr.latest_step()
    finally:
        mgr.close()


def restore_state(directory: str, params_like: Any, opt_state_like: Any,
                  step: Optional[int] = None,
                  plan=None) -> Tuple[int, Any, Any]:
    """Restore (step, params, opt_state).

    ``params_like``/``opt_state_like`` are abstract or concrete pytrees
    giving shapes/dtypes (e.g. a freshly-initialized state).  When
    ``plan`` (a :class:`~.parallel.mesh.MeshPlan`) is given, restored
    params are placed straight into the plan's shardings — resume on a
    different mesh shape than the save ran on Just Works.
    """
    import orbax.checkpoint as ocp

    mgr = _manager(os.path.abspath(directory))
    try:
        step = step if step is not None else mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        restored = mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(params_like),
                opt_state=ocp.args.StandardRestore(opt_state_like),
            ),
        )
    finally:
        mgr.close()
    params, opt_state = restored["params"], restored["opt_state"]
    if plan is not None:
        from .parallel.mesh import shard_params

        params = shard_params(plan, params)
        # optimizer moments are param-shaped: same tensor-parallel layout
        # (a replicated Adam state would multiply per-device memory by the
        # model-axis factor versus a fresh multichip init)
        opt_state = shard_params(plan, opt_state)
    return step, params, opt_state
