"""Batched frame-upscaling engine: planar YCbCr in, planar YCbCr out.

This is the compute half of the ``upscale`` pipeline stage
(:mod:`downloader_tpu.stages.upscale`).  Design, TPU-first:

- ONE jitted computation per frame geometry covers chroma upsample ->
  YCbCr->RGB -> model forward (bf16 convs on the MXU) -> RGB->YCbCr ->
  chroma downsample -> quantize to uint8.  Host<->device traffic is
  exactly the uint8 planes in and out; every intermediate stays in HBM
  and XLA fuses the elementwise colorspace math into the convs.
- Static shapes only: frames are batched to a fixed ``batch`` size and
  the final short batch is zero-padded (then sliced on the host), so one
  compilation serves the whole stream.
- Multi-device: the batch dim is sharded over a 1-axis ``data`` mesh
  (pure data parallelism — inference has no gradient collectives), the
  params are replicated, and XLA partitions the convs.  The same code
  runs single-chip when only one device exists.
"""

from __future__ import annotations

import functools
from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from .models.upscaler import Upscaler, UpscalerConfig
from .ops.colorspace import (
    downsample_chroma,
    fused_subpixel_ycc,
    fused_subpixel_ycc_s2d,
    rgb_to_ycbcr,
    upsample_chroma,
    ycbcr_to_unit_rgb,
)
from .ops.pixel_shuffle import quantize_u8
from .ops.s2d_head import s2d_head
from .parallel.transfer import HopSink, TransferQueue, timed_hop
from .video import Y4MReader, Y4MWriter


# -- spatial tiling (r5) ------------------------------------------------
#
# Very large frames run the convs at poor MFU because the PIXEL_BUDGET
# cap forces tiny dispatch batches (4K -> 2 frames/dispatch) and XLA's
# conv schedule starves at small batch: measured on the v5e (interleaved
# races, scripts/mfu_r5.py) 4K/b2 runs 0.323 MFU untiled vs 0.427 cut
# into a 4x4 tile grid (dispatch batch 32), while 1080p at its actual
# batch_for of 8 already reaches 0.49 — within ~6% of 720p's 0.515, so
# it must NOT be tiled (tiling it measured 0.445: concat/stitch + halo
# overhead with no batch to recover).  The r4 "0.348 at 1080p" datapoint
# was a batch-4 artifact, not a working-set wall.  Grids are therefore
# chosen by BATCH STARVATION — enough tiles to restore >= TARGET_FRAMES
# per dispatch — preferring short tiles (tall 1096-row tiles measured
# ~10% worse than 556-row ones at equal pixel count).
#
# Tiles fold into the batch dim, the per-tile pipeline runs unchanged,
# and kept regions are stitched — all inside the jitted graph.
#
# Exactness: each tile carries a halo >= the model's receptive radius
# (stem 5x5 + (depth-1)+1 3x3s -> radius depth+2) on every interior
# edge; anchors are clamped so outer tile edges coincide with true frame
# edges, where the convs' SAME zero-padding applies exactly as in the
# untiled graph.  Kept-region outputs are therefore the same numbers,
# not an approximation (pinned by test_tiled_matches_untiled).

TARGET_FRAMES = 8  # the measured-good dispatch batch (720p/1080p sweet spot)
# only frames big enough that PIXEL_BUDGET is what starves the batch are
# tiled; a user-configured small batch on small frames is their choice
TILE_MIN_PX = 1920 * 1080


def _tile_halo(depth: int) -> int:
    """Receptive radius (depth+2) rounded up to even, +2 margin."""
    r = depth + 4
    return r + (r % 2)


def _tile_grid(height: int, width: int, sub_h: int, sub_w: int,
               halo: int, batch: int = TARGET_FRAMES) -> Tuple[int, int]:
    """(rows, cols) split restoring >= TARGET_FRAMES per dispatch when
    ``batch`` frames alone are too few; (1, 1) = no tiling."""
    if batch >= TARGET_FRAMES or height * width <= TILE_MIN_PX:
        return (1, 1)
    want = -(-TARGET_FRAMES // max(1, batch))  # tiles per frame needed
    best = None
    for sh in (1, 2, 4):
        for sw in (1, 2, 4):
            if sh * sw < want:
                continue
            kh, kw = height // sh, width // sw
            # kept tiles must stay even-sized and chroma-aligned, and
            # big enough that halos don't dominate
            if height % (sh * max(2, sub_h)) or width % (sw * max(2, sub_w)):
                continue
            if kh <= 2 * halo or kw <= 2 * halo:
                continue
            tile_h = kh + (2 * halo if sh > 1 else 0)
            tile_w = kw + (2 * halo if sw > 1 else 0)
            # prefer short tiles, then narrow ones: 4K races measured
            # (4,4) 0.455 > (4,2) 0.425 > (4,1) 0.367 > (2,2) 0.40 >
            # untiled 0.323 (mfu_r5.py) — both dims want cutting
            key = (tile_h, tile_w)
            if best is None or key < best[0]:
                best = (key, (sh, sw))
    return best[1] if best else (1, 1)


def _tile_anchors(dim: int, splits: int, halo: int) -> "list[tuple[int, int]]":
    """Per-tile (anchor, crop_offset): input slice [anchor, anchor+T)
    with T = dim/splits + 2*halo, kept output [i*K, (i+1)*K) at
    crop_offset inside the tile.  Clamping puts outer tile edges on the
    frame edges (exact SAME-padding semantics there)."""
    if splits == 1:
        return [(0, 0)]
    kept = dim // splits
    tile = kept + 2 * halo
    out = []
    for i in range(splits):
        anchor = min(max(i * kept - halo, 0), dim - tile)
        out.append((anchor, i * kept - anchor))
    return out


class FrameUpscaler:
    """Holds params + compiled geometry-keyed upscale functions."""

    # Per-device pixel budget per dispatch: the conv activations are
    # H*W*features*2 bytes each with several alive, so frames-per-batch
    # must shrink as resolution grows.  Measured on a 16 GB v5e: 8x1080p
    # (16.6 M px) compiles and runs, 4x4K (33 M px) fails at compile.
    # 8 x 1080p exactly — the largest measured-good per-device load.
    PIXEL_BUDGET = 8 * 1920 * 1080

    def __init__(
        self,
        config: UpscalerConfig = UpscalerConfig(),
        batch: int = 8,
        checkpoint_dir: Optional[str] = None,
        use_mesh: bool = True,
        seed: int = 0,
        donate: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self.config = config
        self.model = Upscaler(config)
        # Donating the input planes is OFF by default, on measurement:
        # the u8 planes can never alias an output (outputs are scale^2
        # larger), so donation buys no HBM here — real donation lives on
        # the state-shaped train step (train.compile_train_step), where
        # params/opt_state alias in place.  Worse, the donation
        # bookkeeping forces a synchronous dispatch on the host-CPU
        # backend (measured: ~0.07 s blocking dispatch vs ~0.0003 s
        # async, overlap 1.2 -> 0), which would undo the transfer
        # queue.  The knob stays for backends/configs where the
        # trade-off differs (e.g. HBM-pressured scale-1 passthrough).
        self.donate = donate
        # per-job hop billing target; a worker thread binds the current
        # job's HopLedger around transcode (stages/upscale.py) and the
        # engine bills h2d/compute/d2h without signature changes through
        # the decoder stack.  Unbound (benches, direct calls) it drops.
        self.hop_sink = HopSink()
        # (sub_h, sub_w) -> chooser Decision, for observability/tests
        self.compile_decisions: dict = {}
        if donate:
            import warnings

            # donated-but-unaliasable buffers make XLA warn per call;
            # the donation is still valid — drop the per-dispatch noise
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")

        rng = jax.random.PRNGKey(seed)
        # fully-convolutional: params are geometry-independent
        self.params = self.model.init(rng, jnp.zeros((1, 32, 32, 3), jnp.float32))
        if checkpoint_dir is not None:
            from .checkpoint import restore_state

            # the upscale stage only needs params; a zero-size opt-state
            # placeholder keeps restore_state's contract
            import optax

            opt_like = optax.adam(1e-3).init(self.params)
            _step, self.params, _opt = restore_state(
                checkpoint_dir, self.params, opt_like
            )

        devices = jax.devices()
        self.n_devices = len(devices) if use_mesh else 1
        # static batch: round the requested size up to a multiple of the
        # data-axis size so every device gets equal shards
        self.batch = max(1, -(-batch // self.n_devices) * self.n_devices)
        if self.n_devices > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            from .parallel import make_global

            self._make_global = make_global
            self._mesh = Mesh(np.array(devices), axis_names=("data",))
            self._plane_sharding = NamedSharding(self._mesh, P("data", None, None))
            self._replicated = NamedSharding(self._mesh, P())
            # make_global (not bare device_put): on a mesh spanning
            # several processes — a TPU pod, or the two-process CPU
            # harness in tests/test_multihost.py — each process can only
            # place its addressable shards; every host holds an
            # identical param copy (same PRNG seed), the standard
            # multi-controller recipe.  Single-process this reduces to
            # the plain device_put.
            self.params = jax.tree_util.tree_map(
                lambda leaf: make_global(leaf, self._replicated), self.params
            )
        else:
            self._mesh = None
            self._plane_sharding = None

    # ------------------------------------------------------------------
    @functools.lru_cache(maxsize=8)
    def _compiled(self, sub_h: int, sub_w: int):
        """Jitted (params, y, cb, cr) -> (y', cb', cr') for one chroma
        sampling; geometry specializes at trace time via the arg shapes."""
        jax, jnp = self._jax, self._jnp
        model = self.model

        scale = self.config.scale

        compute_dtype = self.config.compute_dtype

        def core(params, y, cb, cr):
            yf = y.astype(jnp.float32)
            cbf = upsample_chroma(cb.astype(jnp.float32), sub_h, sub_w)
            crf = upsample_chroma(cr.astype(jnp.float32), sub_h, sub_w)
            # normalization folded into the transform coefficients (a
            # small structural win; lane-dim-3/12 elementwise passes are
            # fusion-dependent on TPU — BASELINE.md r3)
            rgb = ycbcr_to_unit_rgb(yf, cbf, crf)
            height, width = y.shape[1], y.shape[2]
            if sub_h == scale and sub_w == scale:
                # the common 4:2:0 + matching-scale path
                if height % 2 == 0 and width % 2 == 0:
                    # s2d head (r4): the plain head's C_out=scale^2*3
                    # starves the MXU's 128 output lanes (~27 ms of a
                    # ~100 ms 720p step); the stride-2 packed head
                    # computes the same numbers at 4x the lane width —
                    # -34% on the whole step (scripts/mfu_r4.py group 3)
                    feats = model.apply(params, rgb, method=Upscaler.trunk)
                    head = params["params"]["subpixel"]
                    packed = s2d_head(feats, head["kernel"], head["bias"],
                                      compute_dtype)
                    return fused_subpixel_ycc_s2d(packed, scale)
                # odd frame dims: fused sub-pixel tail on the plain head
                h12 = model.apply(params, rgb, method=Upscaler.backbone)
                return fused_subpixel_ycc(h12, scale)
            out = model.apply(params, rgb)
            y2, cb2, cr2 = rgb_to_ycbcr(out.astype(jnp.float32) * 255.0)
            cb2 = downsample_chroma(cb2, sub_h, sub_w)
            cr2 = downsample_chroma(cr2, sub_h, sub_w)
            return quantize_u8(y2), quantize_u8(cb2), quantize_u8(cr2)

        halo = _tile_halo(self.config.depth)

        n_devices = self.n_devices

        def fn(params, y, cb, cr):
            height, width = int(y.shape[1]), int(y.shape[2])
            # starvation is PER DEVICE: a 4-device mesh dispatching 8
            # frames of 4K still runs 2 frames per chip (review r5)
            per_device = max(1, int(y.shape[0]) // n_devices)
            rows, cols = _tile_grid(height, width, sub_h, sub_w, halo,
                                    batch=per_device)
            if rows * cols == 1:
                return core(params, y, cb, cr)
            # spatial tiling (module comment above): fold tiles into the
            # batch dim so every dispatch keeps the 720p-shaped conv
            # schedule, then crop halos and stitch
            batch = y.shape[0]
            h_anchors = _tile_anchors(height, rows, halo)
            w_anchors = _tile_anchors(width, cols, halo)
            kept_h, kept_w = height // rows, width // cols
            tile_h = kept_h + (2 * halo if rows > 1 else 0)
            tile_w = kept_w + (2 * halo if cols > 1 else 0)
            tiles = []
            for ah, _oh in h_anchors:
                for aw, _ow in w_anchors:
                    tiles.append((
                        y[:, ah:ah + tile_h, aw:aw + tile_w],
                        cb[:, ah // sub_h:(ah + tile_h) // sub_h,
                           aw // sub_w:(aw + tile_w) // sub_w],
                        cr[:, ah // sub_h:(ah + tile_h) // sub_h,
                           aw // sub_w:(aw + tile_w) // sub_w],
                    ))
            ty = jnp.concatenate([t[0] for t in tiles], axis=0)
            tcb = jnp.concatenate([t[1] for t in tiles], axis=0)
            tcr = jnp.concatenate([t[2] for t in tiles], axis=0)
            oy, ocb, ocr = core(params, ty, tcb, tcr)
            out_rows_y, out_rows_cb, out_rows_cr = [], [], []
            idx = 0
            for _ah, oh in h_anchors:
                row_y, row_cb, row_cr = [], [], []
                for _aw, ow in w_anchors:
                    t_y = oy[idx * batch:(idx + 1) * batch]
                    t_cb = ocb[idx * batch:(idx + 1) * batch]
                    t_cr = ocr[idx * batch:(idx + 1) * batch]
                    oy0, ox0 = oh * scale, ow * scale
                    row_y.append(t_y[:, oy0:oy0 + kept_h * scale,
                                     ox0:ox0 + kept_w * scale])
                    cy0 = oh * scale // sub_h
                    cx0 = ow * scale // sub_w
                    ch = kept_h * scale // sub_h
                    cw = kept_w * scale // sub_w
                    row_cb.append(t_cb[:, cy0:cy0 + ch, cx0:cx0 + cw])
                    row_cr.append(t_cr[:, cy0:cy0 + ch, cx0:cx0 + cw])
                    idx += 1
                out_rows_y.append(jnp.concatenate(row_y, axis=2)
                                  if cols > 1 else row_y[0])
                out_rows_cb.append(jnp.concatenate(row_cb, axis=2)
                                   if cols > 1 else row_cb[0])
                out_rows_cr.append(jnp.concatenate(row_cr, axis=2)
                                   if cols > 1 else row_cr[0])
            if rows > 1:
                return (jnp.concatenate(out_rows_y, axis=1),
                        jnp.concatenate(out_rows_cb, axis=1),
                        jnp.concatenate(out_rows_cr, axis=1))
            return out_rows_y[0], out_rows_cb[0], out_rows_cr[0]

        # pjit-vs-shard_map chooser (parallel/chooser.py): the engine
        # places planes under explicit NamedShardings in _place, so the
        # cached decision lands on pjit when meshed, plain jit when not;
        # donated plane args free their HBM for the (bigger) outputs.
        from .parallel.chooser import compile_step

        donate_argnums = (1, 2, 3) if self.donate else ()
        if self._mesh is not None:
            in_shardings = (self._replicated, self._plane_sharding,
                            self._plane_sharding, self._plane_sharding)
            compiled, decision = compile_step(
                fn, self._mesh, batch_shape=(self.batch,),
                in_shardings=in_shardings, donate_argnums=donate_argnums)
        else:
            compiled, decision = compile_step(
                fn, None, batch_shape=(self.batch,),
                donate_argnums=donate_argnums)
        self.compile_decisions[(sub_h, sub_w)] = decision
        return compiled

    def batch_for(self, height: int, width: int) -> int:
        """Resolution-aware dispatch size: the configured batch, capped
        so per-device pixels stay inside :data:`PIXEL_BUDGET` (a 4K
        stream at the default batch 8 would otherwise fail XLA
        compilation on a 16 GB chip), kept a multiple of the data-axis
        size so every device gets equal shards."""
        per_device = max(1, self.PIXEL_BUDGET // (height * width))
        # both operands are positive multiples of n_devices (__init__
        # rounds self.batch up), so the min is too
        return min(self.batch, per_device * self.n_devices)

    def _place(self, arr: np.ndarray):
        if self._plane_sharding is not None:
            # h2d is billed as the wall time of the placement call: an
            # async backend keeps this near-zero until the staging queue
            # backs up, so a regression that turns h2d synchronous
            # balloons exactly this hop (and trips its budget)
            with timed_hop(self.hop_sink, "h2d", int(arr.nbytes)):
                return self._make_global(arr, self._plane_sharding)
        return arr

    # ------------------------------------------------------------------
    def _dispatch(self, y: np.ndarray, cb: np.ndarray, cr: np.ndarray,
                  sub_h: int, sub_w: int):
        """Pad to the static batch and dispatch WITHOUT blocking.

        Returns ``(device_arrays, n)``: JAX dispatch is asynchronous, so
        the caller can keep reading/decoding input (or queue further
        batches) while the device — and, over a tunneled chip, the RPC
        round-trip — works.  :meth:`_fetch` materializes the result.
        """
        n = y.shape[0]
        pad = self.batch_for(y.shape[1], y.shape[2]) - n
        if pad:
            y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], np.uint8)])
            cb = np.concatenate([cb, np.zeros((pad,) + cb.shape[1:], np.uint8)])
            cr = np.concatenate([cr, np.zeros((pad,) + cr.shape[1:], np.uint8)])
        fn = self._compiled(sub_h, sub_w)
        out = fn(self.params, self._place(y), self._place(cb), self._place(cr))
        # start the d2h copy NOW, behind the still-running computation:
        # fetching is otherwise pull-based — the dominant device->host
        # transfer would only begin inside _fetch's blocking np.asarray,
        # serializing it with the host's read/write work no matter how
        # many batches are in flight.  Measured on the tunneled v5e this
        # is the difference between ~0 and ~full overlap (5.2x on the
        # paced-stream drill); on a TPU VM's PCIe DMA the same applies
        # at smaller scale.  Multi-process callers fetch per-shard
        # (addressable_shards), so only fully-addressable outputs apply.
        for arr in out:
            if getattr(arr, "is_fully_addressable", False) and hasattr(
                arr, "copy_to_host_async"
            ):
                arr.copy_to_host_async()
        return out, n

    def _fetch(self, dispatched) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Materialize one dispatched batch, billing the remaining two
        hops at the points the host actually blocks: ``compute`` is the
        ready-wait, ``d2h`` the host gather (mostly prefetched by the
        async copy started in :meth:`_dispatch`)."""
        out, n = dispatched
        nbytes = sum(int(getattr(a, "nbytes", 0)) for a in out)
        with timed_hop(self.hop_sink, "compute", nbytes):
            for arr in out:
                if hasattr(arr, "block_until_ready"):
                    arr.block_until_ready()
        with timed_hop(self.hop_sink, "d2h", nbytes):
            y2, cb2, cr2 = (np.asarray(a) for a in out)
        return y2[:n], cb2[:n], cr2[:n]

    def upscale_batch(
        self,
        y: np.ndarray,
        cb: np.ndarray,
        cr: np.ndarray,
        sub_h: int,
        sub_w: int,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Upscale (n, H, W)/(n, ch, cw) uint8 planes, any n.

        Pads n up to the dispatch batch (resolution-capped — see
        :meth:`batch_for`), runs the compiled fn, slices back; n beyond
        the cap is pipelined through capped chunks (dispatch runs ahead
        of fetch, like :meth:`upscale_to`, so chunked 4K batches keep
        the async d2h overlap instead of paying serial round trips).
        """
        eff = self.batch_for(y.shape[1], y.shape[2])
        if y.shape[0] <= eff:
            return self._fetch(self._dispatch(y, cb, cr, sub_h, sub_w))
        queue = TransferQueue(self._dispatch, self._fetch, depth=3)
        parts = []
        for i in range(0, y.shape[0], eff):
            parts.extend(queue.submit(
                y[i:i + eff], cb[i:i + eff], cr[i:i + eff], sub_h, sub_w))
        parts.extend(queue.drain())
        return tuple(
            np.concatenate([part[plane] for part in parts])
            for plane in range(3)
        )

    def upscale_y4m(self, src_path: str, dst_path: str) -> int:
        """Upscale a Y4M file; returns the number of frames written."""
        with open(src_path, "rb") as src:
            return self.upscale_stream(src, dst_path)

    def upscale_stream(self, src_fh, dst_path: str, depth: int = 3) -> int:
        """Upscale a Y4M byte stream (file or pipe — e.g. a decode
        front-end's ``ffmpeg -f yuv4mpegpipe -`` stdout) to ``dst_path``;
        returns the number of frames written."""
        with open(dst_path, "wb") as dst:
            return self.upscale_to(src_fh, dst, depth=depth)

    def upscale_to(self, src_fh, dst_fh, depth: int = 3) -> int:
        """Upscale a Y4M byte stream into an open writable — a file, or a
        pipe such as an encode back-end's ``ffmpeg -f yuv4mpegpipe -i -``
        stdin; returns the number of frames written.

        Keeps up to ``depth`` batches in flight through a double-buffered
        :class:`TransferQueue`: batch i+1 is read, staged (h2d) and
        dispatched while batch i is still executing and batch i-1's d2h
        drains, so host IO (and the per-dispatch RPC latency of a
        tunneled device) overlaps device compute instead of serializing
        with it.
        """
        reader = Y4MReader(src_fh)
        hdr = reader.header
        writer = Y4MWriter(dst_fh, hdr.scaled(self.config.scale))
        sub_h, sub_w = hdr.subsampling
        frames = 0

        def write_out(result) -> None:
            nonlocal frames
            y2, cb2, cr2 = result
            for i in range(y2.shape[0]):
                writer.write_frame(y2[i], cb2[i], cr2[i])
            frames += y2.shape[0]

        queue = TransferQueue(self._dispatch, self._fetch,
                              depth=max(1, depth))
        # resolution-capped batch: a 4K stream must not blow HBM just
        # because the configured batch suits 720p (see batch_for)
        batch = self.batch_for(hdr.height, hdr.width)
        for y, cb, cr in _batched(iter(reader), batch):
            for result in queue.submit(y, cb, cr, sub_h, sub_w):
                write_out(result)
        for result in queue.drain():
            write_out(result)
        return frames


def _batched(
    frames: Iterable[Tuple[np.ndarray, np.ndarray, np.ndarray]], batch: int
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    ys, cbs, crs = [], [], []
    for y, cb, cr in frames:
        ys.append(y)
        cbs.append(cb)
        crs.append(cr)
        if len(ys) == batch:
            yield np.stack(ys), np.stack(cbs), np.stack(crs)
            ys, cbs, crs = [], [], []
    if ys:
        yield np.stack(ys), np.stack(cbs), np.stack(crs)


# ----------------------------------------------------------------------
# FLOPs accounting (for MFU reporting in bench.py)

def upscaler_flops_per_frame(config: UpscalerConfig, height: int, width: int) -> int:
    """Matmul-equivalent FLOPs of one forward pass on one (H, W) frame.

    Counts conv MACs x2 (the MXU work); elementwise adds/relus and the
    colorspace math are bandwidth, not FLOPs, and are excluded per the
    usual MFU convention.
    """
    f = config.features
    pixels = height * width
    stem = 2 * pixels * 5 * 5 * config.channels * f
    body = (config.depth - 1) * 2 * pixels * 3 * 3 * f * f
    head = 2 * pixels * 3 * 3 * f * (config.channels * config.scale**2)
    return stem + body + head


# bf16 peak TFLOP/s per JAX device, by device_kind substring (dense, no
# sparsity).  Public numbers from cloud.google.com/tpu/docs: v2/v3 are
# per-core (JAX exposes cores as devices there), v4+ per chip.
_TPU_PEAKS = [
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v4", 275.0),
    ("v3", 61.5),
    ("v2", 22.5),
]


def device_peak_tflops(device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for tag, peak in _TPU_PEAKS:
        if tag in kind:
            return peak
    return None
